"""AOT executable export/restore: the durability side of cold-start
elimination.

:class:`AotStore` is the ``aot/`` sidecar beside a checkpoint
directory: per program, a serialized lowered+compiled executable
(``<program>.bin`` — ``jax.experimental.serialize_executable`` payload
plus its arg/result treedefs) and a digest-bearing manifest
(``<program>.json``, :mod:`.manifest`). The store follows the same
sidecar discipline as ``data_state/``: atomic writes, content digests,
scrubbed by ``CheckpointManager.scrub`` and
``tools/scrub_checkpoints.py``.

The load contract is **honored-or-refused**: :meth:`AotStore.
load_program` verifies the manifest against the live world (versions,
backend, topology, avals, donation, policy, byte digest) BEFORE
deserializing; any mismatch raises a typed
:class:`~singa_tpu.aot.manifest.AotMismatch` and
:meth:`AotStore.try_load_program` turns that into a LOUD
warn-quarantine-return-None — the caller compiles fresh. A stale
artifact never executes and never blocks a restart.

**Trust boundary**: artifacts are pickled serialized executables —
loading one executes whatever the bytes deserialize to. The crc32
digest detects *rot* (a flipped bit, a truncated write), NOT an
adversary: anyone who can write the ``aot/`` directory can rewrite
the manifest digest to match malicious bytes. Load only from
directories with the same write-trust as the checkpoints themselves
(which have the identical property — restored tensors drive training
— so an ``aot/`` sidecar beside them adds no new exposure; shipping
``prebuild`` artifacts from a build box extends that trust to the
build box).

Program-level helpers:

- :func:`export_train_step` / :func:`load_train_step` — the compiled
  train step of a single-device :class:`~singa_tpu.model.Model`
  (mesh-sharded steps are refused at export; they ride the persistent
  compile cache instead). ``load_train_step`` rebuilds the step record
  ``Model._run_step`` dispatches through — the restarted worker's
  first step replays the deserialized executable with ``n_traces``
  reading 1 (the one trace happened in the exporting process) and a
  ``compile_seconds{source="aot"}`` observation instead of a fresh
  compile.
- :func:`export_serving` — a :class:`~singa_tpu.serving.ServingEngine`
  's prefill and decode programs (the engine loads them itself at
  construction via ``aot_store=``). The export lowers FRESH jits of
  the adapter's raw program bodies, so the engine's CI-pinned trace
  counters are untouched.
"""

from __future__ import annotations

import json
import os
import pickle
import time
import warnings

import numpy as np

from ..observability import metrics as _metrics
from ..observability import spans as _spans
from . import manifest as _manifest
from .manifest import AotMismatch

# programs the store knows how to rebuild call records for
TRAIN_STEP = "train_step"
SERVE_PREFILL = "serve_prefill"
SERVE_DECODE = "serve_decode"
SERVE_BATCH = "serve_batch"


class AotExportError(RuntimeError):
    """A program cannot be exported from this object (mesh-sharded
    step, no compiled step yet, non-serializable static args...).
    Typed so callers can degrade to cache-only warm starts loudly."""


def _sds(a):
    import jax
    return jax.ShapeDtypeStruct(
        tuple(int(d) for d in np.shape(a)),
        a.dtype if hasattr(a, "dtype") else np.asarray(a).dtype)


def _tree_sds(tree):
    import jax
    return jax.tree_util.tree_map(_sds, tree)


# -- out-tree / static-layout round-trip -------------------------------------
# Model's _flatten trees are nested tuples (("T", i) | ("L"/"U", kids)
# | ("D", {k: kid})); JSON turns tuples into lists, so the decode side
# restores the exact tuple shape _unflatten expects.

def encode_tree(tree):
    kind = tree[0]
    if kind == "T":
        return ["T", int(tree[1])]
    if kind in ("L", "U"):
        return [kind, [encode_tree(k) for k in tree[1]]]
    return ["D", {k: encode_tree(v) for k, v in tree[1].items()}]


def decode_tree(doc):
    kind = doc[0]
    if kind == "T":
        return ("T", int(doc[1]))
    if kind in ("L", "U"):
        return (kind, [decode_tree(k) for k in doc[1]])
    return ("D", {k: decode_tree(v) for k, v in doc[1].items()})


def encode_layout(layout):
    """Canonical JSON string of a step's static-arg layout (Model
    ``_split_step_args``): tensor slots as ``["T"]``, static values as
    ``["V", value]``. Raises :class:`AotExportError` when a static arg
    is not JSON-representable — such a step cannot be matched to an
    artifact and must not be exported."""
    from ..model import _TensorSlot
    enc = []
    for el in layout:
        if isinstance(el, _TensorSlot):
            enc.append(["T"])
        else:
            enc.append(["V", el])
    try:
        return json.dumps(enc, sort_keys=True)
    except TypeError as e:
        raise AotExportError(
            f"static step argument is not JSON-representable ({e}); "
            "this signature cannot be exported") from None


class AotStore:
    """One ``aot/`` sidecar directory of digest-verified executables
    (module docstring). ``outcomes`` records what happened to each
    program this process touched (``exported`` / ``loaded`` /
    ``refused:<reason>``) — surfaced in trainer summaries and engine
    health."""

    QUARANTINE_DIR = "quarantine"

    def __init__(self, directory, registry=None):
        self.directory = os.path.abspath(str(directory))
        self._reg = registry if registry is not None \
            else _metrics.default_registry()
        self.outcomes = {}

    # -- paths -------------------------------------------------------------
    def _bin_path(self, program):
        return os.path.join(self.directory, f"{program}.bin")

    def _manifest_path(self, program):
        return os.path.join(self.directory, f"{program}.json")

    def programs(self):
        """Program names with a manifest on disk."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return sorted(n[:-5] for n in names if n.endswith(".json"))

    def inspect(self):
        """{program: manifest} for every artifact (unreadable
        manifests report as ``{"error": ...}`` instead of raising —
        this is the CLI's read path)."""
        out = {}
        for p in self.programs():
            try:
                out[p] = _manifest.read(self._manifest_path(p))
            except AotMismatch as e:
                out[p] = {"error": str(e)}
        return out

    def read_manifest(self, program):
        return _manifest.read(self._manifest_path(program))

    # -- save --------------------------------------------------------------
    def save_program(self, program, compiled, *, avals,
                     donate_argnums=(), policy=None, jax_device=None,
                     extra=None):
        """Serialize one compiled executable + its manifest, atomically
        (payload first, manifest last: a crash between the two leaves a
        manifest-less blob that reads as ``missing``, never a manifest
        vouching for absent bytes). Returns the manifest."""
        from jax.experimental import serialize_executable
        t0 = time.perf_counter()
        payload, in_tree, out_tree = serialize_executable.serialize(
            compiled)
        blob = pickle.dumps(
            {"payload": payload, "in_tree": in_tree,
             "out_tree": out_tree}, protocol=pickle.HIGHEST_PROTOCOL)
        doc = _manifest.build(program, blob, avals=avals,
                              donate_argnums=donate_argnums,
                              policy=policy, jax_device=jax_device,
                              extra=extra)
        os.makedirs(self.directory, exist_ok=True)
        bin_path = self._bin_path(program)
        tmp = f"{bin_path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, bin_path)
        _manifest.write(self._manifest_path(program), doc)
        secs = time.perf_counter() - t0
        self._reg.counter(
            "aot_exports_total", "AOT artifacts serialized to disk",
            labels=("program",)).inc(program=program)
        self._reg.histogram(
            "aot_export_seconds",
            "serialize + write wall-clock of one AOT artifact"
        ).observe(secs)
        _spans.event("aot.export", program=program,
                     bytes=len(blob), seconds=round(secs, 4))
        self.outcomes[program] = "exported"
        return doc

    # -- load --------------------------------------------------------------
    def load_program(self, program, *, avals, donate_argnums=(),
                     policy=None, jax_device=None, expect_extra=None):
        """Verify-then-deserialize one program. Returns
        ``(callable, manifest)``; raises :class:`AotMismatch` on ANY
        mismatch (manifest axes, byte digest, or a payload jax itself
        refuses to deserialize — reason ``format``)."""
        doc = self.read_manifest(program)
        bin_path = self._bin_path(program)
        try:
            with open(bin_path, "rb") as f:
                blob = f.read()
        except OSError:
            raise AotMismatch(
                "missing", f"manifest present but no payload at "
                f"{bin_path}") from None
        _manifest.verify(doc, payload=blob, avals=avals,
                         donate_argnums=donate_argnums, policy=policy,
                         jax_device=jax_device,
                         expect_extra=expect_extra)
        from jax.experimental import serialize_executable
        try:
            parts = pickle.loads(blob)
            fn = serialize_executable.deserialize_and_load(
                parts["payload"], parts["in_tree"], parts["out_tree"])
        except (KeyboardInterrupt, SystemExit):
            raise
        except Exception as e:      # noqa: BLE001 — refused, typed
            raise AotMismatch(
                "format", f"payload failed to deserialize on this "
                f"runtime ({type(e).__name__}: {e})") from None
        return fn, doc

    def try_load_program(self, program, **kw):
        """:meth:`load_program` under the honored-or-refused contract:
        on ANY mismatch, warn LOUDLY naming the axis, quarantine the
        stale artifact (except a merely-missing one), count the
        outcome, and return ``(None, None)`` so the caller compiles
        fresh. Returns ``(callable, manifest)`` on success."""
        t0 = time.perf_counter()
        try:
            fn, doc = self.load_program(program, **kw)
        except AotMismatch as e:
            self._reg.counter(
                "aot_loads_total", "AOT artifact load attempts",
                labels=("program", "outcome")).inc(
                    program=program, outcome=f"refused:{e.reason}")
            self.outcomes[program] = f"refused:{e.reason}"
            if e.reason != "missing":
                warnings.warn(
                    f"AOT artifact {program!r} in {self.directory} "
                    f"REFUSED — {e}; falling back to a fresh compile "
                    "and quarantining the artifact", stacklevel=3)
                _spans.event("aot.refused", program=program,
                             reason=e.reason, detail=str(e)[:300])
                self.quarantine(program, e.reason)
            return None, None
        secs = time.perf_counter() - t0
        self._reg.counter(
            "aot_loads_total", "AOT artifact load attempts",
            labels=("program", "outcome")).inc(program=program,
                                               outcome="loaded")
        self._reg.histogram(
            "aot_load_seconds",
            "verify + deserialize wall-clock of one AOT artifact"
        ).observe(secs)
        _spans.event("aot.load", program=program,
                     seconds=round(secs, 4))
        self.outcomes[program] = "loaded"
        return fn, doc

    # -- quarantine / scrub -------------------------------------------------
    def quarantine(self, program, reason):
        """Move a refused artifact (payload + manifest) into
        ``quarantine/`` with the refusal reason in the name — evidence
        for the post-mortem, out of the load path so the next restart
        does not re-refuse it. Never raises."""
        qdir = os.path.join(self.directory, self.QUARANTINE_DIR)
        stamp = f"{program}.{reason}.{os.getpid()}-{int(time.time())}"
        moved = 0
        for src, ext in ((self._bin_path(program), "bin"),
                         (self._manifest_path(program), "json")):
            if not os.path.exists(src):
                continue
            try:
                os.makedirs(qdir, exist_ok=True)
                os.replace(src, os.path.join(qdir, f"{stamp}.{ext}"))
                moved += 1
            except OSError:
                try:        # quarantine must WIN: a stale artifact
                    os.remove(src)   # left in place would re-refuse
                    moved += 1       # (or worse, re-verify) forever
                except OSError:
                    pass
        if moved:
            self._reg.counter(
                "aot_artifacts_quarantined_total",
                "stale/corrupt AOT artifacts moved out of the load "
                "path", labels=("reason",)).inc(reason=reason)
        return moved

    def scrub(self, delete=False):
        """At-rest verification of every artifact's bytes against its
        manifest digest (the digest axis ONLY — version/backend/aval
        axes are load-time concerns relative to the loading process;
        bytes rotting on disk is the scrub concern, and a CPU-side
        scrubber must not demote a healthy TPU artifact). Returns
        {program: "ok"|"corrupt"|"unreadable"}; ``delete=True``
        quarantines the bad ones."""
        from ..integrity import bytes_digest
        report = {}
        for program in self.programs():
            try:
                doc = self.read_manifest(program)
                with open(self._bin_path(program), "rb") as f:
                    blob = f.read()
            except (AotMismatch, OSError) as e:
                warnings.warn(
                    f"aot scrub: artifact {program!r} is unreadable "
                    f"({e})", stacklevel=2)
                report[program] = "unreadable"
                continue
            if bytes_digest(blob) == doc.get("digest"):
                report[program] = "ok"
            else:
                warnings.warn(
                    f"aot scrub: artifact {program!r} FAILED its "
                    f"content-digest check (recorded "
                    f"{doc.get('digest')})", stacklevel=2)
                report[program] = "corrupt"
        if delete:
            for program, status in report.items():
                if status in ("corrupt", "unreadable"):
                    self.quarantine(program, status)
        return report


# ---------------------------------------------------------------------------
# train step
# ---------------------------------------------------------------------------

def _current_step_rec(model):
    rec = getattr(model, "_last_run_rec", None)
    if rec is None or rec.get("jit") is None or "avals" not in rec:
        rec = next((r for r in model._steps.values()
                    if r.get("jit") is not None and "avals" in r), None)
    return rec


def _state_names(model):
    """Canonical name per threaded-state position (the checkpoint
    name space: ``model/...`` / ``optimizer/...``), or None when any
    entry is unnameable/ambiguous. Recorded in the train-step manifest
    because the threaded-state ORDER is a process accident: a fresh
    trace materialises optimizer aux in backward order while a
    restored process materialises it in checkpoint order — same
    tensors, different positions. The loader uses the names to restore
    the exporting process's order before binding the executable."""
    from ..checkpoint import _state_tensor_dict
    by_id = {id(t): name
             for name, t in _state_tensor_dict(model).items()}
    names = [by_id.get(id(t)) for t in model._state_list]
    if None in names or len(set(names)) != len(names):
        return None
    return names


def export_train_step(model, store, *, skip_if_current=False):
    """Serialize the model's compiled train step into ``store``.

    Refused typed (:class:`AotExportError`) for mesh-sharded models
    (``shard_map`` executables are topology-bound; the persistent
    compile cache is their warm-start path) and before any compiled
    step exists. ``skip_if_current=True`` skips the (re-lower +
    serialize) work when the on-disk artifact already matches the live
    program on every manifest axis except the byte digest — the
    restarted-then-re-exporting steady state."""
    if getattr(model, "_dist", None) is not None:
        raise AotExportError(
            "mesh-sharded train steps are not exportable (topology-"
            "bound shard_map executable); the persistent compile "
            "cache is the warm-start path for distributed models")
    rec = _current_step_rec(model)
    if rec is None:
        raise AotExportError(
            "no compiled train step to export: run one training batch "
            "in graph mode first")
    key = next((k for k, r in model._steps.items() if r is rec), None)
    if key is None or not isinstance(key, tuple):
        raise AotExportError(
            "the compiled step's static-arg layout is not hashable/"
            "encodable; cannot stamp a matching manifest")
    layout_doc = encode_layout(key)
    names = _state_names(model)
    if names is None:
        raise AotExportError(
            "threaded state is not uniquely nameable (anonymous or "
            "aliased state tensors); cannot stamp a manifest a "
            "restarted process could match")
    state_avals, rng_aval, in_avals = rec["avals"]
    avals = (list(state_avals), rng_aval, list(in_avals))
    policy = getattr(model, "_policy", None)
    jax_device = getattr(getattr(model, "dev", None), "jax_device",
                         None)
    extra = {"layout": layout_doc, "state_names": names,
             "out_tree": encode_tree(rec["out_tree"]["tree"])}
    if skip_if_current:
        try:
            _manifest.verify(store.read_manifest(TRAIN_STEP),
                             avals=avals, donate_argnums=(),
                             policy=policy, jax_device=jax_device,
                             expect_extra={"layout": layout_doc,
                                           "state_names": names})
            store.outcomes.setdefault(TRAIN_STEP, "current")
            return None          # artifact already matches this program
        except AotMismatch:
            pass
    # the EXPORTED twin is compiled WITHOUT state donation: a
    # deserialized executable's baked-in input/output aliasing frees
    # donated buffers underneath live python references (observed as
    # heap corruption on jaxlib's experimental serialize path), so the
    # artifact trades the in-place state update for memory safety —
    # the warm-restarted step briefly holds 2x state, which is the
    # price of skipping the whole trace+compile. One extra trace in
    # THIS process (n_traces legitimately +1); the loading process
    # never traces at all.
    import jax
    body = getattr(rec["jit"], "__wrapped__", None)
    if body is None:
        raise AotExportError(
            "the compiled step does not expose its traced body "
            "(non-jit executable?); cannot build the non-donating "
            "export twin")
    compiled = jax.jit(body).lower(state_avals, rng_aval,
                                   *in_avals).compile()
    return store.save_program(
        TRAIN_STEP, compiled, avals=avals, donate_argnums=(),
        policy=policy, jax_device=jax_device, extra=extra)


def load_train_step(model, store, layout, input_arrays):
    """Rebuild a dispatchable step record from the stored artifact, or
    return None (refusal already warned/quarantined/counted by the
    store). Called from ``Model._run_step`` at the point a fresh
    signature would otherwise trace+compile; the model's state is
    already materialised (the abstract first-step rehearsal ran)."""
    if getattr(model, "_dist", None) is not None:
        return None
    try:
        layout_doc = encode_layout(layout)
    except AotExportError:
        return None
    t0 = time.perf_counter()
    model._ensure_state()
    names = _state_names(model)
    if names is None:
        return None
    try:
        pre = store.read_manifest(TRAIN_STEP)
    except AotMismatch as e:
        if e.reason == "missing":
            store.outcomes[TRAIN_STEP] = "refused:missing"
            return None        # nothing to load: quiet, like try_load
        pre = None             # unreadable: try_load refuses loudly
    want = (pre or {}).get("state_names")
    if want and names != want:
        if sorted(names) != sorted(want) or model._steps:
            # different state SET (architecture/optimizer changed —
            # the aval/signature verify below refuses it loudly), or
            # other compiled signatures are already bound to the
            # current order and must not be re-ordered under them
            want = None
        else:
            # same tensors, different positions (fresh-trace backward
            # order vs restored checkpoint order): restore the
            # exporting process's order. A NEW list — never an
            # in-place sort — so nothing that captured the old list
            # object can see a reordering.
            by_name = dict(zip(names, model._state_list))
            model._state_list = [by_name[n] for n in want]
            names = want
    state_arrays = [t.data for t in model._state_list]
    rng = model.dev.current_key()
    avals = ([_sds(a) for a in state_arrays], _sds(rng),
             [_sds(a) for a in input_arrays])
    fn, doc = store.try_load_program(
        TRAIN_STEP, avals=avals, donate_argnums=(),
        policy=getattr(model, "_policy", None),
        jax_device=getattr(model.dev, "jax_device", None),
        expect_extra={"layout": layout_doc, "state_names": names})
    if fn is None:
        return None
    from ..observability import perf as _perf
    sig = _perf.step_signature(input_arrays)
    _perf.record_compile(TRAIN_STEP, time.perf_counter() - t0, sig,
                         source="aot")
    # the record Model._run_step dispatches through: the one trace
    # happened in the exporting process, so n_traces READS 1 here and
    # the steady-state pin (no further traces) still holds
    return {"jit": fn, "builder": None,
            "out_tree": {"tree": decode_tree(doc["out_tree"])},
            "leaf_specs": None, "input_specs": None,
            "n_traces": 1, "aot": True, "arg_sig": sig}


# ---------------------------------------------------------------------------
# serving programs
# ---------------------------------------------------------------------------

def serving_program_avals(engine):
    """The prefill/decode call avals of a ServingEngine, derived from
    its live params/cache and geometry — the ONE definition both
    export and engine-side load share, so they can never drift. Both
    KV layouts are described: the ring's slot-array programs and the
    paged block pool's chunked-prefill/verify programs (tables +
    absolute positions; the verify width K is ``speculative_k`` or
    1)."""
    Pa = _tree_sds(engine._P)
    Ca = _tree_sds(engine._cache)
    import jax
    B, S, W = engine.prefill_batch, engine.prefill_len, engine.slots
    i32 = np.dtype(np.int32)
    if getattr(engine, "kv_layout", "ring") == "paged":
        npages = engine._max_blocks
        K = engine._spec_width
        prefill = (Pa, Ca, jax.ShapeDtypeStruct((B, npages), i32),
                   jax.ShapeDtypeStruct((B, S), i32),
                   jax.ShapeDtypeStruct((B,), i32),
                   jax.ShapeDtypeStruct((B,), i32),
                   jax.ShapeDtypeStruct((B,), np.dtype(bool)))
        decode = (Pa, Ca, jax.ShapeDtypeStruct((W, npages), i32),
                  jax.ShapeDtypeStruct((W, K), i32),
                  jax.ShapeDtypeStruct((W,), i32),
                  jax.ShapeDtypeStruct((W,), i32))
        return prefill, decode
    prefill = (Pa, Ca, jax.ShapeDtypeStruct((B, S), i32),
               jax.ShapeDtypeStruct((B,), i32),
               jax.ShapeDtypeStruct((B,), i32),
               jax.ShapeDtypeStruct((B,), np.dtype(bool)))
    decode = (Pa, Ca, jax.ShapeDtypeStruct((W,), i32),
              jax.ShapeDtypeStruct((W,), i32),
              jax.ShapeDtypeStruct((W,), np.dtype(bool)))
    return prefill, decode


def serving_geometry(engine):
    """The engine-geometry manifest stamp (``expect_extra``): an
    artifact exported at different slots/lengths — or a different KV
    LAYOUT (a ring executable honored by a paged engine would be a
    silently wrong program) — must refuse with reason ``signature``
    even before the aval diff names it. Paged manifests additionally
    carry the pool geometry (``kv_block_size``/``kv_blocks``) and the
    verify width."""
    geo = {"slots": engine.slots,
           "max_len": engine.max_len,
           "prefill_len": engine.prefill_len,
           "prefill_batch": engine.prefill_batch,
           "kv_layout": getattr(engine, "kv_layout", "ring")}
    if geo["kv_layout"] == "paged":
        geo.update(kv_block_size=engine.kv_block_size,
                   kv_blocks=engine.kv_blocks,
                   speculative_k=int(getattr(engine, "speculative_k",
                                             0)))
    return {"engine": geo}


def batch_program_avals(engine):
    """The fixed-width forward's call avals of a BatchServingEngine
    (threaded state + the padded input batch) — shared by export and
    engine-side load. State ORDER is stable here by construction:
    both processes materialise it through the same one eager forward
    at engine build, unlike the trainer's restore path."""
    import jax
    state_avals = [_sds(a) for a in engine._state_arrays]
    x_aval = jax.ShapeDtypeStruct(
        (engine.batch,) + engine.input_shape, engine.input_dtype)
    return (state_avals, x_aval)


def batch_geometry(engine):
    return {"engine": {"batch": engine.batch,
                       "input_shape": list(engine.input_shape),
                       "input_dtype": str(engine.input_dtype)}}


def export_serving(engine, store):
    """Serialize a serving engine's compiled programs: the
    autoregressive ServingEngine's prefill/decode split, or the
    stateless BatchServingEngine's one fixed-width forward.

    Lowers FRESH jits of the raw program bodies (not the engines'
    counting wrappers), so the CI-pinned ``n_traces`` counters are
    untouched by an export. Returns {program: manifest}."""
    import jax
    from ..serving.engine import BatchServingEngine, ServingEngine
    dev = getattr(engine, "_hbm_dev", None)
    if isinstance(engine, BatchServingEngine):
        body = getattr(engine._fwd, "__wrapped__", None)
        if body is None:
            raise AotExportError(
                "the batch forward does not expose its traced body; "
                "cannot export")
        avals = batch_program_avals(engine)
        compiled = jax.jit(body).lower(*avals).compile()
        return {SERVE_BATCH: store.save_program(
            SERVE_BATCH, compiled, avals=avals, donate_argnums=(),
            policy=engine.policy, jax_device=dev,
            extra=batch_geometry(engine))}
    if not isinstance(engine, ServingEngine):
        raise AotExportError(
            f"{type(engine).__name__} is not AOT-exportable")
    if getattr(engine, "sharded", False):
        d = engine._part.describe()
        raise AotExportError(
            f"sharded serving programs are not exportable: the "
            f"NamedSharding executables are bound to this mesh "
            f"(batch={d['batch']} × model={d['model']}); the "
            "persistent compile cache is their warm-start path")
    prefill_avals, decode_avals = serving_program_avals(engine)
    geometry = serving_geometry(engine)
    if engine.kv_layout == "paged":
        raws = ((SERVE_PREFILL, engine.adapter.paged_prefill_fn(),
                 prefill_avals),
                (SERVE_DECODE, engine.adapter.paged_decode_fn(),
                 decode_avals))
    else:
        raws = ((SERVE_PREFILL, engine.adapter.prefill_fn(),
                 prefill_avals),
                (SERVE_DECODE, engine.adapter.decode_fn(),
                 decode_avals))
    out = {}
    for program, raw, avals in raws:
        compiled = jax.jit(raw, donate_argnums=(1,)).lower(
            *avals).compile()
        out[program] = store.save_program(
            program, compiled, avals=avals, donate_argnums=(1,),
            policy=engine.policy, jax_device=dev, extra=geometry)
    return out


__all__ = ["AotStore", "AotExportError", "TRAIN_STEP", "SERVE_PREFILL",
           "SERVE_DECODE", "SERVE_BATCH", "export_train_step",
           "load_train_step", "export_serving",
           "serving_program_avals", "serving_geometry",
           "batch_program_avals", "batch_geometry", "encode_tree",
           "decode_tree", "encode_layout"]
