"""AOT-artifact manifests: the honored-or-refused contract.

A serialized executable is opaque bytes compiled for ONE world: a
specific jax/jaxlib pair, backend and topology, argument avals and
donation layout, and (through the traced body) a specific precision/
quantization policy. Running it anywhere else is not a slow path — it
is a silently wrong program. So every artifact an
:class:`~singa_tpu.aot.export.AotStore` writes carries a manifest
recording all of those axes plus a ``crc32`` content digest
(:func:`singa_tpu.integrity.bytes_digest` — the same tagged-digest
discipline as the checkpoint sidecars), and every load runs
:func:`verify` BEFORE deserialization.

:func:`verify` raises a typed :class:`AotMismatch` whose ``reason``
names the FIRST failed axis (``digest`` / ``version`` / ``backend`` /
``topology`` / ``avals`` / ``donation`` / ``policy`` / ``signature`` /
``format`` / ``missing``) and whose message carries recorded-vs-live —
the loud refusal the fallback-and-recompile path and the quarantine
are driven by. There is no partial acceptance: an artifact is honored
whole or refused whole.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from ..integrity import bytes_digest

MANIFEST_VERSION = 1

# every refusal names one of these axes (tests pin the vocabulary)
REASONS = ("missing", "format", "digest", "version", "backend",
           "topology", "avals", "donation", "policy", "signature")


class AotMismatch(RuntimeError):
    """An AOT artifact was refused: the manifest does not match the
    live world (or the bytes do not match the manifest). ``reason``
    is one of :data:`REASONS`; the message carries recorded vs live.
    The contract: the caller falls back to a LOUD fresh compile and
    quarantines the artifact — a refused program never executes."""

    def __init__(self, reason, detail):
        assert reason in REASONS, reason
        self.reason = reason
        super().__init__(f"AOT artifact refused ({reason}): {detail}")


def environment_stamp(jax_device=None):
    """The world this process compiles for: jax/jaxlib versions plus
    backend platform, device kind, and addressable device count of
    ``jax_device``'s platform (the default backend's when None)."""
    import jax
    import jaxlib
    if jax_device is None:
        devices = jax.devices()
        jax_device = devices[0]
    else:
        devices = jax.devices(jax_device.platform)
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "platform": str(jax_device.platform),
            "device_kind": str(getattr(jax_device, "device_kind", "?")),
            "n_devices": len(devices)}


def aval_signature(avals):
    """JSON-able shape/dtype signature of an argument pytree (concrete
    arrays or ``ShapeDtypeStruct``s): ``[[dims...], dtype]`` per leaf,
    plus the treedef string — what :func:`verify` compares against the
    live call signature. Shardings are deliberately NOT recorded:
    single-device artifacts are the supported scope (mesh-sharded
    programs ride the persistent compile cache instead), and the
    topology axis already pins the device count."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(avals)
    return {"leaves": [[[int(d) for d in np.shape(a)],
                        str(getattr(a, "dtype", type(a).__name__))]
                       for a in leaves],
            "treedef": str(treedef)}


def _policy_stamp(policy):
    if policy is None:
        return None
    desc = getattr(policy, "describe", None)
    return dict(desc()) if callable(desc) else dict(policy)


def build(program, payload, *, avals, donate_argnums=(), policy=None,
          jax_device=None, extra=None):
    """Manifest dict for one artifact: identity (program name, format
    version), environment stamp, call contract (avals + donation +
    policy), and the content digest over exactly the bytes that will
    sit on disk."""
    doc = {
        "format": MANIFEST_VERSION,
        "program": str(program),
        "digest": bytes_digest(payload),
        "env": environment_stamp(jax_device),
        "avals": aval_signature(avals),
        "donation": sorted(int(i) for i in donate_argnums),
        "policy": _policy_stamp(policy),
        "created_at": time.time(),
    }
    if extra:
        doc.update(extra)
    return doc


def verify(manifest, *, payload=None, avals=None, donate_argnums=None,
           policy=None, jax_device=None, expect_extra=None):
    """Check a manifest against the live world; raise
    :class:`AotMismatch` naming the first failed axis. Any axis whose
    live value is not supplied is skipped (callers verify what they
    know). ``expect_extra`` maps manifest keys to required values —
    the program-specific contract (e.g. the train step's static-arg
    layout, a serving engine's geometry); a mismatch there is reason
    ``signature``."""
    if not isinstance(manifest, dict) or "digest" not in manifest:
        raise AotMismatch("format", "manifest is not a digest-bearing "
                          "mapping")
    if manifest.get("format") != MANIFEST_VERSION:
        raise AotMismatch(
            "format", f"manifest format {manifest.get('format')!r}, "
            f"this build reads {MANIFEST_VERSION}")
    env = manifest.get("env") or {}
    live_env = environment_stamp(jax_device)
    for k, reason in (("jax", "version"), ("jaxlib", "version"),
                      ("platform", "backend"),
                      ("device_kind", "backend"),
                      ("n_devices", "topology")):
        if env.get(k) != live_env[k]:
            raise AotMismatch(
                reason, f"{k}: artifact recorded {env.get(k)!r}, "
                f"this process is {live_env[k]!r}")
    if payload is not None:
        got = bytes_digest(payload)
        if got != manifest["digest"]:
            raise AotMismatch(
                "digest", f"artifact bytes digest {got} != recorded "
                f"{manifest['digest']} — corrupt on disk (crc32 "
                "detects rot, not an adversary: see the trust-"
                "boundary note in singa_tpu/aot/export.py)")
    if avals is not None:
        live = aval_signature(avals)
        want = manifest.get("avals") or {}
        if want.get("leaves") != live["leaves"] or \
                want.get("treedef") != live["treedef"]:
            raise AotMismatch(
                "avals", f"call signature changed: artifact recorded "
                f"{want.get('leaves')}, live is {live['leaves']}")
    if donate_argnums is not None:
        want = manifest.get("donation")
        live_d = sorted(int(i) for i in donate_argnums)
        if want != live_d:
            raise AotMismatch(
                "donation", f"donation layout changed: artifact "
                f"recorded {want}, live is {live_d}")
    if policy is not None or manifest.get("policy") is not None:
        live_p = _policy_stamp(policy)
        if manifest.get("policy") != live_p:
            raise AotMismatch(
                "policy", f"precision/quant policy changed: artifact "
                f"recorded {manifest.get('policy')}, live is {live_p}")
    for k, want in (expect_extra or {}).items():
        if manifest.get(k) != want:
            raise AotMismatch(
                "signature", f"{k}: artifact recorded "
                f"{manifest.get(k)!r}, live expects {want!r}")
    return manifest


def write(path, doc):
    """Atomic manifest write (tmp + rename — a torn manifest must read
    as missing, never as a half-truth)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read(path):
    """Manifest dict; raises :class:`AotMismatch` with reason
    ``missing`` (no file) or ``format`` (unparseable)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        raise AotMismatch("missing", f"no manifest at {path}") from None
    except ValueError as e:
        raise AotMismatch("format",
                          f"manifest {path} is unparseable ({e})") \
            from None
    if not isinstance(doc, dict):
        raise AotMismatch("format", f"manifest {path} is not a mapping")
    return doc


__all__ = ["MANIFEST_VERSION", "REASONS", "AotMismatch",
           "environment_stamp", "aval_signature", "build", "verify",
           "write", "read"]
