"""The persistent-compile-cache policy: one object, process-wide.

JAX's persistent compilation cache turns a recompile of an
already-seen program into a disk read, but its raw form is a scatter
of config flags with no observability and no size control. This module
fronts it with ONE policy object:

    from singa_tpu import aot
    aot.install(aot.CachePolicy("/ckpts/aot/xla-cache",
                                size_budget_bytes=2 << 30))

or, through the surfaces that compile:
``Model.compile(inputs, compile_cache=policy_or_dir)`` /
``Model.compile_serving(compile_cache=...)``.

What installing buys beyond the raw flags:

- **hit/miss counters** — a process-wide ``jax.monitoring`` listener
  counts cache hits and misses into
  ``compile_cache_hits_total`` / ``compile_cache_misses_total`` on the
  metrics registry (and a host-side snapshot for cheap deltas), so
  every traced dispatch can label its ``compile_seconds`` observation
  ``source="cache"`` or ``source="fresh"``
  (:func:`classify`) — the cold-start win is a dashboard fact, not an
  inference from wall clocks;
- **size budget with LRU GC** — :func:`gc` prunes the cache directory
  least-recently-used-first down to ``size_budget_bytes`` (JAX writes
  an ``-atime`` companion per entry exactly for this), run at install
  and on demand (``tools/aot_cache.py gc``);
- **enable/disable** — one switch, not four flags.

Everything here is host-side and best-effort: a cache that cannot be
installed degrades to fresh compiles with a warning, never a failed
``compile``.
"""

from __future__ import annotations

import os
import threading
import warnings

from ..observability import metrics as _metrics

# jax.monitoring event names (stable across the jax versions we
# support; unknown names simply never fire)
_EVT_HIT = "/jax/compilation_cache/cache_hits"
_EVT_MISS = "/jax/compilation_cache/cache_misses"

_LOCK = threading.Lock()
_ACTIVE = None                    # the installed CachePolicy (or None)
_LISTENING = False
# monotonically-increasing host counters the listener feeds; snapshot()
# hands out copies so dispatch sites can diff around a call
_COUNTS = {"hits": 0, "misses": 0}


class CachePolicy:
    """Persistent-compile-cache configuration (see module docstring).

    - ``directory``: where XLA executables persist.
    - ``enabled``: False turns the cache OFF at install (the one-switch
      opt-out).
    - ``size_budget_bytes``: LRU GC target; None = unbounded.
    - ``min_compile_seconds`` / ``min_entry_bytes``: JAX's write
      thresholds. The defaults (0 / -1) cache EVERYTHING including
      tiny CPU programs — cold-start elimination wants the whole
      program set warm, not just the expensive tail.
    """

    def __init__(self, directory, *, enabled=True,
                 size_budget_bytes=None, min_compile_seconds=0.0,
                 min_entry_bytes=-1):
        self.directory = os.path.abspath(str(directory))
        self.enabled = bool(enabled)
        self.size_budget_bytes = None if size_budget_bytes is None \
            else int(size_budget_bytes)
        self.min_compile_seconds = float(min_compile_seconds)
        self.min_entry_bytes = int(min_entry_bytes)

    def describe(self):
        return {"directory": self.directory, "enabled": self.enabled,
                "size_budget_bytes": self.size_budget_bytes,
                "min_compile_seconds": self.min_compile_seconds,
                "min_entry_bytes": self.min_entry_bytes}

    def __repr__(self):
        return f"CachePolicy({self.describe()!r})"


def _listener(event, **kw):
    """jax.monitoring event listener — must NEVER raise into jax."""
    try:
        if event == _EVT_HIT:
            _COUNTS["hits"] += 1
            _metrics.default_registry().counter(
                "compile_cache_hits_total",
                "XLA compiles served from the persistent cache").inc()
        elif event == _EVT_MISS:
            _COUNTS["misses"] += 1
            _metrics.default_registry().counter(
                "compile_cache_misses_total",
                "XLA compiles the persistent cache could not serve"
            ).inc()
    except Exception:       # noqa: BLE001 — telemetry must stay silent
        pass


def _ensure_listener():
    global _LISTENING
    with _LOCK:
        if _LISTENING:
            return
        try:
            try:        # public surface first; private path for jax
                from jax import monitoring  # versions that lack it
            except ImportError:
                from jax._src import monitoring
            monitoring.register_event_listener(_listener)
            _LISTENING = True
        except Exception as e:      # noqa: BLE001 — counters degrade
            warnings.warn(
                f"compile-cache hit/miss counters unavailable "
                f"({type(e).__name__}: {e}); compile_seconds will "
                "label every compile source=fresh", stacklevel=3)


def resolve(policy):
    """Coerce a user-facing ``compile_cache=`` value to a
    :class:`CachePolicy`: a policy passes through, a path string/
    PathLike becomes an enabled policy over it, ``False`` a disabled
    one over the default directory."""
    if isinstance(policy, CachePolicy):
        return policy
    if policy is False:
        return CachePolicy(default_dir(), enabled=False)
    if policy is True:
        return CachePolicy(default_dir())
    return CachePolicy(os.fspath(policy))


def default_dir():
    return os.path.join(os.path.expanduser("~"), ".cache", "singa_tpu",
                        "xla-cache")


def cache_dir_for(aot_dir):
    """The ONE definition of where the persistent compile cache lives
    inside an ``aot/`` sidecar directory — the trainer, the serving
    example, and the CLI all route through it so the layout can never
    split the warm cache across divergent conventions."""
    return os.path.join(os.path.abspath(str(aot_dir)), "xla-cache")


def install(policy):
    """Install ``policy`` (a :class:`CachePolicy`, a directory, True
    for the default directory, or False to disable) process-wide:
    configure jax's persistent compilation cache, register the
    hit/miss listener, and GC down to the size budget. Returns the
    active policy. Never raises — a cache that cannot install degrades
    to fresh compiles, loudly."""
    global _ACTIVE
    pol = resolve(policy)
    try:
        import jax
        if pol.enabled:
            os.makedirs(pol.directory, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", pol.directory)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              pol.min_compile_seconds)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              pol.min_entry_bytes)
            # the config flag alone is only consulted when jax first
            # checks its cache machinery — a process that already
            # compiled something has memoized "no cache" for the whole
            # task (is_cache_used's once-per-task check). reset_cache
            # drops that memo so installing mid-process works too.
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
            _ensure_listener()
            if pol.size_budget_bytes is not None:
                gc(pol)
        else:
            jax.config.update("jax_compilation_cache_dir", None)
            from jax.experimental.compilation_cache import (
                compilation_cache as _cc)
            _cc.reset_cache()
    except Exception as e:      # noqa: BLE001 — optimisation, not a gate
        warnings.warn(
            f"persistent compile cache unavailable "
            f"({type(e).__name__}: {e}); compiles run fresh",
            stacklevel=2)
        return _ACTIVE
    _ACTIVE = pol
    return pol


def active():
    """The installed :class:`CachePolicy`, or None."""
    return _ACTIVE


def uninstall():
    """Turn the persistent cache back off (tests, or a one-shot tool
    that must not leave process-global config behind). The hit/miss
    listener stays registered — with no cache configured it simply
    never fires again."""
    global _ACTIVE
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:       # noqa: BLE001 — symmetric with install
        pass
    _ACTIVE = None


def snapshot():
    """Copy of the host-side hit/miss counters — take one BEFORE a
    dispatch that may compile, then :func:`classify` after."""
    return dict(_COUNTS)


def classify(before):
    """Label the compile(s) that happened since ``before`` (a
    :func:`snapshot`): ``"cache"`` when every new compilation was
    served from the persistent cache, ``"fresh"`` otherwise —
    including when no cache is installed (no events fire, so nothing
    can prove a hit)."""
    hits = _COUNTS["hits"] - before.get("hits", 0)
    misses = _COUNTS["misses"] - before.get("misses", 0)
    return "cache" if hits > 0 and misses == 0 else "fresh"


def stats(directory=None):
    """{entries, bytes} of a cache directory (the active policy's when
    None). Missing directory counts as empty."""
    d = directory if directory is not None else \
        (_ACTIVE.directory if _ACTIVE is not None else default_dir())
    entries = 0
    total = 0
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    for n in names:
        path = os.path.join(d, n)
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        total += size
        if n.endswith("-cache"):
            entries += 1
    return {"directory": os.path.abspath(str(d)), "entries": entries,
            "bytes": total}


def gc(policy=None, *, budget_bytes=None):
    """LRU garbage collection: delete least-recently-used cache
    entries until the directory fits the budget (the policy's
    ``size_budget_bytes`` unless overridden). Recency comes from each
    entry's ``-atime`` companion file (written by jax on every cache
    read precisely so external GC can be LRU); an entry without one
    falls back to the cache file's own mtime. Returns a report dict;
    never raises."""
    pol = policy if policy is not None else _ACTIVE
    if pol is None and budget_bytes is None:
        return {"removed": 0, "bytes_freed": 0, "entries": 0,
                "bytes": 0}
    directory = pol.directory if pol is not None else default_dir()
    budget = budget_bytes if budget_bytes is not None \
        else getattr(pol, "size_budget_bytes", None)
    try:
        names = os.listdir(directory)
    except OSError:
        return {"removed": 0, "bytes_freed": 0, "entries": 0,
                "bytes": 0}
    entries = []        # (last_use, total_bytes, [paths])
    total = 0
    for n in names:
        if not n.endswith("-cache"):
            continue
        path = os.path.join(directory, n)
        atime_path = os.path.join(directory, n[:-len("-cache")]
                                  + "-atime")
        try:
            size = os.path.getsize(path)
        except OSError:
            continue
        try:
            last_use = os.path.getmtime(atime_path)
            size += os.path.getsize(atime_path)
        except OSError:
            atime_path = None
            last_use = os.path.getmtime(path)
        total += size
        entries.append((last_use, size, [p for p in (path, atime_path)
                                         if p]))
    removed = 0
    freed = 0
    if budget is not None:
        entries.sort()                      # oldest last-use first
        over = total - int(budget)
        for _t, size, paths in entries:
            if over <= 0:
                break
            for p in paths:
                try:
                    os.remove(p)
                except OSError:
                    pass
            over -= size
            freed += size
            removed += 1
    return {"removed": removed, "bytes_freed": freed,
            "entries": len(entries) - removed, "bytes": total - freed}


__all__ = ["CachePolicy", "install", "active", "resolve", "snapshot",
           "classify", "stats", "gc", "default_dir", "cache_dir_for"]
