"""Cold-start elimination: durable, verified compiled programs.

Every elastic restart (the exit-75 path), rescale, and serving-replica
spin-up used to pay a full XLA recompile — BENCH_r05 burned half a day
of round budget on cold-start probe timeouts alone. This subsystem
makes compiled programs **durable artifacts** with a strict
honored-or-refused contract:

- :mod:`.cache` — JAX's persistent compilation cache behind ONE policy
  object (:class:`~singa_tpu.aot.cache.CachePolicy`: directory, size
  budget with LRU GC, enable/disable), wired through ``Model.compile``
  and ``Model.compile_serving`` (``compile_cache=``). Hits and misses
  are counted (``compile_cache_hits_total`` / ``_misses_total``) and
  every traced dispatch's ``compile_seconds`` observation carries a
  ``source="cache"|"fresh"`` label, so the win is visible in telemetry
  instead of inferred from wall clocks.
- :mod:`.manifest` — the refusal side: every exported artifact carries
  a manifest recording jax/jaxlib versions, backend + topology, the
  arg avals and donation layout, the precision/quant policy stamp, and
  a ``crc32`` content digest. :func:`~singa_tpu.aot.manifest.verify`
  raises a typed :class:`~singa_tpu.aot.manifest.AotMismatch` NAMING
  the first failed axis — a mismatched artifact falls back to a loud
  fresh compile and is quarantined, never silently executed.
- :mod:`.export` — the durability side:
  :class:`~singa_tpu.aot.export.AotStore` serializes lowered+compiled
  executables (``jax.experimental.serialize_executable``) into an
  ``aot/`` sidecar beside the checkpoints (same sidecar discipline as
  ``data_state/``; scrubbed by ``CheckpointManager.scrub`` and
  ``tools/scrub_checkpoints.py``). ``ResilientTrainer(aot=True)``
  exports the train step after the first step and a restarted worker
  deserializes it instead of retracing;
  ``compile_serving(aot_store=...)`` does the same for the serving
  prefill/decode programs — a warm restart re-steps / re-serves in
  seconds with ``n_traces`` still 1 and ZERO
  ``compile_seconds{source="fresh"}`` observations (the chaos
  ``warm-restart`` gate).

``tools/aot_cache.py`` is the operator CLI (prebuild / inspect / gc /
scrub / ``--selftest``).
"""

from .cache import CachePolicy, install, snapshot  # noqa: F401
from .export import AotStore, export_serving, export_train_step  # noqa: F401
from .manifest import AotMismatch  # noqa: F401

__all__ = ["CachePolicy", "install", "snapshot", "AotStore",
           "export_train_step", "export_serving", "AotMismatch"]
