"""Device abstraction for the TPU-native framework.

Capability parity with the reference device layer (reference:
``python/singa/device.py:29-135`` and ``include/singa/core/device.h:57-174``),
re-designed for XLA: a :class:`Device` does not own a memory pool or a stream —
XLA's buffer assignment replaces the reference's Block/DeviceMemPool — but it
keeps the user-visible contract: tensor placement, RNG seeding, graph
(lazy-execution) toggling, synchronisation, and time-profiling verbosity.

The reference's buffered-closure Graph (``src/core/scheduler/scheduler.cc``)
maps onto ``jax.jit`` tracing: ``EnableGraph(True)`` arms tracing mode and
``RunGraph`` replays a compiled XLA executable (see ``singa_tpu/model.py``).
"""

from __future__ import annotations

import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Device",
    "CppCPU",
    "TpuDevice",
    "Platform",
    "create_cpu_device",
    "create_tpu_device",
    "create_tpu_devices",
    "create_cuda_gpu",
    "create_cuda_gpus",
    "create_cuda_gpu_on",
    "create_cuda_gpus_on",
    "get_default_device",
    "get_num_tpus",
    "get_num_gpus",
    "device_query",
    "enable_lazy_alloc",
]


class Device:
    """A compute device holding an RNG state and execution-mode flags.

    Mirrors the contract of the reference ``Device`` base class
    (include/singa/core/device.h:57-174): ``SetRandSeed``, ``Sync``,
    ``EnableGraph``/``RunGraph``, verbosity and skip-iteration profiling
    knobs — with XLA semantics underneath.
    """

    _seed_counter = 0
    _lock = threading.Lock()

    def __init__(self, jax_device=None, device_id: int = 0, lang: str = "kCpp"):
        self.id = device_id
        self.lang = lang
        self.jax_device = jax_device
        # Graph/tracing flags (reference device.cc:55-65 buffered mode).
        self.graph_enabled = False
        self.verbosity = 0
        self.skip_iteration = 5
        # Per-device functional RNG (replaces curand generator state).
        with Device._lock:
            Device._seed_counter += 1
            seed = Device._seed_counter
        self._key = jax.device_put(jax.random.PRNGKey(seed), jax_device)
        # Profiling storage filled by model.py when verbosity > 0.
        self.time_profiling = {}

    # ---- RNG ------------------------------------------------------------
    def SetRandSeed(self, seed: int) -> None:
        self._key = jax.device_put(jax.random.PRNGKey(int(seed)),
                                   self.jax_device)

    def set_rand_seed(self, seed: int) -> None:
        self.SetRandSeed(seed)

    def _heal_key(self):
        """Self-heal if a traced consumer leaked its in-trace key into this
        host-side state (the stored key would be a dead tracer): hops to
        a fresh per-device stream (device identity + leak counter)."""
        if isinstance(self._key, jax.core.Tracer) and \
                not isinstance(jnp.zeros(()), jax.core.Tracer):
            self._leaks = getattr(self, "_leaks", 0) + 1
            self._key = jax.random.fold_in(
                jax.random.PRNGKey(id(self) & 0x7fffffff),
                0x5eed + self._leaks)

    def rand_key(self):
        """Split and return a fresh PRNG key (functional curand
        equivalent)."""
        self._heal_key()
        self._key, sub = jax.random.split(self._key)
        return sub

    def current_key(self):
        """The current key WITHOUT splitting — for consumers that advance
        the stream themselves (the compiled train step splits in-trace and
        hands the next key back, avoiding a host-side split per step)."""
        self._heal_key()
        return self._key

    # rng state threading for jit (model.py swaps these in/out of the trace)
    def _get_rng_state(self):
        return self._key

    def _set_rng_state(self, key):
        self._key = key

    # ---- Execution mode -------------------------------------------------
    def EnableGraph(self, enable: bool) -> None:
        self.graph_enabled = bool(enable)

    def RunGraph(self, sequential: bool = False) -> None:
        # Execution of the compiled step is driven by Model; kept for API
        # parity with reference device.cc:67-82 (a no-op at device level).
        pass

    def ResetGraph(self) -> None:
        pass

    def _record_time(self, name: str, seconds: float) -> None:
        """Accumulate a timing sample (count, total seconds) under a name.
        Sample sources: whole compiled steps at verbosity>=1, per-op
        fwd/bwd at verbosity>=2 (reference per-node cudaEvent timing,
        src/core/device/cuda_gpu.cc:117, scheduler.cc:240-298)."""
        rec = self.time_profiling.setdefault(name, [0, 0.0])
        rec[0] += 1
        rec[1] += seconds

    def PrintTimeProfiling(self) -> None:
        """Print the aggregated timing table (reference
        Graph::PrintTimeProfiling, src/core/scheduler/scheduler.cc:240-298:
        verbosity 1 = whole step, verbosity 2 = per-op rows)."""
        if not self.time_profiling:
            print("No time profiling data collected; "
                  "set verbosity>0 and run model steps.")
            return
        rows = sorted(self.time_profiling.items(),
                      key=lambda kv: -kv[1][1])
        width = max(len(k) for k, _ in rows)
        print(f"  {'op':<{width}}  {'calls':>6}  {'total ms':>10}  "
              f"{'avg ms':>9}")
        for name, (count, total) in rows:
            avg = total / count if count else 0.0
            print(f"  {name:<{width}}  {count:>6}  {total * 1e3:>10.3f}  "
                  f"{avg * 1e3:>9.3f}")

    def ResetTimeProfiling(self) -> None:
        self.time_profiling = {}

    def SetVerbosity(self, verbosity: int) -> None:
        """0 = off; 1 = whole-step wall times (after skip_iteration);
        2 = per-op times + static cost analysis + a one-time MEASURED
        per-fusion profile of the compiled step.

        NOTE: verbosity>=2 forces the FIRST graph-mode train call to run
        eagerly (per-op wall times only exist op-by-op), skipping the
        zero-compute abstract rehearsal. On a network-tunneled
        accelerator that eager pass costs one round trip per op and can
        look like a hang on a big model — profile small, or at
        verbosity 1."""
        self.verbosity = int(verbosity)

    def SetSkipIteration(self, skip: int) -> None:
        self.skip_iteration = int(skip)

    # ---- Sync / placement ----------------------------------------------
    def Sync(self) -> None:
        """Block until all queued work on this device is done."""
        (jnp.zeros((), device=self.jax_device) + 0).block_until_ready()

    def put(self, array):
        """Place a host array on this device; returns a jax.Array."""
        return jax.device_put(jnp.asarray(array), self.jax_device)

    def name(self) -> str:
        return f"{type(self).__name__}({self.id})"

    def __repr__(self) -> str:
        return f"<{self.name()} lang={self.lang} platform=" \
               f"{getattr(self.jax_device, 'platform', '?')}>"


class CppCPU(Device):
    """Host CPU device (reference src/core/device/cpp_cpu.cc)."""

    def __init__(self, device_id: int = 0):
        # local (addressable) devices only: under a multi-process
        # jax.distributed mesh, jax.devices() lists other hosts' devices,
        # which this process cannot allocate on
        cpus = [d for d in jax.local_devices() if d.platform == "cpu"]
        if not cpus:
            try:
                cpus = jax.local_devices(backend="cpu")
            except RuntimeError:
                cpus = jax.devices("cpu")   # single-process: all local
        super().__init__(cpus[0], device_id, lang="kCpp")


class TpuDevice(Device):
    """TPU device — the peer of the reference's CudaGPU
    (src/core/device/cuda_gpu.cc), with XLA replacing cuDNN/cuBLAS/cnmem."""

    def __init__(self, device_id: int = 0, jax_device=None):
        if jax_device is None:
            local = jax.local_devices()
            accel = [d for d in local if d.platform != "cpu"]
            if accel:
                jax_device = accel[device_id % len(accel)]
            else:  # CPU fallback keeps the API usable off-TPU
                jax_device = local[device_id % len(local)]
        super().__init__(jax_device, device_id, lang="kTpu")


class Platform:
    """Device discovery/factory (reference src/core/device/platform.cc)."""

    @staticmethod
    def GetNumGPUs() -> int:
        return len([d for d in jax.devices() if d.platform != "cpu"])

    @staticmethod
    def DeviceQuery(device_id: int = 0, verbose: bool = False) -> str:
        devs = jax.devices()
        if device_id >= len(devs):
            return f"no device {device_id}"
        d = devs[device_id]
        info = (f"Device {device_id}: platform={d.platform} "
                f"kind={getattr(d, 'device_kind', '?')} "
                f"process={d.process_index}")
        if verbose:
            print(info)
        return info

    @staticmethod
    def CreateTpuDevices(num: int):
        return [TpuDevice(i) for i in range(num)]


_default_device = None
_lock = threading.Lock()


def get_default_device() -> Device:
    """Default host device (reference python/singa/device.py:121-128)."""
    global _default_device
    with _lock:
        if _default_device is None:
            _default_device = CppCPU()
    return _default_device


def create_cpu_device() -> Device:
    return CppCPU()


def create_tpu_device(device_id: int = 0) -> TpuDevice:
    return TpuDevice(device_id)


def create_tpu_devices(num: int):
    return [TpuDevice(i) for i in range(num)]


# CUDA-named aliases for drop-in compatibility with reference scripts
# (python/singa/device.py:60-118): they return the accelerator present.
def create_cuda_gpu(set_default=True):  # noqa: ARG001 (parity signature)
    return create_tpu_device(0)


def create_cuda_gpu_on(device_id: int):
    return create_tpu_device(device_id)


def create_cuda_gpus(num: int):
    return create_tpu_devices(num)


def create_cuda_gpus_on(device_ids):
    return [create_tpu_device(i) for i in device_ids]


def get_num_tpus() -> int:
    return len([d for d in jax.devices() if d.platform == "tpu"])


def get_num_gpus() -> int:
    # parity alias: number of accelerators visible
    return Platform.GetNumGPUs()


def device_query(device_id: int = 0, verbose: bool = False) -> str:
    return Platform.DeviceQuery(device_id, verbose)


def enable_lazy_alloc(enable: bool) -> None:
    """Parity no-op: XLA always allocates lazily at compile/execute time
    (reference lazy_alloc_ src/core/device/device.cc:23)."""
    _ = enable
