"""Named log channels (reference include/singa/utils/channel.h:35-77,
src/utils/channel.cc).

A :class:`Channel` appends metric/progress lines to a per-channel file
(named ``<directory>/<name>`` by default) and/or stderr. Channels are
process-wide singletons obtained via :func:`get_channel`; the sink lives in
the native runtime (native/singa_native.cc) so C++ and Python writers share
one file handle, with a pure-python fallback when the native library is
unavailable.

API parity: ``init_channel``/``InitChannel``, ``set_channel_directory``/
``SetChannelDirectory``, ``get_channel``/``GetChannel``; per-channel
``enable_dest_stderr``/``enable_dest_file``/``set_dest_file_path``/``send``.
"""

from __future__ import annotations

import os
import sys
import threading

from . import native

_lock = threading.Lock()
_channels = {}
_directory = ""


class Channel:
    """One named output channel. File dest enabled by default, stderr
    disabled by default (reference channel.h:40-46, channel.cc:46-56)."""

    def __init__(self, name):
        self.name = name
        self._handle = None
        self._file = None
        self._to_stderr = False
        self._to_file = True
        if native.AVAILABLE:
            self._handle = native._lib.sg_channel_get(name.encode())
        else:
            self._open(os.path.join(_directory, name) if _directory
                       else name)

    # -- destinations ----------------------------------------------------
    def enable_dest_stderr(self, enable=True):
        self._to_stderr = bool(enable)
        if self._handle is not None:
            native._lib.sg_channel_enable_stderr(self._handle, int(enable))

    def enable_dest_file(self, enable=True):
        self._to_file = bool(enable)
        if self._handle is not None:
            native._lib.sg_channel_enable_file(self._handle, int(enable))

    def set_dest_file_path(self, path):
        if self._handle is not None:
            native._lib.sg_channel_set_dest_file(self._handle,
                                                 str(path).encode())
        else:
            self._open(path)

    def _open(self, path):
        if self._file is not None:
            self._file.close()
        try:
            self._file = open(path, "a")
        except OSError:
            self._file = None

    # -- output ----------------------------------------------------------
    def send(self, message):
        msg = str(message)
        if self._handle is not None:
            native._lib.sg_channel_send(self._handle, msg.encode())
            return
        if self._to_stderr:
            print(msg, file=sys.stderr)
        if self._to_file and self._file is not None:
            self._file.write(msg + "\n")
            self._file.flush()


def init_channel(argv=None):
    """Global channel-system init (reference InitChannel, channel.cc:95)."""
    return None


def set_channel_directory(path):
    """Directory for default per-channel files (reference
    SetChannelDirectory, channel.cc:100). Affects channels created after
    the call."""
    global _directory
    with _lock:
        _directory = str(path)
        if native.AVAILABLE:
            native._lib.sg_set_channel_directory(_directory.encode())


def get_channel(name):
    """Get-or-create the channel singleton (reference GetChannel,
    channel.cc:105)."""
    with _lock:
        ch = _channels.get(name)
        if ch is None:
            ch = Channel(name)
            _channels[name] = ch
        return ch


# reference-style aliases
InitChannel = init_channel
SetChannelDirectory = set_channel_directory
GetChannel = get_channel
