"""Model API: trace-once-then-replay training steps on XLA.

Capability parity with the reference Model (python/singa/model.py): the user
subclasses :class:`Model`, defines ``forward`` and ``train_one_batch``, calls
``compile`` once, then ``model(tx, ty)`` per step. In the reference, graph
mode buffers ops into the C++ Graph on the first call and replays it after
(ModelMeta.buffer_operation, model.py:39-100); here graph mode *is*
``jax.jit``:

- call 1 runs eagerly, materialising deferred layer params and optimizer aux
  state (the reference's trace-with-graph-enabled pass);
- call 2 traces ``train_one_batch`` — forward, the autograd tape's backward,
  and the optimizer update — into ONE XLA computation with all mutable state
  (params, BN running stats, optimizer moments) threaded functionally and
  donated, so XLA buffer-assignment reproduces the Graph's memory recycling
  (scheduler.cc:671-688) and its topological scheduling for free;
- later calls replay the compiled executable.

Distributed, two generations:

- legacy (``DistOpt`` without ``compile(mesh=)``): the compiled step is
  ``shard_map``'d over the mesh 'data' axis — inputs batch-sharded, state
  replicated — and the per-gradient ``psum`` calls inside the tape become
  ICI all-reduces that XLA overlaps with remaining backward compute (the
  TPU form of the reference's stream-overlap design, opt.py:826-865);
- GSPMD (``compile(mesh=...)`` / ``fsdp_axis=`` / ``DistOpt(zero=True)``):
  the SAME step body jitted once with NamedSharding in/out annotations
  from ``parallel/gspmd.py`` — no shard_map, no hand-written psum (the
  communicator is identity outside its collective context); XLA's SPMD
  partitioner inserts the gradient all-reduces, and under FSDP shards
  optimizer state + masters over 'data' with just-in-time gathers
  (reduce-scatter grads → sharded update → all-gather params).
"""

from __future__ import annotations

import io
import json
import time
import zipfile

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .tensor import Tensor
from .layer import Layer
from .autograd_base import CTX
from . import device as device_mod


def _aot_cache_snapshot():
    """Persistent-compile-cache hit/miss counters BEFORE a dispatch
    that may trace — two dict reads through a cached module ref, so the
    steady-state step path pays nothing measurable."""
    global _aot_cache_mod
    if _aot_cache_mod is None:
        from .aot import cache
        _aot_cache_mod = cache
    return _aot_cache_mod.snapshot()


_aot_cache_mod = None


class _TensorSlot:
    """Marker for a traced-tensor position in a step-arg layout (distinct
    from a static ``None`` arg such as the default ``spars``)."""

    def __repr__(self):
        return "<tensor>"


_TENSOR = _TensorSlot()


def _batch_dim_axes(input_specs, default_axis):
    """Mesh axes the batch (dim 0 of the first input) is sharded over —
    the correct default out-spec for batch-leading output leaves."""
    if input_specs:
        spec = input_specs[0]
        if len(spec) > 0 and spec[0] is not None:
            return spec[0]
    return default_axis


def _mesh_step_context(mesh, input_specs, axis):
    """Context both step bodies (train and eval) enter: register every
    mesh axis for collectives AND declare which axes shard the batch
    (read by cross-replica statistics like sync-BN). One shared helper so
    the two bodies can never derive different batch axes."""
    import contextlib

    from .parallel.communicator import batch_shard_axes, collective_context

    stack = contextlib.ExitStack()
    stack.enter_context(collective_context(*mesh.axis_names))
    stack.enter_context(batch_shard_axes(
        _batch_dim_axes(input_specs or [], axis)))
    return stack


def _resolve_leaf_specs(leaves, full_batch, input_specs, axis, user_out):
    """Default per-output-leaf layouts, shared by the train and eval
    builders: a user-supplied spec list wins; otherwise batch-leading
    leaves shard like the input batch dim (which may span several mesh
    axes, e.g. ('data','expert') for MoE — P('data') alone would
    mis-stitch those outputs) and everything else replicates.

    Leaves are already arrays (or array-shaped zeros from the abstract
    rehearsal); only their host metadata is read — no jnp.asarray, no
    device round-trip on the compile path."""
    if user_out is not None:
        return list(user_out)
    shapes = [x.shape if hasattr(x, "shape") else np.shape(x)
              for x in leaves]
    shard_mask = [len(s) >= 1 and s[0] == full_batch for s in shapes]
    batch_ax = _batch_dim_axes(input_specs, axis)
    return [P(batch_ax) if m else P() for m in shard_mask]


def _fit_state_spec(spec, shape, mesh):
    """Spec-to-mesh fitting now lives in the ONE sharding vocabulary
    (``parallel/gspmd.py`` — an indivisible dim falls back to
    replication and the layers' offset math detects the full-width
    tensor); this alias keeps the compiled-step and checkpoint
    live-sharding call sites unchanged. Lazy import: parallel pulls the
    layer stack in, and model.py is imported before it."""
    from .parallel.gspmd import fit_state_spec
    return fit_state_spec(spec, shape, mesh)


def _shard_map_compat_kwargs():
    """shard_map's replication-check kwarg was renamed across jax
    versions; disable it under whichever name this jax uses."""
    import inspect
    sig = inspect.signature(shard_map).parameters
    if "check_vma" in sig:
        return {"check_vma": False}
    if "check_rep" in sig:
        return {"check_rep": False}
    return {}


def _flatten(obj, leaves):
    """Flatten nested tuples/lists/dicts of Tensors into arrays + treedef."""
    if isinstance(obj, Tensor):
        leaves.append(obj.data)
        return ("T", len(leaves) - 1)
    if isinstance(obj, (list, tuple)):
        kids = [_flatten(o, leaves) for o in obj]
        return ("L" if isinstance(obj, list) else "U", kids)
    if isinstance(obj, dict):
        return ("D", {k: _flatten(v, leaves) for k, v in obj.items()})
    leaves.append(jnp.asarray(obj))
    return ("T", len(leaves) - 1)


def _unflatten(tree, leaves, device):
    kind, val = tree
    if kind == "T":
        return Tensor(data=leaves[val], device=device, requires_grad=False)
    if kind == "U":
        return tuple(_unflatten(k, leaves, device) for k in val)
    if kind == "L":
        return [_unflatten(k, leaves, device) for k in val]
    return {k: _unflatten(v, leaves, device) for k, v in val.items()}


class Model(Layer):
    """Base user model (reference python/singa/model.py Model).

    Mesh layout hooks (all optional class/instance attributes):

    - ``input_specs``: per-input PartitionSpec list for the compiled
      train step (default: batch dim over the DistOpt axis).
    - ``output_specs``: per-output-leaf specs for the train step.
    - ``eval_output_specs``: per-output-leaf specs for the SHARDED eval
      path. Without it, batch-leading leaves shard like the input batch
      and every other leaf is ``pmean``'d over the reduce axes — correct
      for mean-type outputs (losses, accuracies averaged in-model), but
      it would divide SUM-type outputs (per-batch counts, summed
      errors) by the world size relative to the gathered eager path.
    - ``eval_output_reduce``: per-leaf ``"mean"``/``"sum"`` list
      selecting how replicated (non-batch-leading) eval leaves combine
      across shards (default ``"mean"``). Models whose eval returns
      per-batch sums set ``"sum"`` for those leaves to keep sharded and
      eager eval numerically identical.
    """

    def __init__(self):
        super().__init__()
        self.graph_mode = True
        self.sequential = False
        self._train = False
        self.dev = None
        self._compiled = False
        self._step_ready = False   # first (eager) train call done
        self._steps = {}           # static-arg signature -> compiled step
        self._state_list = None
        self._dist = None
        self._gspmd_mesh = None    # compile(mesh=...) → GSPMD train step
        self._fsdp_axis = None     # ZeRO/FSDP shard axis (GSPMD only)
        self._policy = None        # mixed_precision.Policy (compile arg)
        self._step_count = 0
        self._eval_steps = {}      # input signature -> compiled eval step
        self.step_times = []

    # -- user hooks --------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def train_one_batch(self, *args, **kwargs):
        raise NotImplementedError

    def _migrate_masters(self, new_policy):
        """Recompiling across a param-dtype change (pure-bf16 ->
        bf16_mixed, or back to an explicit 16-bit master policy): cast
        already-materialised trainable params — and the optimizer aux
        that mirrors them (momentum/moments/residuals) — to the new
        master dtype, so the live state matches what the new policy
        reports and checkpoints. Non-trainable state (BN running stats,
        guard counters/shadows) keeps its own dtype; 16->32 is
        lossless, 32->16 is the destination policy's own quantisation."""
        pd = new_policy.param_dtype if new_policy is not None else None
        if pd is None:
            return

        def _adapt(t):
            if not isinstance(t.data, jax.core.Tracer) and \
                    jnp.issubdtype(t.dtype, jnp.floating) and \
                    t.dtype != pd:
                t.data = t.data.astype(pd)

        for t in self.get_states().values():
            if t.requires_grad:
                _adapt(t)
        opt0 = getattr(self, "optimizer", None)
        if opt0 is not None and hasattr(opt0, "state_tensor_dict"):
            for k, t in opt0.state_tensor_dict().items():
                # per-param aux is named '<param>:<kind>' (residuals
                # 'residual/<param>'); scalars and guard shadows are not
                if ":" in k.rsplit("/", 1)[-1] or \
                        k.startswith("residual/"):
                    _adapt(t)

    def _policy_companion(self, optimizer):
        """Pair a 16-bit precision policy with dynamic loss scaling: the
        promised-automatic GuardedOptimizer wrap, applied wherever the
        optimizer meets the policy — compile(policy=...) over an
        existing optimizer OR set_optimizer called after compile. An
        optimizer already guarded (has dynamic_loss_scale) keeps its own
        configuration."""
        pol = getattr(self, "_policy", None)
        wants = pol is not None and pol.wants_loss_scaling
        mark = vars(optimizer).get("_policy_companion_wrap") \
            if optimizer is not None else None
        if mark is not None and (not wants or mark != pol):
            # undo OUR wrap (never a user's) when the policy stops
            # wanting scaling (loss_scaling=False recompile) or changed
            # contract (bf16_mixed -> float16_mixed must re-derive its
            # init scale, not inherit the old policy's); the same
            # policy keeps the wrap AND its adapted scale state
            optimizer = optimizer.inner
        if (wants and optimizer is not None
                and not hasattr(optimizer, "dynamic_loss_scale")):
            from .resilience import GuardedOptimizer
            optimizer = GuardedOptimizer.for_policy(optimizer, pol)
            optimizer._policy_companion_wrap = pol
        return optimizer

    def set_optimizer(self, optimizer):
        optimizer = self._policy_companion(optimizer)
        self.optimizer = optimizer
        if hasattr(optimizer, "bind_model"):
            # guards (resilience.GuardedOptimizer) shadow model state the
            # optimizer never sees (BN running stats) — hand them the model
            optimizer.bind_model(self)

    # -- modes -------------------------------------------------------------
    def train(self, mode=True):
        self._train = mode
        CTX.training = mode

    def eval(self):
        self.train(False)

    def graph(self, mode=True, sequential=False):
        """Enable/disable compiled-graph execution
        (reference model.py graph())."""
        self.graph_mode = mode
        self.sequential = sequential

    # -- compile -----------------------------------------------------------
    def compile(self, inputs, is_train=True, use_graph=False,
                sequential=False, policy=None, compile_cache=None,
                mesh=None, fsdp_axis=None):
        """Shape-infer via a dry forward run (reference model.py:156-184),
        decide graph (jit) mode, and detect a distributed optimizer.

        ``mesh``: a named :class:`jax.sharding.Mesh` (e.g.
        ``parallel.gspmd.train_mesh(data=8)``) switching the compiled
        train step onto the GSPMD path: ONE jitted program whose
        state/batch arguments carry explicit NamedShardings from the
        ``parallel/gspmd.py`` spec vocabulary — no shard_map wrapper,
        no hand-written psum; XLA's SPMD partitioner inserts the
        gradient all-reduces. Bitwise-parity-pinned against the legacy
        shard_map DP driver (the CI multichip leg).

        ``fsdp_axis``: ZeRO/FSDP memory layout on the GSPMD path —
        params, fp32 masters and optimizer aux sharded over this mesh
        axis (``True`` means ``'data'``) and gathered just-in-time
        inside the program (XLA emits reduce-scatter grads → sharded
        update → all-gather params), ~N× optimizer-state headroom per
        chip. Implied by a ``DistOpt(zero=True)`` optimizer; with no
        explicit ``mesh`` the default data mesh of the model's
        platform is used.

        ``policy``: a :class:`singa_tpu.mixed_precision.Policy` (or its
        name, e.g. ``"bf16_mixed"``) activating mixed-precision compile:
        parameters are created/updated as fp32 masters, matmul/conv/
        attention cast their operands to the compute dtype INSIDE the
        jitted step (one fused XLA program; donation of the fp32 state
        is unchanged), fragile ops (norm stats, softmax/loss reductions)
        stay fp32, and floating output leaves are cast back to the
        policy's output dtype at the step boundary. A 16-bit policy is
        paired with dynamic loss scaling by default: a plain optimizer
        is wrapped in ``resilience.GuardedOptimizer`` here (pass
        ``Policy(name, loss_scaling=False)`` or pre-wrap yourself to
        opt out).

        ``compile_cache``: a :class:`singa_tpu.aot.CachePolicy` (or a
        cache directory, or True for the default directory) installing
        JAX's persistent compilation cache process-wide, so a restart
        of this same program deserializes its executables instead of
        recompiling — every traced dispatch then labels its
        ``compile_seconds`` observation ``source="cache"`` or
        ``"fresh"``. Process-global by nature (it is ONE jax config);
        routed through here so the policy travels with the compile
        call that benefits."""
        assert len(inputs) > 0
        from .observability import metrics as _obs_metrics
        from .observability import spans as _obs_spans
        if compile_cache is not None:
            from .aot import cache as _aot_cache
            _aot_cache.install(compile_cache)
        t0 = time.perf_counter()
        with _obs_spans.span("compile", policy=str(policy)):
            self._compile_body(inputs, is_train, use_graph, sequential,
                              policy, mesh=mesh, fsdp_axis=fsdp_axis)
        _obs_metrics.default_registry().histogram(
            "model_compile_seconds",
            "Model.compile wall-clock (dry run + shape inference; the "
            "XLA trace/compile itself lands on the first step)"
        ).observe(time.perf_counter() - t0)

    def compile_serving(self, policy=None, **kw):
        """Build this model's inference engine (``singa_tpu.serving``):
        the serving sibling of :meth:`compile`.

        Autoregressive models (anything exposing ``decode_adapter`` —
        the transformer and char-rnn zoo models) get a continuous-
        batching :class:`~singa_tpu.serving.ServingEngine`: two
        AOT-compiled fixed-shape programs (batched prefill writing a
        donated ring KV cache; a one-token O(1) decode step) over a
        ``slots``-wide in-flight slot array. Everything else — the
        classifier zoo, ONNX imports through ``sonnx.SONNXModel`` —
        serves through a fixed-width
        :class:`~singa_tpu.serving.BatchServingEngine` (pass
        ``input_shape=`` for the per-sample shape).

        ``policy``: a mixed-precision :class:`Policy` or name
        (``"bf16_mixed"`` serves in bf16 compute with an f32 head/
        logits). Defaults to the policy this model was last
        ``compile``d with, so a bf16-trained model serves bf16 out of
        the box. The engine is returned un-started; call ``.start()``
        for the background loop or drive ``step()`` synchronously.
        Other ``kw`` (``slots``, ``max_len``, ``prefill_len``,
        ``queue_capacity``, ``faults``, ``registry``, ...) pass through
        to the engine.

        Sharded serving (``singa_tpu.parallel.gspmd``):
        ``model_shards=N`` (or an explicit ``mesh=`` with named
        ``batch``/``model`` axes) runs the prefill/decode programs
        tensor/vocab-sharded over a (batch × model) device mesh as the
        SAME single jitted programs — params/KV annotated with
        NamedSharding, XLA inserts the collectives, greedy argmax
        computed in graph over the vocab shards. Configs the mesh
        cannot honor (indivisible heads/vocab/slots, too few devices)
        are typed declines at build.

        Cold-start knobs (``singa_tpu.aot``): ``compile_cache=``
        installs the persistent compilation cache exactly like
        :meth:`compile`'s; ``aot_store=`` (an
        :class:`~singa_tpu.aot.AotStore` or its directory) makes the
        engine deserialize previously exported prefill/decode
        executables instead of tracing — honored-or-refused against
        the artifact manifests — and is where
        ``engine.export_aot()`` writes."""
        from . import mixed_precision as mp
        from .serving import build_engine
        compile_cache = kw.pop("compile_cache", None)
        if compile_cache is not None:
            from .aot import cache as _aot_cache
            _aot_cache.install(compile_cache)
        pol = mp.resolve(policy) if policy is not None \
            else getattr(self, "_policy", None)
        return build_engine(self, policy=pol, **kw)

    def _compile_body(self, inputs, is_train, use_graph, sequential,
                      policy, mesh=None, fsdp_axis=None):
        from . import mixed_precision as mp
        new_policy = mp.resolve(policy)
        if new_policy != getattr(self, "_policy", None):
            # a RE-compile under a different policy must not replay
            # executables traced under the old one (they'd silently run
            # the old precision while every surface reports the new),
            # and params the old policy already materialised — the dry
            # run below creates them on the FIRST compile — move to the
            # new master dtype. Both are no-ops on a fresh model.
            self._invalidate_compiled()
            self._step_ready = False
            self._migrate_masters(new_policy)
        self._policy = new_policy
        opt0 = getattr(self, "optimizer", None)
        if opt0 is not None:
            # loss scaling is the default companion of a 16-bit policy:
            # re-route the existing optimizer through set_optimizer so
            # the _policy_companion wrap applies (set_optimizer called
            # AFTER compile hits the same wrap there)
            self.set_optimizer(opt0)
        self.dev = inputs[0].device
        self.graph_mode = use_graph
        self.sequential = sequential
        prev = CTX.training
        CTX.training = False
        try:
            # abstract dry run: layer.initialize still executes (params
            # materialise concretely — under a policy, as its master
            # dtype) but the inter-layer compute traces with zero device
            # work — on a network-tunneled accelerator an eager dry run
            # costs one round trip PER OP
            self._abstract_call(inputs, lambda: self.forward(*inputs))
        except Exception as e:
            import warnings
            warnings.warn(
                f"abstract dry run failed ({type(e).__name__}: {e}); "
                "falling back to an eager forward — host-side effects in "
                "forward may have run twice", stacklevel=2)
            with self._policy_scope():
                self.forward(*inputs)
        finally:
            CTX.training = prev
        # name params/states now so optimizer aux keys are stable between
        # the eager first step and the traced step
        for name, t in self.get_states().items():
            t.name = t.name or name
        opt = getattr(self, "optimizer", None)
        from .opt import DistOpt
        if isinstance(opt, DistOpt):
            self._dist = opt
        elif isinstance(getattr(opt, "inner", None), DistOpt):
            # a wrapper (e.g. resilience.GuardedOptimizer) around a
            # DistOpt: the mesh/collective plumbing keys off the DistOpt
            self._dist = opt.inner
        if fsdp_axis is True:
            from .parallel.gspmd import DATA_AXIS
            fsdp_axis = DATA_AXIS
        if fsdp_axis is None and self._dist is not None and \
                getattr(self._dist, "zero", False):
            # DistOpt(zero=True) is the optimizer-side spelling of
            # compile(fsdp_axis=...): same GSPMD+FSDP program
            fsdp_axis = self._dist.axis_name
        if (mesh, fsdp_axis) != (self._gspmd_mesh, self._fsdp_axis) \
                and self._steps:
            # a re-compile that changes the partitioning mode must not
            # replay executables built for the old layout
            self._invalidate_compiled()
        self._gspmd_mesh = mesh
        self._fsdp_axis = fsdp_axis
        self._compiled = True
        self.train(is_train)

    def _policy_scope(self):
        """The model's precision-policy scope: entered inside every
        traced body (train step, eval step, abstract rehearsal) AND the
        eager fallbacks, so op-level compute casts and param creation
        see one consistent policy wherever the model's code runs —
        including a watchdog worker thread (the scope is entered inside
        the body, so no ContextVar propagation is needed). Nullcontext
        when the model was compiled without a policy.

        A weight-quantized model (``quant.quantize_params``) also
        enters its dequant scope here: int8 payloads are rebound to
        their in-graph dequantized values for the body's duration, so
        every path — eager, compiled, serving — consumes fp32 weights
        while the threaded/stored state stays int8."""
        import contextlib
        from . import mixed_precision as mp
        stack = contextlib.ExitStack()
        stack.enter_context(mp.policy_scope(getattr(self, "_policy",
                                                    None)))
        if getattr(self, "_quant_pairs", None):
            from .quant import core as _qcore
            stack.enter_context(_qcore.dequant_params_scope(self))
        return stack

    def get_states(self):
        """Layer state walk, plus the per-channel quantization scales a
        weight-quantized model carries (``quant-scale/<param>`` — see
        ``quant.quantize_params``): scales thread through compiled
        steps, checkpoints and digests exactly like any other state."""
        states = super().get_states()
        states.update(getattr(self, "_quant_scales", {}))
        return states

    # -- abstract (zero-compute) materialisation ---------------------------
    def _abstract_call(self, inputs, body):
        """Run ``body`` under ``jax.eval_shape`` with the input tensors'
        payloads abstracted, so python side effects (layer init, optimizer
        aux creation) happen while NO device computation is issued; any
        pre-existing state the body mutated is restored afterwards and
        tracer-valued leftovers are replaced with zeros.

        This is the reference's buffered-first-call semantics
        (model.py:56-91: the first call records, it does not execute) —
        and on a network-tunneled accelerator it turns O(ops) round trips
        into none. RNG keys consumed by the run (param inits, dropout)
        stay consumed, exactly as an eager first call would leave them.
        Returns the body result with concrete zero-filled leaves
        (shapes/dtypes preserved)."""
        from .device import get_default_device
        snapshot = [(t, t.data) for t in self._state_tensors()]
        datas = [t.data for t in inputs]
        devs = list({id(self.dev): self.dev,
                     id(get_default_device()): get_default_device()
                     }.values())
        prev_rngs = [d._get_rng_state() for d in devs]
        captured = {}

        def absfn(arrs):
            for t, a in zip(inputs, arrs):
                t.data = a
            res = body()
            leaves = []
            captured["tree"] = _flatten(res, leaves)
            return leaves

        try:
            with self._policy_scope():
                out_avals = jax.eval_shape(
                    absfn, [jax.ShapeDtypeStruct(np.shape(d), d.dtype)
                            for d in datas])
        finally:
            for t, d in zip(inputs, datas):
                t.data = d
            for t, d in snapshot:
                t.data = d
            # state born during the abstract run (optimizer aux, freshly
            # initialised layer stats) may hold dead tracers: zero it
            for t in self._state_tensors():
                if isinstance(t.data, jax.core.Tracer):
                    t.data = np.zeros(t.data.shape,
                                      t.data.dtype)
            # keys consumed concretely (param inits) stay consumed; if
            # TRACED draws (dropout) left a device rng holding a dead
            # tracer, hop each such device to its OWN fresh stream (a
            # rewind would replay init keys; sharing one repaired key
            # would correlate the devices' draws). Ops fall back to the
            # process-wide default device, so it is covered too.
            for i, (d, prev) in enumerate(zip(devs, prev_rngs)):
                if isinstance(d._get_rng_state(), jax.core.Tracer):
                    d._set_rng_state(jax.random.fold_in(prev, 0x5eed + i))
        leaves = [np.zeros(a.shape, a.dtype) for a in out_avals]
        return _unflatten(captured["tree"], list(leaves), self.dev)

    # -- state plumbing ----------------------------------------------------
    def _state_tensors(self):
        """Ordered mutable state: layer params+states, then optimizer aux."""
        seen = {}
        for name, t in self.get_states().items():
            if id(t) not in seen:
                t.name = t.name or name
                seen[id(t)] = t
        opt = getattr(self, "optimizer", None)
        if opt is not None and hasattr(opt, "state_tensors"):
            for t in opt.state_tensors():
                if id(t) not in seen:
                    seen[id(t)] = t
        return list(seen.values())

    # -- the compiled step -------------------------------------------------
    @staticmethod
    def _split_step_args(args):
        """Split positional args into traced tensor inputs and static
        config. Tensors/arrays are traced; strings, None and python
        scalars — the reference calling convention
        ``model(tx, ty, dist_option, spars)``
        (reference examples/cnn/train_cnn.py:219) — are closed over into
        the compiled step and key its cache, so each distinct dist option
        gets its own executable instead of crashing ``jnp.asarray``."""
        arrays, layout = [], []
        for a in args:
            if isinstance(a, Tensor):
                arrays.append(a.data)
                layout.append(_TENSOR)
            elif isinstance(a, (np.ndarray, jax.Array)):
                arrays.append(jnp.asarray(a))
                layout.append(_TENSOR)
            else:
                layout.append(a)
        return arrays, tuple(layout)

    def _ensure_state(self):
        """Collect mutable state once; move it to the model device
        (optimizer scalars are born on the host default device)."""
        if self._state_list is not None:
            return
        opt = getattr(self, "optimizer", None)
        if hasattr(opt, "materialize_shadows"):
            # create the guard's shadow tensors from the CURRENT concrete
            # values, so they join the threaded state collected below
            opt.materialize_shadows()
        state_list = self._state_tensors()
        for t in state_list:
            if not isinstance(t.data, jax.core.Tracer):
                t.data = self.dev.put(t.data)
                t.device = self.dev
        self._state_list = state_list
        opt = getattr(self, "optimizer", None)
        if opt is not None:
            (opt.opt if hasattr(opt, "opt") else opt)._frozen = True

    def _gspmd_active(self):
        """True when the train step compiles on the GSPMD path (one
        jitted program, NamedSharding in/out, XLA-inserted collectives)
        instead of the legacy shard_map + explicit-psum path."""
        return self._gspmd_mesh is not None or self._fsdp_axis is not None

    def _build_step(self, layout):
        self._ensure_state()
        state_list = self._state_list
        rec = {"jit": None, "builder": None, "out_tree": {},
               "leaf_specs": None, "input_specs": None}
        dist = self._dist
        gspmd = self._gspmd_active()
        n_inputs = sum(1 for s in layout if s is _TENSOR)

        def fn(state_arrays, rng_key, *input_arrays):
            # host-side trace counter: this python body runs ONCE per
            # jit trace (steady-state training must keep it at 1 — the
            # retrace-guard CI test pins that; cost-analysis/audit
            # re-lowers legitimately add to it)
            rec["n_traces"] = rec.get("n_traces", 0) + 1
            # advance the RNG stream inside the trace: one half drives this
            # step's random ops, the other is handed back as the next
            # step's key — no host-side eager split per step (it cost more
            # than the whole dispatch of a small compiled step)
            rng_key, next_key = jax.random.split(rng_key)
            if dist is not None and not gspmd:
                # distinct rng per batch-shard (data and, under sequence
                # parallelism, seq); model-parallel members share the key.
                # The GSPMD path traces OUTSIDE shard_map (axis names are
                # unbound — axis_index would not even trace) and draws
                # global-batch randomness from the one shared key, which
                # XLA partitions like any other value.
                for ax in dist.communicator.reduce_axes:
                    rng_key = jax.random.fold_in(
                        rng_key, jax.lax.axis_index(ax))
            for t, a in zip(state_list, state_arrays):
                t.data = a
            self.dev._set_rng_state(rng_key)
            it = iter(input_arrays)
            ins = [Tensor(data=next(it), device=self.dev,
                          requires_grad=False) if s is _TENSOR else s
                   for s in layout]
            from .ops import fused_optim as _fused
            fused_kinds = []
            with self._policy_scope(), _fused.trace_collector(fused_kinds):
                res = self.train_one_batch(*ins)
            if fused_kinds:
                # the program contains fused Pallas custom calls whose
                # FLOPs XLA's cost analysis cannot count — step_flops
                # must use the reference twin for MFU (see step_flops)
                rec["fused_kinds"] = sorted(set(fused_kinds))
            leaves = []
            rec["out_tree"]["tree"] = _flatten(res, leaves)
            pol = getattr(self, "_policy", None)
            if pol is not None:
                # step-boundary output cast: compute may run 16-bit but
                # what the host sees is the policy's output dtype
                leaves = [pol.cast_output(x) for x in leaves]
            if dist is not None and not gspmd:
                # output leaves that end up replicated (loss scalars,
                # metrics, param snapshots) are averaged across batch-like
                # shards so the replicated out-spec is sound. GSPMD leaves
                # are already GLOBAL values — XLA stitches them; a pmean
                # would both double-average and fail to trace (unbound
                # axis names outside shard_map).
                specs = rec["leaf_specs"]
                raxes = tuple(dist.communicator.reduce_axes)
                leaves = [x if specs[i] != P() else jax.lax.pmean(x, raxes)
                          for i, x in enumerate(leaves)]
            new_state = [t.data for t in state_list]
            return new_state, leaves, next_key

        if gspmd:
            from jax.sharding import NamedSharding
            from .parallel import gspmd as _gspmd
            from .parallel.communicator import get_mesh
            mesh = self._gspmd_mesh
            if mesh is None:
                # fsdp_axis-only compile: default data mesh over the
                # devices of the model's platform
                mesh = (dist.communicator.mesh
                        if dist is not None and
                        dist.communicator.mesh is not None
                        else get_mesh(devices=jax.devices(
                            self.dev.jax_device.platform)))
            fsdp = self._fsdp_axis
            axis = dist.axis_name if dist is not None else _gspmd.DATA_AXIS
            if axis not in mesh.shape:
                raise _gspmd.ShardingDecline(
                    f"train mesh {dict(mesh.shape)} has no batch axis "
                    f"{axis!r}: build it via parallel.gspmd.train_mesh "
                    "or parallel.mesh.MeshConfig")
            if fsdp is not None and fsdp not in mesh.shape:
                raise _gspmd.ShardingDecline(
                    f"fsdp_axis {fsdp!r} is not in the train mesh "
                    f"{dict(mesh.shape)}")
            if dist is not None:
                # keep the communicator's mesh pointer current so
                # checkpoint manifests / heartbeats describe the mesh
                # this model actually trains on (its collectives stay
                # identity — the GSPMD body never enters the context)
                dist.communicator.mesh = mesh

            def build(sample_inputs, rng):
                # output shapes are known from the first (abstract) full-
                # batch rehearsal; an output is batch-sharded iff its
                # leading dim is the global batch
                leaves = []
                _flatten(self._eager_out, leaves)
                full_batch = sample_inputs[0].shape[0]
                # per-state layouts from the ONE sharding vocabulary:
                # announced tensor/expert specs mesh-fitted; under FSDP
                # each state tensor additionally shards its first
                # divisible replicated dim over the fsdp axis
                if fsdp is not None:
                    state_specs = [_gspmd.fsdp_state_spec(
                        t.spec, t.shape, mesh, axis=fsdp)
                        for t in state_list]
                else:
                    state_specs = [_fit_state_spec(t.spec, t.shape, mesh)
                                   for t in state_list]
                self._state_specs = state_specs
                user_in = getattr(self, "input_specs", None)
                rec["input_specs"] = list(user_in) if user_in is not None \
                    else [P(axis)] * n_inputs
                rec["leaf_specs"] = _resolve_leaf_specs(
                    leaves, full_batch, rec["input_specs"], axis,
                    getattr(self, "output_specs", None))

                def ns(s):
                    return NamedSharding(mesh, s)

                in_sh = ([ns(s) for s in state_specs], ns(P()),
                         *[ns(s) for s in rec["input_specs"]])
                out_sh = ([ns(s) for s in state_specs],
                          [ns(s) for s in rec["leaf_specs"]], ns(P()))
                rec["raw_fn"] = fn   # step_flops' reference twin
                return jax.jit(fn, in_shardings=in_sh,
                               out_shardings=out_sh, donate_argnums=(0,))

            rec["builder"] = build
            self._mesh, self._axis = mesh, axis
        elif dist is not None:
            from .parallel.communicator import get_mesh
            mesh = dist.communicator.mesh
            if mesh is None:
                # mesh over the devices of the model's platform (virtual CPU
                # devices in tests, TPU chips in production)
                mesh = get_mesh(
                    devices=jax.devices(self.dev.jax_device.platform))
            dist.communicator.mesh = mesh
            axis = dist.axis_name

            def body(state_arrays, rng_key, *input_arrays):
                with _mesh_step_context(mesh, rec["input_specs"], axis):
                    return fn(state_arrays, rng_key, *input_arrays)

            def build(sample_inputs, rng):
                # output shapes are known from the first (eager) full-batch
                # call: an output is batch-sharded iff its leading dim is
                # the global batch; everything else is pmean'd + replicated
                leaves = []
                _flatten(self._eager_out, leaves)
                full_batch = sample_inputs[0].shape[0]
                # per-state sharding: tensor-parallel weights announce a
                # PartitionSpec via Tensor.spec; everything else replicates
                state_specs = [_fit_state_spec(t.spec, t.shape, mesh)
                               for t in state_list]
                self._state_specs = state_specs
                # per-input layouts: Model.input_specs overrides the default
                # batch-on-'data' sharding (sequence parallelism shards
                # dim 1 over 'seq': P('data', 'seq'))
                user_in = getattr(self, "input_specs", None)
                rec["input_specs"] = list(user_in) if user_in is not None \
                    else [P(axis)] * n_inputs
                in_specs = (state_specs, P(), *rec["input_specs"])
                rec["leaf_specs"] = _resolve_leaf_specs(
                    leaves, full_batch, rec["input_specs"], axis,
                    getattr(self, "output_specs", None))
                out_specs = (state_specs, rec["leaf_specs"], P())
                mapped = shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                                   out_specs=tuple(out_specs),
                                   **_shard_map_compat_kwargs())
                rec["raw_fn"] = mapped   # step_flops' reference twin
                return jax.jit(mapped, donate_argnums=(0,))

            rec["builder"] = build
            self._mesh, self._axis = mesh, axis
        else:
            rec["jit"] = jax.jit(fn, donate_argnums=(0,))
            rec["raw_fn"] = fn
        return rec

    def _cast_output_tree(self, res):
        """Policy output contract for EAGER results (the compiled paths
        cast their flattened leaves instead): floating leaves — Tensor
        OR raw array, matching what _flatten treats as a leaf — go to
        output_dtype."""
        pol = getattr(self, "_policy", None)
        if pol is None:
            return res

        def _cast(t):
            if isinstance(t, Tensor):
                if jnp.issubdtype(t.dtype, jnp.floating) and \
                        t.dtype != pol.output_dtype:
                    t = Tensor(data=pol.cast_output(t.data),
                               device=t.device, requires_grad=False)
                return t
            return pol.cast_output(t)

        return jax.tree_util.tree_map(
            _cast, res, is_leaf=lambda x: isinstance(x, Tensor))

    def _run_step(self, *args):
        """Train-mode step dispatch (reference
        ModelMeta.buffer_operation wrapper, model.py:56-91)."""
        if not self.graph_mode:
            # the non-graph path honors the same policy contract as the
            # compiled one (compute casts + output dtype), just eagerly
            with self._policy_scope():
                res = self.train_one_batch(*args)
            return self._cast_output_tree(res)
        if not self._step_ready:
            # first call materialises params + optimizer aux states.
            # Preferred: abstractly (zero device compute — the reference's
            # buffered first call, model.py:56-91); then THIS call already
            # runs compiled. Fallback: the eager step (host-side ops or
            # data-dependent python in train_one_batch).
            import os
            # verbosity>=2 requests per-op wall times, which only the
            # eager dispatch can record (reference per-node timing)
            if self.dev.verbosity < 2 and \
                    os.environ.get("SINGA_EAGER_FIRST_STEP", "0") != "1":
                try:
                    tensor_args = [a for a in args if isinstance(a, Tensor)]
                    self._eager_out = self._abstract_call(
                        tensor_args, lambda: self.train_one_batch(*args))
                    self._step_ready = True
                except Exception as e:
                    import warnings
                    warnings.warn(
                        "abstract first-step rehearsal failed "
                        f"({type(e).__name__}: {e}); falling back to an "
                        "eager first step — note any host-side effects in "
                        "train_one_batch may have run twice", stacklevel=3)
            if not self._step_ready:
                with self._policy_scope():
                    res = self.train_one_batch(*args)
                self._step_ready = True
                self._eager_out = res
                return self._cast_output_tree(res)
        input_arrays, layout = self._split_step_args(args)
        try:
            hash(layout)
            key = layout
        except TypeError:
            key = repr(layout)
        rec = self._steps.get(key)
        if rec is None:
            # warm restart: an AOT store (ResilientTrainer(aot=...))
            # may hold this signature's exported executable — verify
            # its manifest and deserialize INSTEAD of tracing. Any
            # mismatch (version, topology, avals, digest, policy) was
            # already refused loudly inside the loader and falls
            # through to the normal fresh build below.
            store = getattr(self, "_aot_store", None)
            if store is not None and self._dist is None and \
                    not self._gspmd_active() and isinstance(key, tuple):
                try:
                    from .aot import export as _aot_export
                    rec = _aot_export.load_train_step(
                        self, store, key, input_arrays)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:    # noqa: BLE001 — never blocks
                    import warnings
                    warnings.warn(
                        f"AOT train-step load failed unexpectedly "
                        f"({type(e).__name__}: {e}); compiling fresh",
                        stacklevel=3)
                    rec = None
            if rec is None:
                rec = self._build_step(layout)
            self._steps[key] = rec
            if len(self._steps) == 9:
                import warnings
                warnings.warn(
                    "9th distinct static-arg signature compiled for this "
                    "model; each costs a full trace+compile and is cached. "
                    "Pass per-step-varying values as Tensors, not python "
                    "scalars.", stacklevel=3)
        rng = self.dev.current_key()  # advanced in-trace; next key returned
        if rec["jit"] is None:
            rec["jit"] = rec["builder"](input_arrays, rng)
        state_arrays = [t.data for t in self._state_list]
        if self._dist is not None or self._gspmd_active():
            from jax.sharding import NamedSharding
            rep = NamedSharding(self._mesh, P())
            place = self._place_mesh
            specs = getattr(self, "_state_specs", None) or \
                [P()] * len(state_arrays)
            state_arrays = [
                place(a, NamedSharding(self._mesh, s))
                for a, s in zip(state_arrays, specs)]
            in_specs = rec["input_specs"] or \
                [P(self._axis)] * len(input_arrays)
            # identity cache: benchmark/eval loops feed the same arrays
            # every step — skip re-sharding them (one previous batch is
            # kept alive per slot, the cost of a depth-1 prefetch).
            # Immutable jax.Arrays ONLY: a host numpy array mutated in
            # place between steps would hit on object identity and
            # silently train on the stale device shard.
            cache = rec.setdefault("in_cache", [None] * len(input_arrays))
            placed = []
            for i, (a, s) in enumerate(zip(input_arrays, in_specs)):
                c = cache[i] if i < len(cache) else None
                if c is not None and c[0] is a:
                    placed.append(c[1])
                    continue
                pa = place(a, NamedSharding(self._mesh, s))
                if i < len(cache) and isinstance(a, jax.Array):
                    cache[i] = (a, pa)
                placed.append(pa)
            input_arrays = placed
            rng = place(rng, rep)
        self._last_run_rec = rec       # compiled_step_info audits this
        shapes_key = tuple(np.shape(a) for a in input_arrays)
        if rec.get("avals_key") != shapes_key:
            # abstract signature of this step (shardings included) for
            # compiled_step_info()'s lower-without-rerun audit; refreshed
            # when input shapes change (jit retraces under the same rec,
            # and the audit must describe the executable that just ran)
            def _aval(a):
                return jax.ShapeDtypeStruct(
                    np.shape(a), np.asarray(a).dtype if not hasattr(
                        a, "dtype") else a.dtype,
                    sharding=getattr(a, "sharding", None))
            rec["avals"] = ([_aval(a) for a in state_arrays], _aval(rng),
                            [_aval(a) for a in input_arrays])
            rec["avals_key"] = shapes_key
            rec.pop("audit_compiled", None)
            # the cached cost analysis and FLOP count described the old
            # program — recompute against the new signature on next use
            rec.pop("step_flops", None)
            rec.pop("cost", None)
        # compile/retrace attribution: watch the host-side trace
        # counter across the dispatch — if THIS call traced (first
        # compile, a shape/dtype retrace, or the verbosity AOT
        # re-lower below), its wall-clock lands in compile_seconds and
        # a compile/retrace flight-recorder event names the signature
        # (and, on a retrace, the argument that changed). Steady-state
        # steps pay two dict reads.
        n_traces0 = rec.get("n_traces", 0)
        t_compile0 = time.perf_counter()
        cache_counts0 = _aot_cache_snapshot()
        if self.dev.verbosity >= 2 and "cost" not in rec:
            # one-time XLA cost analysis of this step signature (the
            # compiled-world per-op metric: flops / bytes, reference
            # per-node profiling scheduler.cc:240-298). The AOT-compiled
            # executable replaces the jit wrapper so the signature is
            # compiled exactly once.
            rec["cost"] = None
            try:
                compiled = rec["jit"].lower(
                    state_arrays, rng, *input_arrays).compile()
                rec["cost"] = compiled.cost_analysis()
                rec["jit"] = compiled
            except Exception:   # cost analysis is backend-best-effort
                pass
        t0 = time.perf_counter()
        if self.dev.verbosity >= 2 and not rec.get("fusions_measured"):
            # one-time MEASURED per-fusion table for this signature (the
            # compiled-world per-node timing, reference
            # scheduler.cc:240-298) — this very step runs under a
            # profiler trace, so no extra compute and no state copies
            from . import profiling as _prof
            rec["fusions_measured"] = True

            def run_once():
                res = rec["jit"](state_arrays, rng, *input_arrays)
                # the trace must not stop before the device finishes:
                # block_until_ready can resolve on a proxy's enqueue-ACK
                # (utils.force_completion docstring), truncating the
                # fusion table
                from .utils import force_completion
                force_completion(res)
                return res

            (new_state, leaves, next_key), fus = \
                _prof.measure_step_fusions(run_once)
            for name, (cnt, tot) in fus.items():
                c0, t0_ = self.dev.time_profiling.get(
                    f"fusion/{name}", (0, 0.0))
                self.dev.time_profiling[f"fusion/{name}"] = (c0 + cnt,
                                                             t0_ + tot)
        else:
            new_state, leaves, next_key = rec["jit"](state_arrays, rng,
                                                     *input_arrays)
        if rec.get("n_traces", 0) > n_traces0:
            from .aot import cache as _aot_cache
            from .observability import perf as _perf
            sig = _perf.step_signature(input_arrays)
            _perf.record_compile(
                "train_step", time.perf_counter() - t_compile0, sig,
                prev_signature=rec.get("arg_sig"),
                source=_aot_cache.classify(cache_counts0),
                step=self._step_count)
            rec["arg_sig"] = sig
        self.dev._set_rng_state(next_key)  # tracing clobbered dev rng
        if self._dist is not None or self._gspmd_active():
            # bound the async in-flight queue: a host loop can dispatch
            # compiled steps much faster than they run, and hundreds of
            # queued multi-device programs starve the collective
            # rendezvous (the CPU backend aborts after 40s; on TPU it
            # just bloats memory). Blocking on step N-2 keeps a depth-2
            # pipeline — overlap without unbounded growth. The fence
            # rides the returned rng key: an output (never donated, so
            # still alive two steps later) whose readiness implies the
            # whole step executed.
            fence = getattr(self, "_step_fence", None)
            if fence is None:
                from collections import deque
                fence = self._step_fence = deque()
            fence.append(next_key)
            if len(fence) > 2:
                jax.block_until_ready(fence.popleft())
        self._step_count += 1
        if self.dev.verbosity > 0 and \
                self._step_count > self.dev.skip_iteration:
            # reference semantics: timing starts after skip_iteration
            # steps (include/singa/core/device.h:115-129)
            jax.block_until_ready(new_state)
            self.dev._record_time("train_one_batch",
                                  time.perf_counter() - t0)
        for t, a in zip(self._state_list, new_state):
            t.data = a
        return _unflatten(rec["out_tree"]["tree"], list(leaves), self.dev)

    # -- profiling / debugging --------------------------------------------
    def cost_analysis(self):
        """XLA cost analysis (flops, bytes accessed, ...) per compiled
        step signature, captured at verbosity>=2. The compiled-world form
        of the reference's per-op profiling (scheduler.cc:240-298): XLA
        fuses ops, so per-fusion costs replace per-node times."""
        out = {}
        for key, rec in self._steps.items():
            c = rec.get("cost")
            if isinstance(c, (list, tuple)):
                c = c[0] if c else None
            out[key] = c
        return out

    def graph_debug(self, *args, print_out=True, max_rows=None):
        """Dump the traced training step as a jaxpr op table — the XLA-era
        ``Graph::Debug`` (reference src/core/scheduler/scheduler.cc:109-238
        dumps nodes/edges/blocks; here each jaxpr equation is a node and
        its avals are the blocks). Call with the same args as a step."""
        if not self._step_ready:
            raise ValueError(
                "graph_debug needs materialised state: run one training "
                "step first (the eager first call creates optimizer aux)")
        input_arrays, layout = self._split_step_args(args)
        self._ensure_state()
        state_arrays = [t.data for t in self._state_list]
        backup = list(state_arrays)
        host_key = self.dev._get_rng_state()

        def fn(state_arrays, *input_arrays):
            for t, a in zip(self._state_list, state_arrays):
                t.data = a
            it = iter(input_arrays)
            ins = [Tensor(data=next(it), device=self.dev,
                          requires_grad=False) if s is _TENSOR else s
                   for s in layout]
            # same policy scope as the real step, so the dumped jaxpr
            # shows the convert ops the compiled program actually runs
            with self._policy_scope():
                res = self.train_one_batch(*ins)
            leaves = []
            _flatten(res, leaves)
            return [t.data for t in self._state_list], leaves

        try:
            jaxpr = jax.make_jaxpr(fn)(state_arrays, *input_arrays)
        finally:
            for t, a in zip(self._state_list, backup):
                t.data = a
            self.dev._set_rng_state(host_key)
        eqns = jaxpr.jaxpr.eqns
        lines = [f"step graph: {len(eqns)} ops, "
                 f"{len(jaxpr.jaxpr.invars)} inputs, "
                 f"{len(jaxpr.jaxpr.outvars)} outputs"]
        shown = eqns if max_rows is None else eqns[:max_rows]
        for i, eqn in enumerate(shown):
            outs = ", ".join(str(v.aval) for v in eqn.outvars)
            lines.append(f"{i:4d}  {eqn.primitive.name:<28} -> {outs}")
        if max_rows is not None and len(eqns) > max_rows:
            lines.append(f"... {len(eqns) - max_rows} more ops")
        text = "\n".join(lines)
        if print_out:
            print(text)
        return text

    def _invalidate_compiled(self):
        """Drop every compiled step/eval specialization: the state
        tensors' identities changed (load_states / checkpoint restore)
        and the traced closures are bound to the old ones."""
        self._steps = {}
        self._eval_steps = {}
        self._state_list = None

    def _place_mesh(self, a, sharding):
        """Lay an array out on the mesh. On a multi-process mesh the
        sharding spans devices of other hosts, which device_put cannot
        reach — each process contributes its addressable shards from its
        (SPMD-identical) host copy instead."""
        if getattr(a, "sharding", None) == sharding:
            return a
        if sharding.is_fully_addressable:
            return jax.device_put(a, sharding)
        val = np.asarray(jax.device_get(a))
        return jax.make_array_from_callback(
            val.shape, sharding, lambda idx: val[idx])

    # -- sharded eval ------------------------------------------------------

    def _eval_input_specs(self, n_inputs):
        user_in = getattr(self, "input_specs", None)
        if user_in is not None:
            # eval usually takes fewer inputs than training (x, no y):
            # use the leading specs
            return list(user_in)[:n_inputs]
        return [P(self._axis)] * n_inputs

    def _eval_divisible(self, input_arrays, in_specs):
        for a, s in zip(input_arrays, in_specs):
            shape = np.shape(a)
            for d, names in enumerate(s):
                if names is None:
                    continue
                names = names if isinstance(names, tuple) else (names,)
                k = 1
                for nm in names:
                    k *= self._mesh.shape[nm]
                if d >= len(shape) or shape[d] % k:
                    return False
        return True

    def _build_eval(self, input_tensors):
        """Compile an eval forward under the SAME mesh and shardings as
        the training step, so tp/ep-sharded state is consumed where it
        lives instead of being gathered to one device — which OOMs for
        exactly the models model-parallelism exists for. (Reference
        inference runs on the same device graph, model.py:210-222.)"""
        self._ensure_state()
        state_list = self._state_list
        dist = self._dist
        mesh, axis = self._mesh, self._axis
        rec = {}

        # leaf shapes via an abstract rehearsal: zero device compute, and
        # collectives are identity outside the mesh so logical shapes match
        out = self._abstract_call(
            list(input_tensors), lambda: self.forward(*input_tensors))
        leaves0 = []
        _flatten(out, leaves0)
        rec["input_specs"] = self._eval_input_specs(len(input_tensors))
        rec["leaf_specs"] = _resolve_leaf_specs(
            leaves0, input_tensors[0].shape[0], rec["input_specs"], axis,
            getattr(self, "eval_output_specs", None))
        state_specs = getattr(self, "_state_specs", None) or \
            [_fit_state_spec(t.spec, t.shape, mesh) for t in state_list]
        rec["state_specs"] = state_specs

        def fn(state_arrays, *input_arrays):
            backup = [t.data for t in state_list]
            for t, a in zip(state_list, state_arrays):
                t.data = a
            prev = CTX.training
            CTX.training = False
            try:
                ins = [Tensor(data=a, device=self.dev,
                              requires_grad=False)
                       for a in input_arrays]
                with self._policy_scope():
                    res = self.forward(*ins)
            finally:
                CTX.training = prev
                # eval leaves state untouched: restore the concrete
                # arrays so no tracer outlives the trace
                for t, a in zip(state_list, backup):
                    t.data = a
            leaves = []
            rec["tree"] = _flatten(res, leaves)
            pol = getattr(self, "_policy", None)
            if pol is not None:
                leaves = [pol.cast_output(x) for x in leaves]
            specs = rec["leaf_specs"]
            raxes = tuple(dist.communicator.reduce_axes)
            kinds = getattr(self, "eval_output_reduce", None) or []

            def combine(i, x):
                if specs[i] != P():          # batch-sharded: stitched
                    return x
                kind = kinds[i] if i < len(kinds) else "mean"
                red = jax.lax.psum if kind == "sum" else jax.lax.pmean
                return red(x, raxes)

            leaves = [combine(i, x) for i, x in enumerate(leaves)]
            return leaves

        def body(state_arrays, *input_arrays):
            with _mesh_step_context(mesh, rec["input_specs"], axis):
                return fn(state_arrays, *input_arrays)

        mapped = shard_map(body, mesh=mesh,
                           in_specs=(state_specs, *rec["input_specs"]),
                           out_specs=rec["leaf_specs"],
                           **_shard_map_compat_kwargs())
        rec["jit"] = jax.jit(mapped)   # state NOT donated: eval reuses it
        return rec

    def _run_eval(self, *args):
        """Mesh-resident eval dispatch. Returns NotImplemented when the
        batch does not divide the mesh — the caller falls back to the
        gather-and-run-eager path."""
        input_arrays = [a.data for a in args]
        if not self._eval_divisible(input_arrays,
                                    self._eval_input_specs(len(args))):
            return NotImplemented
        # the key carries the resolved specs: changing input_specs /
        # eval_output_specs after a first eval must re-specialize, not
        # silently reuse the stale layout
        key = (tuple((tuple(np.shape(a)), str(getattr(a, "dtype", "?")))
                     for a in input_arrays),
               repr(self._eval_input_specs(len(args))),
               repr(getattr(self, "eval_output_specs", None)),
               repr(getattr(self, "eval_output_reduce", None)))
        rec = self._eval_steps.get(key)
        fresh = rec is None
        try:
            if fresh:
                rec = self._build_eval(args)
                self._eval_steps[key] = rec
            if rec is NotImplemented:
                return NotImplemented
            from jax.sharding import NamedSharding
            place = self._place_mesh
            state_arrays = [place(t.data, NamedSharding(self._mesh, s))
                            for t, s in zip(self._state_list,
                                            rec["state_specs"])]
            placed = [place(a, NamedSharding(self._mesh, s))
                      for a, s in zip(input_arrays, rec["input_specs"])]
            leaves = rec["jit"](state_arrays, *placed)
        except Exception as e:
            if not fresh:
                raise
            # per-shard constraints beyond input divisibility (e.g. a
            # pipeline's microbatch assert on the LOCAL batch) surface
            # when the shard_map first traces — fall back to the
            # gather+eager path, which sees the global batch. Only
            # STRUCTURAL errors pin the signature; a transient failure
            # (device OOM, interrupted backend: RuntimeError family)
            # falls back for THIS call and retries on the next, so one
            # bad moment cannot silently degrade every later eval of
            # this shape to the gather path.
            import warnings
            structural = isinstance(
                e, (TypeError, ValueError, AssertionError,
                    NotImplementedError, IndexError, KeyError))
            if not structural:
                # RuntimeError family (XlaRuntimeError covers both a
                # transient OOM and a permanent lowering failure): allow
                # a bounded number of retries, then pin — an unbounded
                # retry would pay a full retrace+compile attempt on
                # EVERY eval of a signature that can never build
                fails = getattr(self, "_eval_fail_counts", None)
                if fails is None:
                    fails = self._eval_fail_counts = {}
                fails[key] = fails.get(key, 0) + 1
                structural = fails[key] >= 3
            if structural:
                self._eval_steps[key] = NotImplemented
            else:
                self._eval_steps.pop(key, None)
            warnings.warn(
                f"sharded eval unavailable for this signature "
                f"({type(e).__name__}: {e}); falling back to gathered "
                f"eager eval ({'pinned' if structural else 'will retry'})",
                stacklevel=3)
            return NotImplemented
        return _unflatten(rec["tree"], list(leaves), self.dev)

    def _unshard_state(self):
        """After mesh-sharded training the live state arrays span the mesh;
        gather them to the model device so eager (eval) ops can mix them
        with single-device inputs."""
        if self._state_list is None:
            return
        gather = {}
        for t in self._state_list:
            arr = t.data
            if hasattr(arr, "devices") and not isinstance(
                    arr, jax.core.Tracer) and len(arr.devices()) > 1:
                gather[id(t)] = (t, arr)
        if gather:
            # one batched cross-process gather for everything host-sharded
            from .tensor import to_host_tree
            hosts = to_host_tree({k: a for k, (_t, a) in gather.items()})
            for k, (t, _a) in gather.items():
                t.data = self.dev.put(hosts[k])

    def __call__(self, *args, **kwargs):
        if self._train:
            if kwargs:
                raise TypeError(
                    "train-mode model calls take positional tensors only "
                    "(the compiled step is positional); got keyword "
                    f"arguments {sorted(kwargs)}")
            return self._run_step(*args)
        if self._dist is not None or self._gspmd_active():
            # the sharded (shard_map) eval path needs a communicator for
            # its cross-shard reductions and consumes state in the TRAIN
            # layout — under FSDP that layout splits whole weights, so
            # eval instead gathers below and runs the eager forward
            if (not kwargs and self.graph_mode and args
                    and self._dist is not None
                    and self._fsdp_axis is None
                    and getattr(self, "_mesh", None) is not None
                    and all(isinstance(a, Tensor) for a in args)):
                res = self._run_eval(*args)
                if res is not NotImplemented:
                    return res
            # fallback (no mesh yet / odd batch / kwargs / FSDP): gather
            # state to the model device and run the eager forward
            self._unshard_state()
        prev = CTX.training
        CTX.training = False
        try:
            with self._policy_scope():
                res = self.forward(*args, **kwargs)
            # the eager path honors the same output contract as the
            # compiled one (a bf16-computed eval still hands back
            # output_dtype leaves)
            return self._cast_output_tree(res)
        finally:
            CTX.training = prev

    # -- persistence (reference model.py:244-330) --------------------------
    TENSOR_DICT_FILENAME = "/tensor_dict.npz"
    STATES_ATTR_FILENAME = "/states_attr.json"

    def compiled_step_info(self):
        """Perf-readiness audit of the latest compiled train step:
        re-lowers the recorded abstract signature (no step re-runs, no
        state copies) and returns

        - ``memory_analysis``: XLA's executable memory breakdown
          (per-device under a mesh);
        - ``donated_bytes``: bytes the executable aliases input→output —
          donation actually holding for the threaded state is THE
          invariant that keeps big-model training at 1× weights instead
          of 2×;
        - ``state_bytes``: logical bytes of the threaded state, for
          comparison (divide by the device count under a mesh);
        - ``hlo``: the optimized HLO text, for structural regression
          checks (host round-trips show up as callback custom-calls,
          lost sharding as missing collectives).

        Requires one compiled step to have run. No reference
        counterpart (closest: Graph::Debug's node dump).
        """
        # audit the signature that actually RAN last (a one-off
        # odd-shaped batch must not hijack the audit away from the main
        # training signature); fall back to any compiled rec
        rec = getattr(self, "_last_run_rec", None)
        if rec is None or rec.get("jit") is None or "avals" not in rec:
            rec = None
            for r in self._steps.values():
                if r.get("jit") is not None and "avals" in r:
                    rec = r
        if rec is None:
            raise RuntimeError(
                "compiled_step_info() needs a compiled step: run one "
                "training batch in graph mode first")
        fn = rec["jit"]
        state_avals, rng_aval, in_avals = rec["avals"]
        compiled = rec.get("audit_compiled")
        if compiled is None:
            if hasattr(fn, "lower"):
                compiled = fn.lower(state_avals, rng_aval,
                                    *in_avals).compile()
            else:                  # verbosity path already AOT-compiled
                compiled = fn
            rec["audit_compiled"] = compiled   # repeat audits are free
        ma = compiled.memory_analysis()
        hlo = compiled.as_text()
        state_bytes = sum(
            int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
            for a in state_avals)
        donated = getattr(ma, "alias_size_in_bytes", None)
        try:
            cost = compiled.cost_analysis()
        except Exception:       # cost analysis is backend-best-effort
            cost = None
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        return {"memory_analysis": ma, "donated_bytes": donated,
                "state_bytes": state_bytes, "hlo": hlo,
                "cost_analysis": cost,
                "n_traces": rec.get("n_traces"),
                "policy": self._policy.describe()
                if getattr(self, "_policy", None) is not None else None}

    def step_flops(self, compute=True):
        """FLOPs of one compiled training step, from XLA's cost
        analysis of the signature that last ran — the numerator of an
        honest MFU (``flops / step_seconds / chip_peak``), derived from
        the program actually executing rather than an analytic model.

        ``compute=False`` only consults an ALREADY-CACHED analysis
        (the verbosity>=2 path, a prior ``compiled_step_info()`` /
        ``step_flops()`` call) and returns None otherwise — the form
        the resilient trainer uses so MFU telemetry never pays a
        re-lower on the step path. Returns None when no step has
        compiled or the backend reports no flops."""
        rec = getattr(self, "_last_run_rec", None)
        if rec is None or rec.get("jit") is None or "avals" not in rec:
            rec = next((r for r in self._steps.values()
                        if r.get("jit") is not None and "avals" in r),
                       None)
        if rec is None:
            return None
        if "step_flops" in rec:
            return rec["step_flops"]
        if rec.get("fused_kinds"):
            # the executed program fuses optimizer updates into Pallas
            # custom calls, which XLA's cost analysis cannot see into
            # (on TPU they count ~0 flops; interpret mode counts the
            # emulation loop instead) — either way the analyzed number
            # would move vs the unfused program and MFU would lie. Lower
            # a REFERENCE twin of the same signature with every fused
            # kernel declined: fused and unfused programs then report
            # IDENTICAL FLOPs by construction. One extra trace+compile,
            # on the cost-analysis path only, never the step path
            # (compute=False still returns None until someone pays it).
            if not compute:
                return None
            raw = rec.get("raw_fn")
            if raw is None:
                return None
            state_avals, rng_aval, in_avals = rec["avals"]
            from .ops import fused_optim as _fused
            # a FRESH jit forces a fresh trace (the step's own jit would
            # serve its cached — fused — jaxpr from lower()); the traced
            # body mutates live state tensors and the device rng, so
            # snapshot and restore around it exactly like graph_debug
            backup = [(t, t.data) for t in (self._state_list or [])]
            rng_backup = self.dev._get_rng_state()
            # a fresh closure defeats jax's global trace cache (keyed on
            # the function object — reusing `raw` would serve the FUSED
            # jaxpr without ever re-running the body)
            def _twin_body(state_arrays, rng_key, *input_arrays):
                return raw(state_arrays, rng_key, *input_arrays)

            try:
                with _fused.force_reference():
                    twin = jax.jit(_twin_body, donate_argnums=(0,)).lower(
                        state_avals, rng_aval, *in_avals).compile()
                cost = twin.cost_analysis()
            except Exception:
                rec["step_flops"] = None
                return None
            finally:
                for t, d in backup:
                    t.data = d
                self.dev._set_rng_state(rng_backup)
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else None
            flops = None
            if isinstance(cost, dict):
                f = cost.get("flops")
                if f and f > 0:
                    flops = float(f)
            rec["step_flops"] = flops
            return flops
        cost = rec.get("cost")              # verbosity>=2 capture
        compiled = rec.get("audit_compiled")
        if cost is None:
            if compiled is None:
                if not compute:
                    return None             # nothing cached; stay cheap
                fn = rec["jit"]
                state_avals, rng_aval, in_avals = rec["avals"]
                try:
                    compiled = fn.lower(state_avals, rng_aval,
                                        *in_avals).compile() \
                        if hasattr(fn, "lower") else fn
                    rec["audit_compiled"] = compiled
                except Exception:
                    rec["step_flops"] = None
                    return None
            try:
                cost = compiled.cost_analysis()
            except Exception:
                cost = None
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        flops = None
        if isinstance(cost, dict):
            f = cost.get("flops")
            if f and f > 0:
                flops = float(f)
        rec["step_flops"] = flops
        return flops

    def profile_step(self, *args, record=True, events_out=None):
        """Run ONE training step under a ``jax.profiler`` trace and
        return ``(result, {fusion_name: (count, total_seconds)})`` —
        the measured per-fusion decomposition of the compiled step
        (reference per-node timing, scheduler.cc:240-298), on demand
        instead of only at device verbosity>=2. Rows are recorded into
        the metrics registry (``profile_fusion_seconds``/``_count``
        gauges) and folded into ``dev.time_profiling`` like the
        verbosity path's rows. Call with the same args as a training
        step; profiler failures degrade to an empty table
        (:func:`singa_tpu.profiling.measure_step_fusions`).

        ``record=False`` skips the registry publish (the device table
        still folds): the sampling profiler is then the ONE publisher,
        into ITS registry — without it every sampled step would set
        each gauge twice and a custom-registry profiler would leak the
        table into the default registry too.

        ``events_out``: a list that receives the capture's RAW
        timestamped trace events (``profiling.parse_trace_events``) —
        what ``observability.timeline.analyze`` buckets into the
        compute/collective/memcpy/host/idle step decomposition. Same
        single parse pass; an out-param so the 2-tuple return shape
        stays stable."""
        from . import profiling as _prof
        from .utils import force_completion

        def run_once():
            res = self(*args)
            # the trace must outlive the device work (see the
            # verbosity>=2 path): block on true completion of the raw
            # output arrays (Tensors are not jax pytree leaves)
            leaves = []
            _flatten(res, leaves)
            force_completion(leaves)
            return res

        result, table = _prof.measure_step_fusions(
            run_once, events_out=events_out)
        if record:
            _prof.record_fusion_metrics(table)
        for name, (cnt, tot) in table.items():
            c0, t0 = self.dev.time_profiling.get(
                f"fusion/{name}", (0, 0.0))
            self.dev.time_profiling[f"fusion/{name}"] = (c0 + cnt,
                                                         t0 + tot)
        return result, table

    def save_states(self, fpath, aux_states={}, quantize=None):  # noqa: B006 (parity)
        """Zip of params+states .npz and an attribute JSON, including
        optimizer aux states (reference model.py:244-295).

        ``quantize``: a quantized policy (or its name, e.g.
        ``"int8_weight_only"``) persists eligible weights as int8
        payloads plus per-channel ``quant-scale/`` fp32 sidecars (~4x
        smaller archive; lossy — fp32 masters stay untouched in
        memory). A model compiled under ``int8_weight_only`` quantizes
        its checkpoints by default; ``load_states`` dequantizes back
        into fp32 masters. A model already weight-quantized in place
        (``quant.quantize_params``) saves its int8 state as-is."""
        from . import mixed_precision as mp
        states = {k: v for k, v in self.get_states().items()}
        attr = {k: {"shape": list(v.shape), "dtype": str(v.dtype)}
                for k, v in states.items()}
        qpol = mp.resolve(quantize) if quantize is not None \
            else getattr(self, "_policy", None)
        if quantize is not None and (
                not isinstance(qpol, mp.QuantPolicy)
                or qpol.weight_quant is None):
            # an EXPLICIT quantize= that cannot be honored must fail,
            # not silently write a full-size fp32 archive the caller
            # believes is 4x smaller
            raise ValueError(
                f"save_states(quantize={quantize!r}): not a weight-"
                "quantizing policy (only 'int8_weight_only' persists "
                "int8 payloads; fp8/QAT presets quantize compute, not "
                "storage)")
        do_quant = (isinstance(qpol, mp.QuantPolicy)
                    and qpol.weight_quant is not None
                    and not getattr(self, "_quant_pairs", None)
                    and (quantize is not None
                         or getattr(qpol, "quantize_checkpoints",
                                    False)))
        if do_quant:
            # the archive self-describes as quantized: the preset
            # round-trips through meta/precision_policy
            attr["meta/precision_policy"] = qpol.describe()
        elif getattr(self, "_policy", None) is not None:
            # self-describing checkpoints: params in the archive are the
            # POLICY'S MASTERS (fp32 under bf16_mixed) — record the
            # policy so a reader can tell masters from a pure-16-bit run
            attr["meta/precision_policy"] = self._policy.describe()
        from .tensor import to_host_tree

        def _portable(a):
            # bf16 isn't a stock-numpy dtype: inside the .npz it would
            # round-trip as an uncastable raw-void array. Store it as
            # (lossless) f32 — attr records the true dtype, and
            # copy_from_numpy casts back to the param's dtype on load.
            a = np.asarray(a)
            return a.astype(np.float32) if str(a.dtype) == "bfloat16" \
                else a

        # one batched cross-process gather for every host-sharded param
        arrays = {k: _portable(v) for k, v in to_host_tree(
            {k: v.data for k, v in states.items()}).items()}
        if do_quant:
            from .quant import core as _qcore
            for k, t in states.items():
                if not _qcore.eligible(t):
                    continue
                q, s = _qcore.quantize_int8(
                    arrays[k], _qcore.channel_axis(np.shape(arrays[k])))
                arrays[k] = np.asarray(q)
                arrays[_qcore.SCALE_PREFIX + k] = np.asarray(s)
                attr[k] = {"shape": list(np.shape(arrays[k])),
                           "dtype": "int8",
                           "quant": {"kind": "int8",
                                     "orig_dtype": attr[k]["dtype"]}}
                attr[_qcore.SCALE_PREFIX + k] = {
                    "shape": list(np.shape(arrays[_qcore.SCALE_PREFIX
                                                  + k])),
                    "dtype": "float32", "quant_scale": True}
        opt = getattr(self, "optimizer", None)
        if opt is not None and hasattr(opt, "get_states"):
            for k, v in opt.get_states().items():
                arrays[f"optimizer/{k}"] = _portable(v)
                attr[f"optimizer/{k}"] = {
                    "shape": list(np.shape(v)),
                    "dtype": str(np.asarray(v).dtype),
                    "optimizer": True}
        for k, v in aux_states.items():
            raw = np.asarray(v.numpy() if isinstance(v, Tensor) else v)
            # attr records the TRUE dtype, taken before the portable-f32
            # conversion, so load_states can cast bf16 aux back
            attr[f"aux/{k}"] = {"shape": list(raw.shape),
                                "dtype": str(raw.dtype),
                                "aux": True}
            arrays[f"aux/{k}"] = _portable(raw)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        buf.seek(0)
        with zipfile.ZipFile(fpath, "w") as zf:
            zf.writestr(self.TENSOR_DICT_FILENAME.strip("/"), buf.read())
            zf.writestr(self.STATES_ATTR_FILENAME.strip("/"),
                        json.dumps(attr))

    def load_states(self, fpath):
        """Restore params/states (+ optimizer aux) and return aux states
        (reference model.py:297-330)."""
        with zipfile.ZipFile(fpath, "r") as zf:
            attr = json.loads(zf.read(
                self.STATES_ATTR_FILENAME.strip("/")))
            with zf.open(self.TENSOR_DICT_FILENAME.strip("/")) as f:
                data = np.load(io.BytesIO(f.read()))
                arrays = {k: data[k] for k in data.files}

        def _true_dtype(k, a):
            # the archive stores bf16 as portable f32 (save_states
            # _portable); attr records the real dtype — cast back here
            # so every consumer (fresh optimizer aux included) sees the
            # dtype that was saved, not the transport representation
            want = attr.get(k, {}).get("dtype")
            if want and str(a.dtype) != want:
                if want == "bfloat16":
                    # numpy only knows bfloat16 once ml_dtypes (shipped
                    # with jax) has registered it — import explicitly so
                    # the cast can't silently hand consumers f32 arrays
                    try:
                        import ml_dtypes  # noqa: F401
                    except ImportError:
                        pass  # astype below fails loudly via the warning
                try:
                    return a.astype(np.dtype(want))
                except TypeError:
                    import warnings
                    warnings.warn(
                        f"load_states: recorded dtype {want!r} for {k!r} "
                        f"cannot be restored (keeping {a.dtype})",
                        stacklevel=2)
                    return a
            return a

        arrays = {k: _true_dtype(k, v) for k, v in arrays.items()}
        model_states = {k: v for k, v in arrays.items()
                        if not k.startswith(("optimizer/", "aux/"))}
        my_states = self.get_states()
        # quantized archive (save_states(quantize=...)): int8 payloads
        # carry a quant-scale/ sidecar — restoring into fp32 masters
        # dequantizes here; restoring into an equally-quantized model
        # copies payload and scale verbatim (its live tensors are int8,
        # so the dequant branch never fires for them)
        from .quant.core import SCALE_PREFIX as _QSCALE
        from .quant.core import dequantize_entry
        q_scales = {k[len(_QSCALE):]: v for k, v in arrays.items()
                    if k.startswith(_QSCALE)}
        for k, v in model_states.items():
            if k in my_states:
                lt = my_states[k]
                if (k in q_scales and np.dtype(v.dtype) == np.int8
                        and jnp.issubdtype(lt.dtype, jnp.floating)):
                    v = dequantize_entry(v, q_scales[k])
                lt.copy_from_numpy(v)
        opt = getattr(self, "optimizer", None)
        if opt is not None and hasattr(opt, "set_states"):
            opt_states = {k[len("optimizer/"):]: v
                          for k, v in arrays.items()
                          if k.startswith("optimizer/")}
            if opt_states:
                opt.set_states(opt_states)
                if hasattr(opt, "announce_aux_specs"):
                    # restored momentum/moments shard like their params
                    opt.announce_aux_specs(my_states)
        self._invalidate_compiled()
        return {k[len("aux/"):]: Tensor(data=v, requires_grad=False)
                for k, v in arrays.items() if k.startswith("aux/")}
