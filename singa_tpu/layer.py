"""Layer API: deferred shape-inferring initialization + hierarchical params.

Capability parity with the reference layer system (python/singa/layer.py):
``initialize`` runs lazily on the first forward with the input's shapes
(LayerMeta, layer.py:29-73), parameters/states are exposed as hierarchical
name→Tensor dicts (layer.py:75+), and the same layer zoo is provided.

TPU-first: layers hold Tensors whose payloads are jax.Arrays; a layer's
forward builds tape ops that trace under jit. Conv/BN/Pool/RNN use the
Handle configs from ``singa_tpu.ops`` which lower to MXU-friendly lax
primitives.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from . import autograd
from .autograd_base import CTX
from .tensor import Tensor
from .ops.conv import ConvHandle
from .ops.batchnorm import BatchNormHandle
from .ops.pooling import PoolingHandle
from .ops.rnn import CudnnRNNHandle


class Layer:
    """Base layer (reference python/singa/layer.py Layer)."""

    sep = "."

    def __init__(self):
        self.name = self.__class__.__name__
        self._initialized = False
        self._parent = None

    # -- naming / hierarchy ----------------------------------------------
    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        if isinstance(value, Layer):
            value.name = name
            value._parent = self
        elif isinstance(value, (list, tuple)):
            for i, v in enumerate(value):
                if isinstance(v, Layer):
                    v.name = f"{name}{self.sep}{i}"
                    v._parent = self
        object.__setattr__(self, name, value)

    def _sublayers(self):
        out = []
        for k, v in vars(self).items():
            if k.startswith("_") or k == "name":
                continue
            if isinstance(v, Layer):
                out.append((v.name, v))
            elif isinstance(v, (list, tuple)):
                out.extend((s.name, s) for s in v if isinstance(s, Layer))
        return out

    # -- lifecycle --------------------------------------------------------
    def initialize(self, *input):  # noqa: A002
        pass

    def forward(self, *input):  # noqa: A002
        raise NotImplementedError

    def ensure_initialized(self, *args, **kwargs):
        """Run the deferred, shape-inferring init (if still pending)
        WITHOUT executing forward (reference LayerMeta: graph is
        disabled during init so param creation is not taped). Under
        an abstract dry run (Model._abstract_call's eval_shape) the
        compile-time-eval scope makes param creation execute
        CONCRETELY — inits read only static shapes and concrete rng
        keys, so real weights materialise while the surrounding
        forward stays traced. Callers that need params but not outputs
        (e.g. the fused CE head consuming ``head.W`` directly) use this
        to avoid materialising a full forward's activations."""
        if self._initialized:
            return
        import jax as _jax
        prev = CTX.training
        CTX.training = False
        try:
            with _jax.ensure_compile_time_eval():
                self.initialize(*args, **kwargs)
        finally:
            CTX.training = prev
        self._initialized = True

    def __call__(self, *args, **kwargs):
        self.ensure_initialized(*args, **kwargs)
        return self.forward(*args, **kwargs)

    @property
    def training(self):
        return CTX.training

    # -- params / states ---------------------------------------------------
    def _own_params(self):
        """Override: dict of local param name -> Tensor."""
        return {}

    def _own_states(self):
        """Override: dict of local state name -> Tensor (includes params)."""
        return dict(self._own_params())

    def _own(self, which):
        """_own_params/_own_states tolerant of deferred init: a layer
        whose ``initialize`` has not run yet simply has no state."""
        try:
            return which()
        except AttributeError:
            return {}

    def get_params(self):
        params = {f"{self.name}{self.sep}{k}": v
                  for k, v in self._own(self._own_params).items()}
        for _, sub in self._sublayers():
            for k, v in sub.get_params().items():
                params[f"{self.name}{self.sep}{k}"] = v
        return params

    def set_params(self, params):
        for k, v in self._own(self._own_params).items():
            full = f"{self.name}{self.sep}{k}"
            if full in params:
                v.copy_from(params[full])
        for _, sub in self._sublayers():
            sub.set_params({k[len(self.name) + 1:]: v
                            for k, v in params.items()
                            if k.startswith(self.name + self.sep)})

    def get_states(self):
        states = {f"{self.name}{self.sep}{k}": v
                  for k, v in self._own(self._own_states).items()}
        for _, sub in self._sublayers():
            for k, v in sub.get_states().items():
                states[f"{self.name}{self.sep}{k}"] = v
        return states

    def set_states(self, states):
        for k, v in self._own(self._own_states).items():
            full = f"{self.name}{self.sep}{k}"
            if full in states:
                v.copy_from(states[full])
        for _, sub in self._sublayers():
            sub.set_states({k[len(self.name) + 1:]: v
                            for k, v in states.items()
                            if k.startswith(self.name + self.sep)})

    def device_check(self, *tensors):
        devs = [t.device for t in tensors if isinstance(t, Tensor)]
        return devs[0] if devs else None

    def register_layers(self, *layers):
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            layers = layers[0]
        self._registered = list(layers)


def _param(shape, device, init="zeros", dtype=jnp.float32):
    # deferred inits pass the INPUT's dtype here; under an active
    # precision policy the master must not follow a 16-bit activation —
    # ops cast params down at their use sites instead (mixed_precision)
    from .mixed_precision import param_dtype as _policy_param_dtype
    dtype = _policy_param_dtype(dtype)
    t = Tensor(shape=shape, device=device, dtype=dtype,
               requires_grad=True, stores_grad=True)
    if init == "ones":
        t.data = jnp.ones(shape, dtype=dtype)
    return t


class Linear(Layer):
    """y = xW + b (reference layer.Linear:287)."""

    def __init__(self, out_features, *args, bias=True):
        super().__init__()
        self.out_features = out_features
        # legacy two-positional form Linear(in_features, out_features[, bias])
        # (reference layer.py:305-312); in_features is re-inferred at init.
        # A bool second positional is the new-API bias, not out_features.
        if len(args) > 0 and not isinstance(args[0], bool):
            self.out_features = args[0]
            if len(args) > 1:
                bias = args[1]
        elif len(args) > 0:
            bias = args[0]
        self.bias = bias

    def initialize(self, x):
        self.in_features = x.shape[-1]
        dev = x.device
        self.W = _param((self.in_features, self.out_features), dev,
                        dtype=x.dtype)
        std = math.sqrt(2.0 / (self.in_features + self.out_features))
        self.W.gaussian(0.0, std)
        if self.bias:
            self.b = _param((self.out_features,), dev, dtype=x.dtype)

    def forward(self, x):
        y = autograd.matmul(x, self.W)
        if self.bias:
            y = autograd.add_bias(y, self.b, axis=0)
        return y

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class Gemm(Layer):
    """onnx-style Gemm layer (reference layer.Gemm)."""

    def __init__(self, nb_kernels, alpha=1.0, beta=1.0, transA=False,
                 transB=True, bias=True, bias_shape=None):
        super().__init__()
        self.nb_kernels = nb_kernels
        self.alpha, self.beta = alpha, beta
        self.transA, self.transB = int(transA), int(transB)
        self.bias = bias
        self.bias_shape = bias_shape

    def initialize(self, x):
        dev = x.device
        feat = x.shape[0] if self.transA else x.shape[-1]
        w_shape = (self.nb_kernels, feat) if self.transB \
            else (feat, self.nb_kernels)
        self.W = _param(w_shape, dev)
        std = math.sqrt(2.0 / (feat + self.nb_kernels))
        self.W.gaussian(0.0, std)
        if self.bias:
            self.b = _param(self.bias_shape or (1, self.nb_kernels), dev)

    def forward(self, x):
        if self.bias:
            return autograd.gemm(x, self.W, self.b, self.alpha, self.beta,
                                 self.transA, self.transB)
        return autograd.gemm(x, self.W, None, self.alpha, self.beta,
                             self.transA, self.transB)

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class Embedding(Layer):
    """Token embedding lookup (reference layer.Embedding)."""

    def __init__(self, input_dim, output_dim, initializer="gaussian"):
        super().__init__()
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.initializer = initializer

    def initialize(self, x):
        self.W = _param((self.input_dim, self.output_dim), x.device)
        if self.initializer == "gaussian":
            self.W.gaussian(0.0, 0.02)
        else:
            self.W.uniform(-0.05, 0.05)

    def forward(self, x):
        return autograd.embedding(x, self.W)

    def _own_params(self):
        return {"W": self.W}


class Conv2d(Layer):
    """2-D convolution layer (reference layer.Conv2d:508)."""

    def __init__(self, nb_kernels, kernel_size, *args, stride=1, padding=0,
                 dilation=1, group=1, bias=True, pad_mode="NOTSET",
                 activation="NOTSET", space_to_depth=False):
        super().__init__()
        # legacy form Conv2d(in_ch, nb_kernels, k[, stride[, padding]])
        # (reference layer.py:552-560); in_channels is inferred at init
        if len(args) > 0:
            nb_kernels = kernel_size
            kernel_size = args[0]
        if len(args) > 1:
            stride = args[1]
        if len(args) > 2:
            padding = args[2]
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.group = group
        self.bias = bias
        self.pad_mode = pad_mode
        self.activation = activation
        self.space_to_depth = space_to_depth

    def initialize(self, x):
        from .ops.layout import channel_axis
        self.in_channels = x.shape[channel_axis(len(x.shape))]
        dev = x.device
        ks = self.kernel_size if isinstance(self.kernel_size, (tuple, list)) \
            else (self.kernel_size, self.kernel_size)
        w_shape = (self.nb_kernels, self.in_channels // self.group, *ks)
        self.W = _param(w_shape, dev, dtype=x.dtype)
        # reference layer.py:636-638: glorot-style over fan_in+fan_out so
        # channel-reducing convs (e.g. squeeze layers) don't inflate
        # variance; fan_out is per-group so depthwise convs aren't
        # under-initialized by the total channel count
        std = math.sqrt(
            2.0 / (w_shape[1] * ks[0] * ks[1]
                   + self.nb_kernels / self.group))
        self.W.gaussian(0.0, std)
        if self.bias:
            self.b = _param((self.nb_kernels,), dev, dtype=x.dtype)
        pad = self.padding
        pad_mode = None
        if self.pad_mode == "SAME_UPPER":
            pad_mode = "SAME"
        elif self.pad_mode == "SAME_LOWER":
            pad_mode = "SAME_LOWER"  # lax places the odd pad at the start
        elif self.pad_mode == "VALID":
            pad_mode = "VALID"
        self.handle = ConvHandle(x, ks, self.stride, pad,
                                 self.in_channels, self.nb_kernels,
                                 self.bias, self.group, pad_mode,
                                 dilation=self.dilation,
                                 space_to_depth=self.space_to_depth)

    def forward(self, x):
        from .ops.conv import conv2d
        y = conv2d(self.handle, x, self.W, self.b if self.bias else None)
        if self.activation == "RELU":
            y = autograd.relu(y)
        return y

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class ConvTranspose2d(Layer):
    """2-D transposed convolution (the ConvTranspose capability the
    reference exposes through its ONNX backend, python/singa/sonnx.py).
    Weight layout (C_in, C_out/group, kH, kW), ONNX/torch convention."""

    def __init__(self, nb_kernels, kernel_size, stride=1, padding=0,
                 output_padding=0, dilation=1, group=1, bias=True):
        super().__init__()
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_padding = output_padding
        self.dilation = dilation
        self.group = group
        self.bias = bias

    def initialize(self, x):
        from .ops.conv import ConvTransposeHandle
        from .ops.layout import channel_axis
        self.in_channels = x.shape[channel_axis(len(x.shape))]
        dev = x.device
        ks = self.kernel_size if isinstance(self.kernel_size, (tuple, list)) \
            else (self.kernel_size, self.kernel_size)
        w_shape = (self.in_channels, self.nb_kernels // self.group, *ks)
        self.W = _param(w_shape, dev, dtype=x.dtype)
        # transpose-conv weight is (in, out/group, kh, kw): the fan_in term
        # is the per-group INPUT channels (w_shape[0]/group), not w_shape[1]
        std = math.sqrt(
            2.0 / ((self.in_channels // self.group) * ks[0] * ks[1]
                   + self.nb_kernels / self.group))
        self.W.gaussian(0.0, std)
        if self.bias:
            self.b = _param((self.nb_kernels,), dev, dtype=x.dtype)
        self.handle = ConvTransposeHandle(
            x, ks, self.stride, self.padding, self.in_channels,
            self.nb_kernels, self.bias, self.group,
            dilation=self.dilation, output_padding=self.output_padding)

    def forward(self, x):
        from .ops.conv import conv_transpose2d
        return conv_transpose2d(self.handle, x, self.W,
                                self.b if self.bias else None)

    def _own_params(self):
        p = {"W": self.W}
        if self.bias:
            p["b"] = self.b
        return p


class SeparableConv2d(Layer):
    """Depthwise + pointwise conv (reference layer.SeparableConv2d:740)."""

    def __init__(self, nb_kernels, kernel_size, *args, stride=1, padding=0,
                 bias=False):
        super().__init__()
        # legacy form SeparableConv2d(in_ch, nb_kernels, k[, stride[, pad]])
        if len(args) > 0:
            nb_kernels = kernel_size
            kernel_size = args[0]
        if len(args) > 1:
            stride = args[1]
        if len(args) > 2:
            padding = args[2]
        self.depthwise = None
        self.pointwise = None
        self.nb_kernels = nb_kernels
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.bias = bias

    def initialize(self, x):
        from .ops.layout import channel_axis
        in_channels = x.shape[channel_axis(len(x.shape))]
        self.depthwise = Conv2d(in_channels, self.kernel_size,
                                stride=self.stride, padding=self.padding,
                                group=in_channels, bias=self.bias)
        self.pointwise = Conv2d(self.nb_kernels, 1, bias=self.bias)
        self.depthwise.name = f"{self.name}{self.sep}depthwise"
        self.pointwise.name = f"{self.name}{self.sep}pointwise"

    def forward(self, x):
        return self.pointwise(self.depthwise(x))

    def get_params(self):
        out = {}
        for sub in (self.depthwise, self.pointwise):
            out.update(sub.get_params())
        return out

    def set_params(self, params):
        for sub in (self.depthwise, self.pointwise):
            sub.set_params(params)

    def get_states(self):
        return self.get_params()

    def set_states(self, states):
        self.set_params(states)


class BatchNorm2d(Layer):
    """BN over channel axis (reference layer.BatchNorm2d:802)."""

    def __init__(self, *args, momentum=0.9, eps=1e-5, freeze_stats=False):
        super().__init__()
        # legacy form BatchNorm2d(channels[, momentum]); channels is
        # re-inferred from the input at initialize time. A lone float
        # positional is a momentum (the pre-channel-arg API).
        if len(args) == 1 and isinstance(args[0], float):
            momentum = args[0]
        elif len(args) > 1:
            momentum = args[1]
        self.momentum = momentum
        self.eps = eps
        # caffe use_global_stats: always normalise with running stats
        self.freeze_stats = freeze_stats

    def initialize(self, x):
        from .ops.layout import channel_axis
        self.channels = x.shape[channel_axis(len(x.shape))]
        dev = x.device
        c = (self.channels,)
        self.scale = _param(c, dev, init="ones")
        self.bias = _param(c, dev)
        self.running_mean = Tensor(shape=c, device=dev, requires_grad=False)
        self.running_var = Tensor(shape=c, device=dev, requires_grad=False)
        self.running_var.data = jnp.ones(c, dtype=jnp.float32,
                                         device=dev.jax_device)
        self.handle = BatchNormHandle(self.momentum, x, self.eps)

    def forward(self, x):
        from .ops.batchnorm import batchnorm_2d
        return batchnorm_2d(self.handle, x, self.scale, self.bias,
                            self.running_mean, self.running_var,
                            freeze_stats=self.freeze_stats)

    def _own_params(self):
        return {"scale": self.scale, "bias": self.bias}

    def _own_states(self):
        return {"scale": self.scale, "bias": self.bias,
                "running_mean": self.running_mean,
                "running_var": self.running_var}


class LayerNorm(Layer):
    """Layer normalisation over the trailing dim (TPU extension: the
    transformer family needs it; not in the reference layer zoo)."""

    def __init__(self, eps=1e-5):
        super().__init__()
        self.eps = eps

    def initialize(self, x):
        d = (x.shape[-1],)
        self.scale = _param(d, x.device, init="ones")
        self.bias = _param(d, x.device)

    def forward(self, x):
        from .autograd import _LayerNorm
        return _LayerNorm(self.eps)(x, self.scale, self.bias)

    def _own_params(self):
        return {"scale": self.scale, "bias": self.bias}


class Pooling2d(Layer):
    """Base pooling layer (reference layer.Pooling2d:891)."""

    def __init__(self, kernel_size, stride=None, padding=0, is_max=True):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride if stride is not None else kernel_size
        self.padding = padding
        self.is_max = is_max

    def initialize(self, x):
        self.handle = PoolingHandle(x, self.kernel_size, self.stride,
                                    self.padding, self.is_max)

    def forward(self, x):
        from .ops.pooling import pooling_2d
        return pooling_2d(self.handle, x)


class MaxPool2d(Pooling2d):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__(kernel_size, stride, padding, True)


class AvgPool2d(Pooling2d):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__(kernel_size, stride, padding, False)


class MaxPool1d(Pooling2d):
    def __init__(self, kernel_size, stride=None, padding=0):
        if stride is None:
            stride = kernel_size
        super().__init__((1, kernel_size), (1, stride), (0, padding), True)


class AvgPool1d(Pooling2d):
    def __init__(self, kernel_size, stride=None, padding=0):
        if stride is None:
            stride = kernel_size
        super().__init__((1, kernel_size), (1, stride), (0, padding), False)


class RNN_Base(Layer):
    def step_forward(self, x, h, c=None):
        raise NotImplementedError


class RNN(RNN_Base):
    """Pure-tape vanilla RNN over a list of per-step tensors
    (reference layer.RNN:1129)."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 nonlinearity="tanh", bias=True, batch_first=False,
                 dropout=0, bidirectional=False):
        super().__init__()
        assert num_layers == 1 and not bidirectional
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.nonlinearity = nonlinearity
        self.bias = bias

    def initialize(self, xs, h0):
        dev = h0.device
        k = 1.0 / math.sqrt(self.hidden_size)
        self.Wx = _param((self.input_size, self.hidden_size), dev)
        self.Wh = _param((self.hidden_size, self.hidden_size), dev)
        self.b = _param((self.hidden_size,), dev)
        for p in (self.Wx, self.Wh, self.b):
            p.uniform(-k, k)

    def step_forward(self, x, h):
        y = autograd.add(autograd.matmul(x, self.Wx),
                         autograd.matmul(h, self.Wh))
        y = autograd.add_bias(y, self.b, axis=0)
        return autograd.tanh(y) if self.nonlinearity == "tanh" \
            else autograd.relu(y)

    def forward(self, xs, h0):
        out = []
        h = h0
        for x in xs:
            h = self.step_forward(x, h)
            out.append(h)
        return out, h

    def _own_params(self):
        return {"Wx": self.Wx, "Wh": self.Wh, "b": self.b}


class LSTM(RNN_Base):
    """Pure-tape LSTM over a list of per-step tensors
    (reference layer.LSTM:1229)."""

    def __init__(self, input_size, hidden_size, num_layers=1, bias=True,
                 batch_first=False, dropout=0, bidirectional=False):
        super().__init__()
        assert num_layers == 1 and not bidirectional
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.bias = bias

    def initialize(self, xs, hc):
        h0, _ = hc
        dev = h0.device
        k = 1.0 / math.sqrt(self.hidden_size)
        self.Wx = _param((self.input_size, 4 * self.hidden_size), dev)
        self.Wh = _param((self.hidden_size, 4 * self.hidden_size), dev)
        self.b = _param((4 * self.hidden_size,), dev)
        for p in (self.Wx, self.Wh, self.b):
            p.uniform(-k, k)

    def step_forward(self, x, h, c):
        g = autograd.add_bias(
            autograd.add(autograd.matmul(x, self.Wx),
                         autograd.matmul(h, self.Wh)), self.b, axis=0)
        H = self.hidden_size
        i = autograd.sigmoid(autograd.slice(g, [0], [H], [1]))
        f = autograd.sigmoid(autograd.slice(g, [H], [2 * H], [1]))
        gg = autograd.tanh(autograd.slice(g, [2 * H], [3 * H], [1]))
        o = autograd.sigmoid(autograd.slice(g, [3 * H], [4 * H], [1]))
        c_new = autograd.add(autograd.mul(f, c), autograd.mul(i, gg))
        h_new = autograd.mul(o, autograd.tanh(c_new))
        return h_new, c_new

    def forward(self, xs, hc):
        h, c = hc
        out = []
        for x in xs:
            h, c = self.step_forward(x, h, c)
            out.append(h)
        return out, (h, c)

    def _own_params(self):
        return {"Wx": self.Wx, "Wh": self.Wh, "b": self.b}


class CudnnRNN(Layer):
    """Packed-weight fused RNN on lax.scan (reference layer.CudnnRNN:1550 —
    the name is kept for drop-in parity; nothing cuDNN remains)."""

    def __init__(self, hidden_size, activation="tanh", num_layers=1,
                 bias=True, batch_first=False, dropout=0,
                 bidirectional=False, rnn_mode="lstm", use_mask=False,
                 return_sequences=True):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        self.num_layers = num_layers
        self.bias = bias
        self.batch_first = batch_first
        self.dropout = dropout
        self.bidirectional = bidirectional
        self.rnn_mode = rnn_mode if rnn_mode != "vanilla" else activation
        self.use_mask = use_mask
        self.return_sequences = return_sequences

    def initialize(self, x, hx=None, cx=None, seq_lengths=None):
        xs = x.shape if not self.batch_first \
            else (x.shape[1], x.shape[0], x.shape[2])
        self.handle = CudnnRNNHandle(
            type("S", (), {"shape": xs}), self.hidden_size,
            mode=self.rnn_mode, num_layers=self.num_layers, bias=self.bias,
            dropout=self.dropout, bidirectional=self.bidirectional)
        self.W = _param((self.handle.weights_size,), x.device,
                        dtype=x.dtype)
        k = 1.0 / math.sqrt(self.hidden_size)
        self.W.uniform(-k, k)

    def forward(self, x, hx=None, cx=None, seq_lengths=None):
        from .ops.rnn import rnn_op
        h = self.handle
        if self.batch_first:
            x = autograd.transpose(x, (1, 0, 2))
        B = x.shape[1]
        shape = (h.num_layers * h.num_directions, B, h.hidden_size)
        if hx is None:
            hx = Tensor(shape=shape, device=x.device, dtype=x.dtype,
                        requires_grad=False)
        if cx is None:
            cx = Tensor(shape=shape, device=x.device, dtype=x.dtype,
                        requires_grad=False)
        y, hy, cy = rnn_op(h, x, hx, cx, self.W, seq_lengths)
        if self.batch_first:
            y = autograd.transpose(y, (1, 0, 2))
        if not self.return_sequences:
            y = autograd.make_slice(y, 0 if not self.batch_first else 1,
                                    y.shape[0 if not self.batch_first else 1]
                                    - 1)
            y = autograd.squeeze(y, 0 if not self.batch_first else 1)
        return y, hy, cy

    def _own_params(self):
        return {"W": self.W}


# ---- stateless wrappers ---------------------------------------------------

class ReLU(Layer):
    def forward(self, x):
        return autograd.relu(x)


class Sigmoid(Layer):
    def forward(self, x):
        return autograd.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return autograd.tanh(x)


class Add(Layer):
    def forward(self, a, b):
        return autograd.add(a, b)


class Flatten(Layer):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return autograd.flatten(x, self.axis)


class SoftMax(Layer):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return autograd.softmax(x, self.axis)


class SoftMaxCrossEntropy(Layer):
    def forward(self, x, t):
        return autograd.softmax_cross_entropy(x, t)


class MeanSquareError(Layer):
    def forward(self, x, t):
        return autograd.mse_loss(x, t)


class CrossEntropy(Layer):
    def forward(self, x, t):
        return autograd.cross_entropy(x, t)


class BinaryCrossEntropy(Layer):
    def forward(self, x, t):
        return autograd.binary_cross_entropy(x, t)


class LRN(Layer):
    """Across-channel local response normalisation
    (reference src/model/layer/lrn.cc:150)."""

    def __init__(self, size=5, alpha=1e-4, beta=0.75, k=1.0):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return autograd.lrn(x, self.size, self.alpha, self.beta, self.k)


class Dropout(Layer):
    def __init__(self, ratio=0.5):
        super().__init__()
        self.ratio = ratio

    def forward(self, x):
        return autograd.dropout(x, self.ratio)


class FusedCEHead(Layer):
    """LM classifier head fused with softmax-cross-entropy: the
    (tokens, vocab) logits matrix — usually the biggest single HBM
    allocation of large-vocab LM training — is never materialised;
    loss AND grads are computed in vocab chunks with an online
    logsumexp (ops/losses.fused_ce_head). Call as
    ``loss = head(hidden, target_ids)``."""

    def __init__(self, vocab_size, chunk=8192):
        super().__init__()
        self.vocab_size = vocab_size
        self.chunk = chunk

    def initialize(self, h, ids):
        self.W = _param((h.shape[-1], self.vocab_size), h.device)
        self.W.gaussian(0.0, 0.02)
        self.b = _param((self.vocab_size,), h.device)

    def forward(self, h, ids):
        from .ops.losses import fused_softmax_cross_entropy
        return fused_softmax_cross_entropy(h, self.W, self.b, ids,
                                           self.chunk)

    def _own_params(self):
        return {"W": self.W, "b": self.b}


class FusedCEHeadStage(FusedCEHead):
    """:class:`FusedCEHead` shaped as the TERMINAL stage of a
    heterogeneous 1F1B pipeline: ``forward(h)`` passes hidden states
    through unchanged while the pipeline's in-schedule loss calls
    ``.loss(o, y)`` (raw arrays) against this stage's own packed params —
    the (tokens, vocab) logits then exist nowhere: not in HBM (fused
    scan) and not on the pipe wire (a 1F1B last stage's output never
    rides it). Use as ``HeteroPipeline1F1B([..., head], head.loss,
    n_micro)``; the head params live in the stage's flat pack like any
    other stage params, so the schedule's own vjp delivers their
    gradients."""

    def initialize(self, h):
        # Linear's glorot std and draw count (FusedCEHead uses 0.02): a
        # pipeline with this stage must be parity-checkable against the
        # same pipeline with a dense layer.Linear head, which requires
        # identical rng draws in identical order
        self.W = _param((h.shape[-1], self.vocab_size), h.device)
        self.W.gaussian(0.0, math.sqrt(2.0 / (h.shape[-1]
                                              + self.vocab_size)))
        self.b = _param((self.vocab_size,), h.device)

    def forward(self, h):
        return h

    def loss(self, o, y):
        """Per-microbatch in-schedule loss: ``o`` (mb, S, D) hidden
        array, ``y`` (mb, S) float-encoded target ids -> f32 scalar."""
        from .ops.losses import fused_ce_head
        return fused_ce_head(o.reshape(-1, o.shape[-1]), self.W.data,
                             self.b.data, y.reshape(-1), self.chunk)


class Cat(Layer):
    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def forward(self, xs):
        return autograd.cat(xs, self.axis)


class Reshape(Layer):
    def __init__(self, shape=None):
        super().__init__()
        self.shape = shape

    def forward(self, x, shape=None):
        return autograd.reshape(x, shape if shape is not None else self.shape)
