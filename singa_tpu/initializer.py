"""Parameter initializers (parity: reference python/singa/initializer.py).

All fillers mutate the given Tensor in place via the device's functional
PRNG (jax.random), replacing curand host-side filling.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor


def _compute_fans(shape):
    """fan_in/fan_out following the reference's conv-aware convention
    (initializer.py:_compute_fans)."""
    shape = tuple(shape)
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) in (3, 4, 5):
        receptive = int(np.prod(shape[2:]))
        fan_in = shape[1] * receptive
        fan_out = shape[0] * receptive
    else:
        fan_in = fan_out = int(np.sqrt(np.prod(shape)))
    return float(fan_in), float(fan_out)


def _random_fill(t: Tensor, mode: str, scale: float, distribution: str):
    fan_in, fan_out = _compute_fans(t.shape)
    n = {"fan_in": fan_in, "fan_out": fan_out,
         "fan_avg": (fan_in + fan_out) / 2.0}[mode]
    s = scale / max(1.0, n)
    if distribution == "normal":
        std = np.sqrt(s)
        t.gaussian(0.0, std)
    else:
        limit = np.sqrt(3.0 * s)
        t.uniform(-limit, limit)
    return t


def eye(t: Tensor):
    assert len(t.shape) == 2, "eye initializer needs a matrix"
    t.data = jnp.eye(t.shape[0], t.shape[1], dtype=t.dtype)
    return t


def orthogonal(t: Tensor):
    assert len(t.shape) == 2
    k = t.device.rand_key()
    a = jax.random.normal(k, t.shape, dtype=jnp.float32)
    q, r = jnp.linalg.qr(a if t.shape[0] >= t.shape[1] else a.T)
    q = q * jnp.sign(jnp.diag(r))
    if t.shape[0] < t.shape[1]:
        q = q.T
    t.data = q.astype(t.dtype)
    return t


def lecun_uniform(t: Tensor):
    return _random_fill(t, "fan_in", 1.0, "uniform")


def lecun_normal(t: Tensor):
    return _random_fill(t, "fan_in", 1.0, "normal")


def glorot_uniform(t: Tensor):
    return _random_fill(t, "fan_avg", 1.0, "uniform")


def glorot_normal(t: Tensor):
    return _random_fill(t, "fan_avg", 1.0, "normal")


def he_uniform(t: Tensor):
    return _random_fill(t, "fan_in", 2.0, "uniform")


def he_normal(t: Tensor):
    return _random_fill(t, "fan_in", 2.0, "normal")


# ---- deprecated reference aliases (initializer.py:gaussian/xavier/...) ----

def uniform(t: Tensor, fan_in=0, fan_out=0):
    avg = 1
    x = fan_in + fan_out
    if fan_in * fan_out == 0:
        x = max(fan_in, fan_out)
        avg = 2
    limit = float(np.sqrt(3.0 * avg / max(1, x)))
    t.uniform(-limit, limit)
    return t


def gaussian(t: Tensor, fan_in=0, fan_out=0):
    avg = 1
    x = fan_in + fan_out
    if fan_in * fan_out == 0:
        x = max(fan_in, fan_out)
        avg = 2
    std = float(np.sqrt(avg / max(1, x)))
    t.gaussian(0.0, std)
    return t


def xavier(t: Tensor):
    return glorot_uniform(t)


def glorot(t: Tensor):
    return glorot_normal(t)


def msra(t: Tensor):
    return he_normal(t)
