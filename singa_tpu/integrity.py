"""End-to-end integrity primitives: content digests, wire CRCs,
replica fingerprints.

Every byte this framework moves or stores used to be trusted blindly: a
flipped bit in a checkpoint shard, a corrupted frame on the control-plane
TCP wire, or a single replica silently diverging (SDC, non-deterministic
kernels) was either never detected or surfaced thousands of steps later
as an unexplainable NaN. This module hosts the shared primitives the
three integrity fronts are built on:

- **Content digests** (disk): :func:`tensor_digest` / :func:`digest_tree`
  / :func:`manifest_digest` produce tagged ``"crc32:<hex>:<nbytes>"``
  strings over a tensor's dtype+shape+raw bytes. ``checkpoint.py`` writes
  them as per-step sidecars and re-verifies on restore and scrub;
  ``snapshot.py``/``io.py`` write them beside Snapshot/BinFile records.
- **Wire framing** (network): :func:`seal_frame` / :func:`open_frame`
  wrap a message payload in a magic + version + CRC + length header, so
  a corrupted or truncated control-plane frame raises a typed
  :class:`IntegrityError` instead of feeding garbage into protocol
  parsing (``network.py`` adds the max-length guard on receive).
- **Replica fingerprints** (compute): :func:`state_fingerprint` is the
  host-side digest ranks exchange over the cluster control plane to
  agree their parameters have not forked;
  :func:`replica_buffer_mismatches` compares the per-device buffers of a
  REPLICATED array (they must be bit-identical — a divergent buffer is
  silent data corruption on that device). The in-graph form (cheap
  per-shard reduction all-gathered over the mesh axis) lives in
  :func:`singa_tpu.parallel.communicator.replica_fingerprint`.

The checksum engine is ``zlib.crc32`` (stdlib, C speed — the only
dependency-free option; digests are algorithm-tagged so CRC32C/xxhash
can swap in without invalidating the format).
"""

from __future__ import annotations

import json
import os
import struct
import zlib

import numpy as np

DIGEST_ALGO = "crc32"

# wire protocol: 4-byte magic + 1-byte version, then the frame CRCs.
WIRE_MAGIC = b"SGTW"
WIRE_VERSION = 1
# header: magic(4) version(1) meta_crc(4) payload_crc(4) meta_len(4)
# payload_len(4)
_HDR = struct.Struct("<4sBIIII")
# a corrupted length field must never drive a giant allocation: frames
# beyond this are rejected before their buffers are created. Control-
# plane messages are tiny (JSON dicts); 64 MiB is generous headroom.
MAX_MESSAGE_BYTES = 64 << 20


class IntegrityError(RuntimeError):
    """Content failed an integrity check (digest/CRC mismatch, torn or
    oversized frame, replica divergence). Distinct from ``OSError``-
    family failures: the bytes were readable, but they are WRONG."""


# ---------------------------------------------------------------------------
# content digests
# ---------------------------------------------------------------------------

def crc32(data: bytes, value: int = 0) -> int:
    return zlib.crc32(data, value) & 0xFFFFFFFF


def _raw_buffer(arr):
    """Zero-copy byte view of a C-contiguous array — ``tobytes`` would
    duplicate multi-GB checkpoints a second time just to CRC them.
    Extended dtypes (bfloat16, fp8 — ml_dtypes registers them as void
    dtypes) refuse the buffer protocol directly; a ``uint8`` reinterpret
    view restores the zero-copy path for them, so every quantized-
    checkpoint dtype (int8 payloads, bf16, fp8 e4m3/e5m2) digests
    uniformly. ``tobytes`` remains the last-resort single copy."""
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        pass
    try:
        return memoryview(arr.view(np.uint8)).cast("B")
    except (ValueError, TypeError):
        return arr.tobytes()


def bytes_digest(data) -> str:
    """Tagged content digest of a raw byte blob — the AOT-artifact
    form (``singa_tpu/aot``): serialized executables are opaque bytes,
    so the digest covers exactly what sits on disk."""
    data = bytes(data)
    return f"{DIGEST_ALGO}:{crc32(data):08x}:{len(data)}"


def tensor_digest(arr) -> str:
    """Tagged content digest of an array: dtype + shape + raw bytes.
    Covering dtype/shape means a truncated-and-reshaped or silently
    recast tensor fails the check even when its bytes happen to agree."""
    arr = np.asarray(arr)
    head = f"{arr.dtype!s}|{arr.shape}".encode("ascii")
    c = crc32(_raw_buffer(np.ascontiguousarray(arr)), crc32(head))
    return f"{DIGEST_ALGO}:{c:08x}:{arr.nbytes}"


def data_state_digest(state) -> str:
    """Digest of a data-iterator ``state_dict()`` (the checkpointable
    data pipeline, ``singa_tpu/data.py``) over its canonical JSON form
    — sorted keys, compact separators — so dict ordering never matters.
    Rides the data-state sidecar beside every checkpoint, the
    two-phase-commit ACK, and the commit marker: the sample-stream
    offset a resume rewinds to is vouched for end to end, exactly like
    the tensors."""
    blob = json.dumps(state, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return f"{DIGEST_ALGO}:{crc32(blob):08x}:{len(blob)}"


def record_digest(key: bytes, value: bytes) -> str:
    """Digest of one KV record (Snapshot/BinFile sidecars)."""
    key = key.encode("utf-8") if isinstance(key, str) else bytes(key)
    c = crc32(bytes(value), crc32(key))
    return f"{DIGEST_ALGO}:{c:08x}:{len(value)}"


def digest_tree(arrays: dict) -> dict:
    """name -> tensor digest for a flat state dict."""
    return {k: tensor_digest(v) for k, v in arrays.items()}


def manifest_digest(digests: dict) -> str:
    """One digest over a whole digest tree (sorted, so dict order never
    matters): the manifest-level fingerprint recorded in commit markers
    and exchanged between replicas."""
    c = 0
    for k in sorted(digests):
        c = crc32(f"{k}={digests[k]}\n".encode("utf-8"), c)
    return f"{DIGEST_ALGO}:{c:08x}:{len(digests)}"


def verify_tree(arrays: dict, digests: dict) -> list:
    """Names whose content does not match its recorded digest — a
    digested entry MISSING from ``arrays`` counts as a failure too (a
    tensor vanishing is as corrupt as a tensor changing). Entries of
    ``arrays`` without a recorded digest are ignored (additive state)."""
    bad = []
    for k, want in digests.items():
        if k not in arrays:
            bad.append(k)
        elif tensor_digest(arrays[k]) != want:
            bad.append(k)
    return bad


# -- sidecar files ----------------------------------------------------------

def write_digest_sidecar(path: str, records: dict, **extra) -> None:
    """Atomically (tmp + rename) write a digest sidecar JSON: per-record
    digests plus the manifest digest over them."""
    doc = {"algo": DIGEST_ALGO, "records": dict(records),
           "manifest": manifest_digest(records)}
    doc.update(extra)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_digest_sidecar(path: str):
    """Sidecar dict, or None when absent/unparseable (a torn sidecar
    must degrade to 'unverified', never crash a restore that predates
    the integrity layer)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and "records" in doc else None


# ---------------------------------------------------------------------------
# wire framing
# ---------------------------------------------------------------------------

def seal_frame(meta: bytes, payload: bytes) -> bytes:
    """Wrap ``payload`` with the integrity header (magic, protocol
    version, CRCs over meta AND payload, both lengths). Returns the
    sealed payload; ``meta`` rides unchanged but is covered by the
    header's CRC, so metadata corruption is detected too."""
    meta, payload = bytes(meta), bytes(payload)
    return _HDR.pack(WIRE_MAGIC, WIRE_VERSION, crc32(meta),
                     crc32(payload), len(meta), len(payload)) + payload


def open_frame(meta: bytes, sealed: bytes) -> bytes:
    """Verify and strip the integrity header; returns the original
    payload. Raises :class:`IntegrityError` naming the first failed
    check (magic, version, truncation, length, CRC)."""
    meta, sealed = bytes(meta), bytes(sealed)
    if len(sealed) < _HDR.size:
        raise IntegrityError(
            f"frame truncated: {len(sealed)}B < {_HDR.size}B header")
    magic, ver, mcrc, pcrc, mlen, plen = _HDR.unpack_from(sealed)
    if magic != WIRE_MAGIC:
        raise IntegrityError(f"bad frame magic {magic!r} "
                             f"(expected {WIRE_MAGIC!r})")
    if ver != WIRE_VERSION:
        raise IntegrityError(f"frame protocol version {ver} "
                             f"(this side speaks {WIRE_VERSION})")
    payload = sealed[_HDR.size:]
    if mlen != len(meta) or plen != len(payload):
        raise IntegrityError(
            f"frame length mismatch: header says meta {mlen}B / payload "
            f"{plen}B, got {len(meta)}B / {len(payload)}B")
    if crc32(meta) != mcrc:
        raise IntegrityError("frame metadata CRC mismatch")
    if crc32(payload) != pcrc:
        raise IntegrityError("frame payload CRC mismatch")
    return payload


def frame_meta(doc: dict) -> bytes:
    """Canonical metadata bytes for a sealed frame: sorted keys, compact
    separators, utf-8 — the same canonical-JSON form
    :func:`data_state_digest` uses, so the bytes (and hence the header
    CRC :func:`seal_frame` computes over them) are independent of dict
    insertion order on either side of the wire."""
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")


def parse_frame_meta(meta: bytes) -> dict:
    """Decode :func:`frame_meta` bytes back into the metadata dict.
    Raises :class:`IntegrityError` on non-JSON or non-object metadata —
    the caller has usually just CRC-verified ``meta`` via
    :func:`open_frame`, so a parse failure means a protocol bug, not
    line noise, but it still must surface typed."""
    try:
        doc = json.loads(bytes(meta).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise IntegrityError(f"frame metadata is not canonical JSON: {e}")
    if not isinstance(doc, dict):
        raise IntegrityError(
            f"frame metadata must be a JSON object, got {type(doc).__name__}")
    return doc


# ---------------------------------------------------------------------------
# replica fingerprints (host side)
# ---------------------------------------------------------------------------

def state_fingerprint(arrays: dict) -> str:
    """One digest over a whole state dict — what ranks exchange through
    the cluster control plane to agree their replicas have not forked
    (bit-exact: any reordering of updates, SDC, or non-deterministic
    kernel shows up)."""
    return manifest_digest(digest_tree(arrays))


def replica_buffer_mismatches(arrays: dict) -> dict:
    """For every REPLICATED multi-device array, compare the per-device
    buffers — replicas of the same logical array must be bit-identical,
    so a disagreeing buffer is silent data corruption on that device.
    Returns ``{name: [device descriptions holding a minority value]}``
    (empty when everything agrees). Sharded (non-replicated) and
    single-device arrays are skipped — their buffers legitimately
    differ or have nothing to compare."""
    out = {}
    for name, arr in arrays.items():
        shards = getattr(arr, "addressable_shards", None)
        if shards is None or len(shards) < 2:
            continue
        full = (slice(None),) * getattr(arr, "ndim", 0)
        crcs = []
        for s in shards:
            if tuple(s.index) != tuple(full):
                crcs = None          # genuinely sharded: not replicas
                break
            crcs.append((crc32(_raw_buffer(np.ascontiguousarray(
                np.asarray(s.data)))), s.device))
        if not crcs:
            continue
        values = [c for c, _d in crcs]
        majority = max(set(values), key=values.count)
        bad = [str(d) for c, d in crcs if c != majority]
        if bad:
            out[name] = bad
    return out


__all__ = [
    "IntegrityError", "DIGEST_ALGO", "WIRE_MAGIC", "WIRE_VERSION",
    "MAX_MESSAGE_BYTES", "crc32", "bytes_digest", "tensor_digest",
    "data_state_digest", "record_digest",
    "digest_tree", "manifest_digest", "verify_tree",
    "write_digest_sidecar", "read_digest_sidecar", "seal_frame",
    "open_frame", "frame_meta", "parse_frame_meta",
    "state_fingerprint", "replica_buffer_mismatches",
]
