"""The autograd tape engine (see singa_tpu/autograd.py for the op library).

Split from autograd.py so the structured ops in ``singa_tpu/ops/`` can
subclass :class:`Operator` without a circular import. The public surface is
re-exported by ``singa_tpu.autograd`` for reference parity
(python/singa/autograd.py:71-314).
"""

from __future__ import annotations

import time
from collections import deque

import jax
import jax.numpy as jnp

from .tensor import Tensor
from . import device as device_mod


def _profiling(dev, arrays) -> bool:
    """Per-op timing is on when the device asks for verbosity>=2 and the
    values are concrete (timing a traced abstract op is meaningless — the
    compiled step's cost is captured by XLA cost analysis instead)."""
    return (dev is not None and dev.verbosity >= 2 and
            not any(isinstance(a, jax.core.Tracer) for a in arrays))


class _Context:
    """Global autograd mode flags (reference: autograd.training module var).

    ``recording`` tapes ops without training semantics (ONNX export traces
    the inference path: BN uses running stats, dropout is identity).
    """

    def __init__(self):
        self.training = False
        self.recording = False


CTX = _Context()


def is_training() -> bool:
    return CTX.training


def set_training(flag: bool) -> None:
    CTX.training = bool(flag)


def _raw(x):
    if isinstance(x, Tensor):
        return x.data
    return jnp.asarray(x)


def _is_float0(g):
    return g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0)


class Operator:
    """A differentiable operation node on the tape.

    Subclasses implement ``forward(*arrays) -> array | tuple`` with pure
    jax.numpy; the whole tape therefore traces under ``jax.jit`` into one XLA
    computation. ``backward`` defaults to the vjp of ``forward`` — exactly
    consistent with forward and XLA-fused; override only for custom gradient
    semantics. Mirrors reference ``Operator._do_forward`` (autograd.py:270-314).
    """

    op_count = 0
    differentiable = True

    def __init__(self, name: str | None = None):
        if name is None:
            name = f"{type(self).__name__}#{Operator.op_count}"
            Operator.op_count += 1
        self.name = name
        self.src = []
        self.y_ids = ()
        self.y_shapes = ()
        self.y_dtypes = ()
        self._vjp_fn = None
        self.dev = None

    def __call__(self, *xs):
        return self._do_forward(*xs)

    def _has_custom_backward(self) -> bool:
        return type(self).backward is not Operator.backward

    def _do_forward(self, *xs):
        raws = [_raw(x) for x in xs]
        self.dev = next((x.device for x in xs if isinstance(x, Tensor)),
                        device_mod.get_default_device())
        tape = ((CTX.training or CTX.recording) and self.differentiable and
                any(isinstance(x, Tensor) and x.requires_grad for x in xs))
        prof = _profiling(self.dev, raws)
        if prof:
            jax.block_until_ready(raws)   # exclude producers' async work
            t0 = time.perf_counter()
        if tape and not self._has_custom_backward():
            ys, self._vjp_fn = jax.vjp(self.forward, *raws)
        else:
            ys = self.forward(*raws)
        if prof:
            jax.block_until_ready(ys)
            self.dev._record_time(f"fwd/{type(self).__name__}",
                                  time.perf_counter() - t0)
        multiple = isinstance(ys, (tuple, list))
        ys_t = tuple(ys) if multiple else (ys,)

        outs = []
        for y in ys_t:
            t = Tensor.__new__(Tensor)
            t.data = y
            t.device = self.dev
            t.requires_grad = tape
            t.stores_grad = False
            t.creator = self if tape else None
            t.name = None
            t.grad = None
            outs.append(t)

        if tape:
            if CTX.recording:
                # Export traces address tensors by id(); hold a strong ref
                # to every input so no intermediate is garbage-collected
                # mid-trace and its id reused by a later tensor (which
                # would silently mis-wire the exported graph).
                self._export_refs = xs
            self.src = []
            for x in xs:
                if isinstance(x, Tensor) and x.requires_grad:
                    if x.creator is None:
                        x.creator = Dummy(x)
                    self.src.append((x.creator, id(x),
                                     x if x.stores_grad else None, True))
                else:
                    # keep the constant value reachable (ONNX export emits
                    # it as an initializer); backward ignores this entry
                    self.src.append((None, id(x),
                                     x if isinstance(x, Tensor) else None,
                                     False))
            self.y_ids = tuple(id(t) for t in outs)
            self.y_shapes = tuple(y.shape for y in ys_t)
            self.y_dtypes = tuple(y.dtype for y in ys_t)

        return tuple(outs) if multiple else outs[0]

    def forward(self, *xs):
        raise NotImplementedError

    def backward(self, *dys):
        """Default: vjp of forward. Returns one grad per forward input."""
        assert self._vjp_fn is not None, \
            f"{self.name}: backward called without a recorded forward"
        # cotangents must match the primal output dtypes: ops whose
        # backward crosses a precision boundary (e.g. an f32 loss feeding
        # a bf16 net) would otherwise hand mismatched dtypes to vjp rules
        dys = tuple(
            dy.astype(dt) if hasattr(dy, "astype") and dy.dtype != dt
            else dy for dy, dt in zip(dys, self.y_dtypes))
        if len(self.y_shapes) > 1:
            grads = self._vjp_fn(tuple(dys))
        else:
            grads = self._vjp_fn(dys[0])
        return grads if len(grads) > 1 else grads[0]


class Dummy(Operator):
    """Leaf creator marking graph inputs/params (reference autograd.Dummy)."""

    def __init__(self, tensor: Tensor, name=None):
        super().__init__(name)
        self.tensor = tensor
        self.src = []
        self.y_ids = (id(tensor),)
        self.y_shapes = (tensor.shape,)
        self.y_dtypes = (tensor.dtype,)


def infer_dependency(op: Operator):
    """Count, for every upstream op, how many consumer edges reference it
    (reference autograd.py:71-102)."""
    dependency = {op: 0}
    queue = deque([op])
    while queue:
        cur = queue.popleft()
        for (src_op, _xid, _t, requires) in cur.src:
            if src_op is None or not requires:
                continue
            if src_op not in dependency:
                dependency[src_op] = 0
                queue.append(src_op)
            dependency[src_op] += 1
    return dependency


def backward(y: Tensor, dy=None):
    """Reverse-mode over the tape from ``y``; lazily yields
    ``(param_tensor, grad_tensor)`` pairs as each parameter's gradient
    becomes complete (reference autograd.py:128-224), so optimizers can
    overlap updates / collective all-reduces with the rest of backward."""
    assert y.creator is not None, "call backward on a tape output"
    if dy is None:
        dy = jnp.ones(y.shape, dtype=y.dtype)
    else:
        dy = _raw(dy)

    dependency = infer_dependency(y.creator)
    pending = {y.creator: [None] * len(y.creator.y_ids)}
    pending[y.creator][y.creator.y_ids.index(id(y))] = dy
    ready = deque([y.creator])
    seen_params = set()

    while ready:
        op = ready.popleft()
        dys = pending.pop(op)
        dys = [d if d is not None else jnp.zeros(s, dt)
               for d, s, dt in zip(dys, op.y_shapes, op.y_dtypes)]

        if isinstance(op, Dummy):
            t = op.tensor
            if t.stores_grad and id(t) not in seen_params:
                seen_params.add(id(t))
                g = Tensor(data=dys[0], device=t.device, requires_grad=False)
                t.grad = g
                yield (t, g)
            continue

        prof = _profiling(op.dev, dys)
        if prof:
            jax.block_until_ready(dys)
            t0 = time.perf_counter()
        dxs = op.backward(*dys)
        if not isinstance(dxs, (tuple, list)):
            dxs = (dxs,)
        if prof:
            jax.block_until_ready([d for d in dxs if not _is_float0(d)])
            op.dev._record_time(f"bwd/{type(op).__name__}",
                                time.perf_counter() - t0)
        assert len(dxs) == len(op.src), \
            f"{op.name}: backward returned {len(dxs)} grads for " \
            f"{len(op.src)} inputs"

        for (src_op, x_id, _t, requires), dx in zip(op.src, dxs):
            if src_op is None or not requires or _is_float0(dx):
                continue
            slot = pending.setdefault(src_op, [None] * len(src_op.y_ids))
            pos = src_op.y_ids.index(x_id)
            slot[pos] = dx if slot[pos] is None else slot[pos] + dx
            dependency[src_op] -= 1
            if dependency[src_op] == 0:
                ready.append(src_op)


def gradients(y: Tensor, dy=None):
    """Materialise all (param, grad) pairs into a dict keyed by param
    (reference autograd.gradients, autograd.py:105)."""
    return {p: g for p, g in backward(y, dy)}
