"""Evaluation metrics.

Capability parity with the reference metric stack (include/singa/model/
metric.h:32-69 ``Metric``/``Accuracy`` and the per-example accuracy helper
used in the examples, examples/cnn/train_cnn.py:49-54).
"""

from __future__ import annotations

import numpy as np

from .tensor import Tensor


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric:
    """Base metric (reference include/singa/model/metric.h:32)."""

    def forward(self, prediction, target):
        """Per-sample scores as a float array."""
        raise NotImplementedError

    def evaluate(self, prediction, target):
        """Mean score over the batch."""
        return float(np.mean(self.forward(prediction, target)))

    # C++-style aliases; delegate so subclass overrides dispatch correctly
    def Forward(self, prediction, target):
        return self.forward(prediction, target)

    def Evaluate(self, prediction, target):
        return self.evaluate(prediction, target)


class Accuracy(Metric):
    """Top-k accuracy (reference include/singa/model/metric.h:59-77).

    ``target`` may be integer class ids or one-hot rows.
    """

    def __init__(self, top_k=1):
        self.top_k = top_k

    def forward(self, prediction, target):
        pred = _np(prediction)
        tgt = _np(target)
        if tgt.shape == pred.shape:
            tgt = np.argmax(tgt, axis=-1)  # one-hot rows
        # anything else ((B,), (B,1), (B,S) vs (B,S,V), ...) is int labels
        tgt = tgt.astype(np.int64).ravel()
        pred2d = pred.reshape(-1, pred.shape[-1])
        if self.top_k == 1:
            return (np.argmax(pred2d, axis=-1) == tgt).astype(np.float32)
        topk = np.argsort(-pred2d, axis=-1)[:, :self.top_k]
        return np.any(topk == tgt[:, None], axis=-1).astype(np.float32)


def accuracy(pred, target):
    """Batch accuracy as a float (reference examples/cnn/train_cnn.py:49)."""
    return Accuracy().evaluate(pred, target)
