"""Pooling via windowed reductions.

Capability parity with the reference pooling operation
(src/model/operation/pooling.h:40-96): a static :class:`PoolingHandle`
(the role of ``CudnnPoolingHandle``'s descriptors) and forward/backward via
``lax.reduce_window`` — XLA emits the max-pool argmax routing and avg-pool
scatter in the vjp, replacing cudnnPoolingBackward.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


class PoolingHandle:
    """Static pooling config (reference PoolingHandle pooling.h:40-72).

    ``padding`` may be an int, an (ph, pw) pair, or an explicit
    ((ph0, ph1), (pw0, pw1)) for asymmetric padding (ONNX import).
    """

    def __init__(self, x, kernel_size, stride=None, padding=0, is_max=True,
                 layout=None, count_include_pad=True):
        from .layout import resolve as _resolve_layout
        # True matches the reference's cuDNN include-padding average mode
        # (CUDNN_POOLING_AVERAGE_COUNT_INCLUDE_PADDING); the ONNX
        # AveragePool DEFAULT is exclude (count_include_pad=0), which the
        # backend requests explicitly
        self.count_include_pad = bool(count_include_pad)
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride if stride is not None else kernel_size)
        if (isinstance(padding, (tuple, list)) and len(padding) == 2
                and isinstance(padding[0], (tuple, list))):
            self.pad_pairs = tuple(tuple(int(v) for v in p) for p in padding)
            self.padding = (self.pad_pairs[0][0], self.pad_pairs[1][0])
        else:
            ph, pw = _pair(padding)
            self.pad_pairs = ((ph, ph), (pw, pw))
            self.padding = (ph, pw)
        self.is_max_pooling = bool(is_max)
        self.layout = _resolve_layout(layout)
        xs = x.shape if hasattr(x, "shape") else tuple(x)
        self.batchsize = int(xs[0])
        if self.layout == "NHWC" and len(xs) == 4:
            self.channels = int(xs[3])
            self.height, self.width = int(xs[1]), int(xs[2])
        else:
            self.channels = int(xs[1])
            if len(xs) == 4:
                self.height, self.width = int(xs[2]), int(xs[3])
        if len(xs) == 4:
            kh, kw = self.kernel_size
            sh, sw = self.stride
            (p0, p1), (q0, q1) = self.pad_pairs
            self.pooled_height = (self.height + p0 + p1 - kh) // sh + 1
            self.pooled_width = (self.width + q0 + q1 - kw) // sw + 1


class _Pooling2d(Operator):
    def __init__(self, handle: PoolingHandle):
        super().__init__()
        self.handle = handle

    def forward(self, x):
        h = self.handle
        kh, kw = h.kernel_size
        sh, sw = h.stride
        if h.layout == "NHWC":
            dims = (1, kh, kw, 1)
            strides = (1, sh, sw, 1)
            pads = ((0, 0), h.pad_pairs[0], h.pad_pairs[1], (0, 0))
        else:
            dims = (1, 1, kh, kw)
            strides = (1, 1, sh, sw)
            pads = ((0, 0), (0, 0), h.pad_pairs[0], h.pad_pairs[1])
        if h.is_max_pooling:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                else jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, dims, strides, pads)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if h.count_include_pad:
            # divide by full window size (reference cuDNN include mode)
            return s / float(kh * kw)
        # ONNX default: divide by the VALID element count per window —
        # a reduce_window over ones gives it; XLA folds this to a
        # constant table at compile time
        ones = jnp.ones(x.shape, x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return s / cnt


class GlobalAveragePool(Operator):
    """(N,C,H,W) -> (N,C,1,1) mean (reference autograd.GlobalAveragePool)."""

    def __init__(self, data_format="channels_first"):
        super().__init__()
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "channels_first":
            return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)
        return jnp.mean(x, axis=tuple(range(1, x.ndim - 1)), keepdims=True)


def pooling_2d(handle: PoolingHandle, x):
    """Functional wrapper (parity: reference autograd.pooling_2d:1847)."""
    return _Pooling2d(handle)(x)


def globalaveragepool(x, data_format="channels_first"):
    return GlobalAveragePool(data_format)(x)
