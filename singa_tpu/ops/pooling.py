"""Pooling via windowed reductions.

Capability parity with the reference pooling operation
(src/model/operation/pooling.h:40-96): a static :class:`PoolingHandle`
(the role of ``CudnnPoolingHandle``'s descriptors) and forward/backward via
``lax.reduce_window`` — XLA emits the max-pool argmax routing and avg-pool
scatter in the vjp, replacing cudnnPoolingBackward.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


class PoolingHandle:
    """Static pooling config (reference PoolingHandle pooling.h:40-72)."""

    def __init__(self, x, kernel_size, stride=None, padding=0, is_max=True):
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride if stride is not None else kernel_size)
        self.padding = _pair(padding)
        self.is_max_pooling = bool(is_max)
        xs = x.shape if hasattr(x, "shape") else tuple(x)
        self.batchsize = int(xs[0])
        self.channels = int(xs[1])
        if len(xs) == 4:
            self.height, self.width = int(xs[2]), int(xs[3])
            kh, kw = self.kernel_size
            sh, sw = self.stride
            ph, pw = self.padding
            self.pooled_height = (self.height + 2 * ph - kh) // sh + 1
            self.pooled_width = (self.width + 2 * pw - kw) // sw + 1


class _Pooling2d(Operator):
    def __init__(self, handle: PoolingHandle):
        super().__init__()
        self.handle = handle

    def forward(self, x):
        h = self.handle
        kh, kw = h.kernel_size
        sh, sw = h.stride
        ph, pw = h.padding
        dims = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
        pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        if h.is_max_pooling:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
                else jnp.iinfo(x.dtype).min
            return lax.reduce_window(x, init, lax.max, dims, strides, pads)
        # average pool: divide by true window size (count_include_pad=True
        # matches the reference cuDNN mode
        # CUDNN_POOLING_AVERAGE_COUNT_INCLUDE_PADDING)
        s = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        return s / float(kh * kw)


class GlobalAveragePool(Operator):
    """(N,C,H,W) -> (N,C,1,1) mean (reference autograd.GlobalAveragePool)."""

    def __init__(self, data_format="channels_first"):
        super().__init__()
        self.data_format = data_format

    def forward(self, x):
        if self.data_format == "channels_first":
            return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)
        return jnp.mean(x, axis=tuple(range(1, x.ndim - 1)), keepdims=True)


def pooling_2d(handle: PoolingHandle, x):
    """Functional wrapper (parity: reference autograd.pooling_2d:1847)."""
    return _Pooling2d(handle)(x)


def globalaveragepool(x, data_format="channels_first"):
    return GlobalAveragePool(data_format)(x)
