"""Multi-layer (bi)directional RNN/LSTM/GRU as a `lax.scan` over time.

Capability parity with the reference's cuDNN-only RNN operation
(src/model/operation/rnn.h:38-131): one flat parameter vector per RNN (the
cuDNN packed-weights convention, rnn.h:89-92) unpacked by static offsets, and
variable-length sequence masking equivalent to the packed "Ex" entry points
(GpuRNNForwardTrainingEx, rnn.h:117-131) via per-step `where` masking.

TPU-first notes: the time loop is a single `lax.scan`, so XLA compiles one
fused step reused across timesteps; each step's gate matmul is one MXU GEMM
of shape (batch, in+hidden) @ (in+hidden, gates*hidden). Backward is the vjp
of the scan (reverse scan), replacing cudnnRNNBackwardData/Weights.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator, is_training

_GATES = {"lstm": 4, "gru": 3, "tanh": 1, "relu": 1, "vanilla": 1}


class CudnnRNNHandle:
    """Static RNN config + flat-weight layout map (reference CudnnRNNHandle
    rnn.h:38-93). The name keeps API parity; nothing cuDNN remains.

    Flat layout per layer, per direction:
      W_ih (G*H, in) | W_hh (G*H, H) | b_ih (G*H) | b_hh (G*H)
    with gate order i,f,g,o (lstm) / r,z,n (gru).
    """

    def __init__(self, x, hidden_size, mode="lstm", num_layers=1,
                 bias=True, dropout=0.0, bidirectional=False,
                 gru_linear_before_reset=True):
        xs = x.shape if hasattr(x, "shape") else tuple(x)
        self.feature_size = int(xs[-1])
        self.hidden_size = int(hidden_size)
        self.mode = mode if isinstance(mode, str) else \
            {0: "relu", 1: "tanh", 2: "lstm", 3: "gru"}[mode]
        self.num_layers = int(num_layers)
        self.bias = bool(bias)
        self.dropout = float(dropout)
        self.bidirectional = bool(bidirectional)
        # True = torch/cuDNN convention (n-gate bias inside the reset
        # product); False = ONNX GRU default linear_before_reset=0
        self.gru_linear_before_reset = bool(gru_linear_before_reset)
        self.num_directions = 2 if self.bidirectional else 1
        self.gates = _GATES[self.mode]
        self.batch_first = False

        # offset map: [(layer, dir)] -> (Wih, Whh, bih, bhh) slices
        self.offsets = []
        off = 0
        G, H = self.gates, self.hidden_size
        for layer in range(self.num_layers):
            in_size = self.feature_size if layer == 0 \
                else H * self.num_directions
            per_dir = []
            for _d in range(self.num_directions):
                shapes = [(G * H, in_size), (G * H, H), (G * H,), (G * H,)]
                slices = []
                for s in shapes:
                    n = int(np.prod(s))
                    slices.append((off, off + n, s))
                    off += n
                per_dir.append(slices)
            self.offsets.append(per_dir)
        self.weights_size = off

    def unpack(self, W):
        """Flat W -> nested [(layer)][(dir)] param tuples."""
        out = []
        for per_dir in self.offsets:
            dirs = []
            for slices in per_dir:
                dirs.append(tuple(W[a:b].reshape(s) for a, b, s in slices))
            out.append(dirs)
        return out


def _step(mode, params, carry, x_t, gru_lbr=True):
    Wih, Whh, bih, bhh = params
    h, c = carry
    if mode == "gru":
        gi = x_t @ Wih.T + bih
        H = h.shape[-1]
        if gru_lbr:
            gh = h @ Whh.T + bhh
            r = jax.nn.sigmoid(gi[:, :H] + gh[:, :H])
            z = jax.nn.sigmoid(gi[:, H:2 * H] + gh[:, H:2 * H])
            n = jnp.tanh(gi[:, 2 * H:] + r * gh[:, 2 * H:])
        else:
            # ONNX linear_before_reset=0: reset gates the hidden STATE
            # before the recurrent matmul (bias outside the product); only
            # the r/z gate columns go through the plain recurrent matmul
            gh = h @ Whh[:2 * H].T + bhh[:2 * H]
            r = jax.nn.sigmoid(gi[:, :H] + gh[:, :H])
            z = jax.nn.sigmoid(gi[:, H:2 * H] + gh[:, H:])
            n = jnp.tanh(gi[:, 2 * H:] + (r * h) @ Whh[2 * H:, :].T
                         + bhh[2 * H:])
        h_new = (1 - z) * n + z * h
        return (h_new, c), h_new
    g = x_t @ Wih.T + h @ Whh.T + bih + bhh
    if mode == "lstm":
        H = h.shape[-1]
        i = jax.nn.sigmoid(g[:, :H])
        f = jax.nn.sigmoid(g[:, H:2 * H])
        gg = jnp.tanh(g[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(g[:, 3 * H:])
        c_new = f * c + i * gg
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new
    h_new = jnp.tanh(g) if mode == "tanh" or mode == "vanilla" \
        else jnp.maximum(g, 0)
    return (h_new, c), h_new


def _run_direction(mode, params, x, h0, c0, lengths, reverse,
                   gru_lbr=True):
    """Scan one direction over (T, B, F) -> (T, B, H), h_T, c_T."""
    T = x.shape[0]
    ts = jnp.arange(T)
    if reverse:
        x = jnp.flip(x, axis=0)
        ts = jnp.flip(ts, axis=0)

    def body(carry, inp):
        x_t, t = inp
        (h_new, c_new), out = _step(mode, params, carry, x_t,
                                    gru_lbr=gru_lbr)
        if lengths is not None:
            valid = (t < lengths)[:, None]
            h_new = jnp.where(valid, h_new, carry[0])
            c_new = jnp.where(valid, c_new, carry[1])
            out = jnp.where(valid, out, jnp.zeros_like(out))
        return (h_new, c_new), out

    (hT, cT), ys = lax.scan(body, (h0, c0), (x, ts))
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT, cT


class _RNN(Operator):
    """The RNN op (reference autograd._RNN:4818-4931). Inputs:
    (x, hx, cx, W[, seq_lengths]); outputs (y, hy, cy)."""

    def __init__(self, handle: CudnnRNNHandle, use_mask=False):
        super().__init__()
        self.handle = handle
        self.use_mask = use_mask

    def forward(self, x, hx, cx, W, seq_lengths=None):
        # policy discipline: the scanned gate matmuls run in the compute
        # dtype (W is the packed master; lengths are index-valued)
        from ..mixed_precision import cast_compute as _cast_compute
        x, hx, cx, W = _cast_compute(x, hx, cx, W)
        h = self.handle
        lengths = seq_lengths
        D, L, H = h.num_directions, h.num_layers, h.hidden_size
        params = h.unpack(W)
        inp = x
        h_out, c_out = [], []
        for layer in range(L):
            ys = []
            for d in range(D):
                idx = layer * D + d
                y, hT, cT = _run_direction(
                    h.mode, params[layer][d], inp,
                    hx[idx], cx[idx], lengths, reverse=(d == 1),
                    gru_lbr=h.gru_linear_before_reset)
                ys.append(y)
                h_out.append(hT)
                c_out.append(cT)
            inp = jnp.concatenate(ys, axis=-1) if D == 2 else ys[0]
            if h.dropout > 0 and layer < L - 1 and is_training():
                key = self.dev.rand_key()
                keep = 1.0 - h.dropout
                mask = jax.random.bernoulli(key, keep, inp.shape)
                inp = jnp.where(mask, inp / keep, 0.0)
        return inp, jnp.stack(h_out), jnp.stack(c_out)


def rnn_op(handle, x, hx, cx, W, seq_lengths=None):
    """Functional wrapper (parity: reference autograd.py rnn driver)."""
    if seq_lengths is None:
        return _RNN(handle)(x, hx, cx, W)
    return _RNN(handle, use_mask=True)(x, hx, cx, W, seq_lengths)
