"""Fused optimizer-update Pallas kernels: one HBM pass per parameter.

The reference optimizer updates are chains of elementwise ops (momentum
EWMA, bias correction, axpy) that XLA *may* fuse but, measured on the
bench ResNet step, often splits across several HBM round trips of the
full parameter + aux state — pure ``timeline_mfu_loss{compute_
inefficiency}`` budget. These kernels do the whole update in ONE pass
over flattened parameter blocks: read grad + master + aux once, write
master + aux once, with the aux/master outputs aliased onto their
inputs. Parameters whose size is not a (rows×128)-tile multiple pay a
pad/slice around the kernel (XLA fuses what it can, but the aliasing
then covers the padded buffers, not the live state) — whether the
fused form still wins for a given model is exactly what the banked
``fused_optim_ab`` hardware A/B decides; it is never assumed.

House pattern (``ops/attention.py``): availability gate that DECLINES
to the reference path rather than erroring (``available``), interpreter
mode on CPU so tier-1 CI pins the exact kernel math the TPU executes
(``FORCE_PALLAS_INTERPRET`` — the ``pallas`` pytest marker selects
these suites), and selection is measured-not-guessed: the optimizers
only take this path when constructed with ``fused=True``, which bench
steers through ``bench._measured_choice`` ("fused_optim_ab") — never
unconditionally.

FLOPs accounting: a Pallas kernel is a custom call XLA's cost analysis
cannot see into (on TPU it counts ~0 flops; in interpreter mode it
counts the lowered emulation loop instead). Either way the fused
program's analyzed FLOPs would differ from the reference program's and
MFU would move without the hardware doing anything different.
``trace_collector`` records which fused kernels a step trace took, and
``Model.step_flops`` re-lowers the step under :func:`force_reference`
when any did — so fused and unfused programs report IDENTICAL FLOPs by
construction (pinned in tests/test_fused_kernels.py).
"""

from __future__ import annotations

import contextlib
import contextvars
import functools

import jax
import jax.numpy as jnp

try:  # pallas import is TPU-oriented; keep CPU-only installs working
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False

# Test hook, same contract as ops/attention.py: run the kernels under
# pl.pallas_call(interpret=True) on CPU so CI validates the exact math.
FORCE_PALLAS_INTERPRET = False

_LANES = 128
_SUBLANES = 8

# On silicon, one more kernel launch costs more than it saves for tiny
# parameters (a bias vector); the reference path keeps those. Interpret
# mode accepts ANY size so CPU CI exercises the padding/tiling logic.
MIN_FUSED_ELEMS = 4096

_FORCE_REFERENCE = contextvars.ContextVar("fused_force_reference",
                                          default=False)
_TRACE_SINK = contextvars.ContextVar("fused_trace_sink", default=None)


@contextlib.contextmanager
def force_reference():
    """Decline every fused kernel inside this scope — the reference
    elementwise math traces instead. ``Model.step_flops`` lowers its
    cost-analysis twin under this, so the FLOPs number always describes
    the reference program regardless of what the executed step fused."""
    tok = _FORCE_REFERENCE.set(True)
    try:
        yield
    finally:
        _FORCE_REFERENCE.reset(tok)


@contextlib.contextmanager
def trace_collector(sink):
    """Collect the kind tag of every fused kernel dispatched inside this
    scope into ``sink`` (a list). The Model step builder installs one
    per trace so the compiled-step record knows whether its program
    contains cost-analysis-invisible custom calls."""
    tok = _TRACE_SINK.set(sink)
    try:
        yield
    finally:
        _TRACE_SINK.reset(tok)


def _mark(kind):
    sink = _TRACE_SINK.get()
    if sink is not None:
        sink.append(kind)


def _interpret():
    return FORCE_PALLAS_INTERPRET or jax.default_backend() != "tpu"


def available(n_elems):
    """Kernel-eligibility gate: Pallas importable, not inside
    :func:`force_reference`, and either a real TPU backend with a
    parameter big enough to amortise the launch, or the interpret-mode
    test hook (any size, so CI covers padding)."""
    if not HAS_PALLAS or _FORCE_REFERENCE.get():
        return False
    if jax.default_backend() == "tpu":
        return int(n_elems) >= MIN_FUSED_ELEMS
    return FORCE_PALLAS_INTERPRET


# ---------------------------------------------------------------------------
# flattened-block layout: any parameter shape -> (rows, 128) f32-friendly
# tiles, rows padded to a sublane multiple; the tail pad is zeros, whose
# updates are computed and sliced away (cheaper than masking in-kernel)
# ---------------------------------------------------------------------------

def _pad_rows(n):
    rows = -(-n // _LANES)
    return -(-rows // _SUBLANES) * _SUBLANES


def _to_rows(arr, rows):
    flat = arr.ravel()
    pad = rows * _LANES - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, _LANES)


def _from_rows(arr, shape, n):
    return arr.ravel()[:n].reshape(shape)


def _block_rows(rows):
    """Largest row-block that tiles ``rows`` (rows is a sublane
    multiple, so 8 always divides)."""
    return next(b for b in (512, 256, 128, 64, 32, 16, 8)
                if rows % b == 0)


def _scalar(x):
    return jnp.asarray(x, jnp.float32).reshape(1, 1)


# ---------------------------------------------------------------------------
# SGD with momentum
# ---------------------------------------------------------------------------

def _sgd_kernel(lr_ref, p_ref, g_ref, m_ref, po_ref, mo_ref, *,
                momentum, dampening, weight_decay, nesterov):
    lr = lr_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p
    m_new = momentum * m_ref[...].astype(jnp.float32) \
        + (1.0 - dampening) * g
    upd = g + momentum * m_new if nesterov else m_new
    po_ref[...] = (p - lr * upd).astype(po_ref.dtype)
    mo_ref[...] = m_new.astype(mo_ref.dtype)


def sgd_momentum_update(p, g, m, lr, *, momentum, dampening=0.0,
                        weight_decay=0.0, nesterov=False):
    """Fused ``opt.SGD`` momentum update: returns ``(p_new, m_new)``
    with the input shapes/dtypes preserved. Math identical to the
    reference ``SGD.apply`` chain (f32 accumulate, store back in the
    state dtype); parity is pinned bitwise in interpret mode."""
    _mark("sgd")
    shape, n = p.shape, p.size
    rows = _pad_rows(n)
    br = _block_rows(rows)
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    kernel = functools.partial(
        _sgd_kernel, momentum=float(momentum),
        dampening=float(dampening), weight_decay=float(weight_decay),
        nesterov=bool(nesterov))
    po, mo = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                  blk, blk, blk],
        out_specs=[blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), m.dtype)],
        # master/momentum update in place: input p (index 1 after the
        # scalar) aliases output 0, m (index 3) aliases output 1 — the
        # "one HBM pass" contract
        input_output_aliases={1: 0, 3: 1},
        interpret=_interpret(),
    )(_scalar(lr), _to_rows(p, rows), _to_rows(g, rows),
      _to_rows(m, rows))
    return _from_rows(po, shape, n), _from_rows(mo, shape, n)


# ---------------------------------------------------------------------------
# Adam
# ---------------------------------------------------------------------------

def _adam_kernel(lr_ref, bc1_ref, bc2_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, beta_1, beta_2, epsilon,
                 weight_decay):
    lr = lr_ref[0, 0]
    bc1 = bc1_ref[0, 0]
    bc2 = bc2_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p
    m_new = beta_1 * m_ref[...].astype(jnp.float32) + (1.0 - beta_1) * g
    v_new = beta_2 * v_ref[...].astype(jnp.float32) \
        + (1.0 - beta_2) * g * g
    mhat = m_new / bc1
    vhat = v_new / bc2
    po_ref[...] = (p - lr * mhat
                   / (jnp.sqrt(vhat) + epsilon)).astype(po_ref.dtype)
    mo_ref[...] = m_new.astype(mo_ref.dtype)
    vo_ref[...] = v_new.astype(vo_ref.dtype)


def adam_update(p, g, m, v, lr, bias_corr1, bias_corr2, *, beta_1,
                beta_2, epsilon, weight_decay=0.0):
    """Fused ``opt.Adam`` update (no amsgrad): returns
    ``(p_new, m_new, v_new)``. ``bias_corr1/2`` are the traced
    ``1 - beta^t`` denominators (computed by the caller exactly as the
    reference does, so the step-counter semantics cannot drift)."""
    _mark("adam")
    shape, n = p.shape, p.size
    rows = _pad_rows(n)
    br = _block_rows(rows)
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    sca = pl.BlockSpec((1, 1), lambda i: (0, 0))
    kernel = functools.partial(
        _adam_kernel, beta_1=float(beta_1), beta_2=float(beta_2),
        epsilon=float(epsilon), weight_decay=float(weight_decay))
    po, mo, vo = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[sca, sca, sca, blk, blk, blk, blk],
        out_specs=[blk, blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), m.dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), v.dtype)],
        input_output_aliases={3: 0, 5: 1, 6: 2},
        interpret=_interpret(),
    )(_scalar(lr), _scalar(bias_corr1), _scalar(bias_corr2),
      _to_rows(p, rows), _to_rows(g, rows), _to_rows(m, rows),
      _to_rows(v, rows))
    return (_from_rows(po, shape, n), _from_rows(mo, shape, n),
            _from_rows(vo, shape, n))


# ---------------------------------------------------------------------------
# RMSProp
# ---------------------------------------------------------------------------

def _rmsprop_kernel(lr_ref, p_ref, g_ref, r_ref, po_ref, ro_ref, *,
                    rho, epsilon, weight_decay):
    lr = lr_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p
    # op order mirrors opt.RMSProp.apply exactly (rho*rms first, then
    # the (1-rho)*g*g term) so f32 params hold BITWISE parity
    r_new = rho * r_ref[...].astype(jnp.float32) \
        + (1.0 - rho) * g * g
    r_stored = r_new.astype(ro_ref.dtype)
    ro_ref[...] = r_stored
    po_ref[...] = (p - lr * g
                   / jnp.sqrt(r_stored.astype(jnp.float32)
                              + epsilon)).astype(po_ref.dtype)


def rmsprop_update(p, g, r, lr, *, rho, epsilon, weight_decay=0.0):
    """Fused ``opt.RMSProp`` update: returns ``(p_new, rms_new)`` with
    the input shapes/dtypes preserved, grad+master+rms read once and
    master+rms written once (aliased in place). Math identical to the
    reference chain — the rms store-back happens BEFORE the param
    update reads it, exactly like the reference's
    ``rms.data = ...; p.data = f(rms.data)`` sequence, so a non-f32
    rms state quantizes at the same point in both paths."""
    _mark("rmsprop")
    shape, n = p.shape, p.size
    rows = _pad_rows(n)
    br = _block_rows(rows)
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    kernel = functools.partial(
        _rmsprop_kernel, rho=float(rho), epsilon=float(epsilon),
        weight_decay=float(weight_decay))
    po, ro = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                  blk, blk, blk],
        out_specs=[blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), r.dtype)],
        input_output_aliases={1: 0, 3: 1},
        interpret=_interpret(),
    )(_scalar(lr), _to_rows(p, rows), _to_rows(g, rows),
      _to_rows(r, rows))
    return _from_rows(po, shape, n), _from_rows(ro, shape, n)


# ---------------------------------------------------------------------------
# AdaGrad
# ---------------------------------------------------------------------------

def _adagrad_kernel(lr_ref, p_ref, g_ref, h_ref, po_ref, ho_ref, *,
                    epsilon, weight_decay):
    lr = lr_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    if weight_decay:
        g = g + weight_decay * p
    h_new = h_ref[...].astype(jnp.float32) + g * g
    h_stored = h_new.astype(ho_ref.dtype)
    ho_ref[...] = h_stored
    po_ref[...] = (p - lr * g
                   / jnp.sqrt(h_stored.astype(jnp.float32)
                              + epsilon)).astype(po_ref.dtype)


def adagrad_update(p, g, h, lr, *, epsilon, weight_decay=0.0):
    """Fused ``opt.AdaGrad`` update: returns ``(p_new, history_new)``,
    same one-HBM-pass/aliasing contract as the other kernels. The
    accumulated-square history is unbounded by design (AdaGrad's
    semantics); f32 accumulation in-kernel matches the reference's
    f32 math on f32 state bitwise."""
    _mark("adagrad")
    shape, n = p.shape, p.size
    rows = _pad_rows(n)
    br = _block_rows(rows)
    blk = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    kernel = functools.partial(
        _adagrad_kernel, epsilon=float(epsilon),
        weight_decay=float(weight_decay))
    po, ho = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0)),
                  blk, blk, blk],
        out_specs=[blk, blk],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
                   jax.ShapeDtypeStruct((rows, _LANES), h.dtype)],
        input_output_aliases={1: 0, 3: 1},
        interpret=_interpret(),
    )(_scalar(lr), _to_rows(p, rows), _to_rows(g, rows),
      _to_rows(h, rows))
    return _from_rows(po, shape, n), _from_rows(ho, shape, n)
