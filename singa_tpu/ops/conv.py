"""2-D convolution on the MXU.

Capability parity with the reference convolution operation
(src/model/operation/convolution.h:43-141): a :class:`ConvHandle` fixes the
static geometry once per layer instance (the role of ``CudnnConvHandle``'s
descriptor/algorithm setup), and the op lowers to
``lax.conv_general_dilated``, which XLA tiles directly onto the TPU systolic
array — there is no im2col path and no algorithm search; backward comes from
the vjp of the same primitive (cudnnConvolutionBackwardData/Filter
equivalents are emitted by XLA).

Layout: NCHW / OIHW at the API for reference parity. A handle built
inside :func:`..ops.layout.use_layout` ("NHWC") instead takes
channels-last activations (weights stay OIHW, so checkpoints are
layout-independent) — the TPU-friendly form where the channel dim sits
in the 128-lane minor position; see ops/layout.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator
from ..mixed_precision import cast_compute as _cast_compute


def _pair(v):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


class ConvHandle:
    """Static conv config (reference ConvHandle convolution.h:43-90).

    ``padding`` may be an int, an (ph, pw) pair, or an explicit
    ((ph0, ph1), (pw0, pw1)) for odd/asymmetric padding (the reference's
    odd-padding helper, python/singa/utils.py).
    """

    def __init__(self, x, kernel_size, stride, padding, in_channels,
                 out_channels, bias=True, group=1, pad_mode=None,
                 dilation=1, layout=None, space_to_depth=False):
        from .layout import resolve as _resolve_layout
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.dilation = _pair(dilation)
        if (isinstance(padding, (tuple, list)) and len(padding) == 2
                and isinstance(padding[0], (tuple, list))):
            self.padding = tuple(tuple(int(v) for v in p) for p in padding)
        else:
            ph, pw = _pair(padding)
            self.padding = ((ph, ph), (pw, pw))
        self.in_channels = int(in_channels)
        self.out_channels = int(out_channels)
        self.bias = bool(bias)
        self.group = int(group)
        self.pad_mode = pad_mode  # "SAME"/"VALID" override, else explicit
        self.layout = _resolve_layout(layout)
        xs = x.shape if hasattr(x, "shape") else tuple(x)
        self.batchsize = int(xs[0]) if len(xs) > 0 else 0
        if len(xs) == 4:
            if self.layout == "NHWC":
                self.height, self.width = int(xs[1]), int(xs[2])
            else:
                self.height, self.width = int(xs[2]), int(xs[3])
        # weights are OIHW in BOTH layouts (checkpoint-stable); only the
        # activation spec changes — XLA maps either onto the MXU
        self.dimension_numbers = (self.layout, "OIHW", self.layout)
        self.space_to_depth = bool(space_to_depth)
        if self.space_to_depth:
            kh, kw = self.kernel_size
            (p0, p1), (q0, q1) = self.padding
            if (self.stride != (2, 2) or kh != kw or p0 != p1
                    or q0 != q1 or p0 != q0 or 2 * p0 != kh - 1
                    or self.group != 1 or self.dilation != (1, 1)
                    or self.pad_mode
                    or (self.height and self.height % 2)
                    or (self.width and self.width % 2)):
                raise ValueError(
                    "space_to_depth stem requires stride 2, square odd "
                    "kernel with pad = (K-1)/2, group 1, no dilation, "
                    "and even spatial dims (the 7x7/s2 ResNet stem "
                    "shape)")

    def output_shape(self, x_shape):
        if self.layout == "NHWC":
            n, h, w, _ = x_shape
        else:
            n, _, h, w = x_shape
        (p0, p1), (q0, q1) = self.padding
        kh, kw = self.kernel_size
        sh, sw = self.stride
        dh, dw = self.dilation
        oh = (h + p0 + p1 - (dh * (kh - 1) + 1)) // sh + 1
        ow = (w + q0 + q1 - (dw * (kw - 1) + 1)) // sw + 1
        if self.layout == "NHWC":
            return (n, oh, ow, self.out_channels)
        return (n, self.out_channels, oh, ow)


def _add_bias(y, b, layout):
    """Per-channel bias broadcast for either activation layout."""
    if b is None:
        return y
    return y + (b.reshape(1, 1, 1, -1) if layout == "NHWC"
                else b.reshape(1, -1, 1, 1))


def _s2d_geometry(K, P):
    """Tap decomposition of a stride-2 conv axis: kernel position p maps
    to block offset t and parity a via p - P = 2t + a. Returns
    (t_min, t_max) — the transformed kernel spans t_max - t_min + 1."""
    qs = [p - P for p in range(K)]
    ts = [(q - (q % 2)) // 2 for q in qs]
    return min(ts), max(ts)


def _space_to_depth_conv(x, W, handle):
    """The MLPerf-style stem transform: a KxK stride-2 conv with tiny
    C_in (3 for images — wasting 3/128 of the MXU's lane dim) is
    EXACTLY a (K+1)/2-rounded conv at stride 1 on the space-to-depth'd
    input with 4x the channels. Weights stay stored as (O, C, K, K) —
    checkpoints unchanged — and are re-indexed into the transformed
    kernel inside the trace — one gather + one scatter over constant
    numpy index tables per step (tiny: O*C*4*Kp*Kp elements)."""
    h = handle
    K, _ = h.kernel_size
    (P, _), _ = h.padding
    t_min, t_max = _s2d_geometry(K, P)
    Kp = t_max - t_min + 1
    O, C = h.out_channels, h.in_channels
    # weight re-index: W4[o, c*4 + ah*2 + aw, th-t_min, tw-t_min]
    #   = W[o, c, p_h, p_w]  with p = (2t + a) + P. The index tables are
    # numpy constants, so the whole remap is ONE gather + ONE scatter in
    # the trace (not K*K*C dynamic-update-slices).
    c_i, ph_i, pw_i = np.meshgrid(np.arange(C), np.arange(K),
                                  np.arange(K), indexing="ij")
    c_i, ph_i, pw_i = c_i.ravel(), ph_i.ravel(), pw_i.ravel()
    qh, qw = ph_i - P, pw_i - P
    ah, aw = qh % 2, qw % 2
    th, tw = (qh - ah) // 2, (qw - aw) // 2
    W4 = jnp.zeros((O, C * 4, Kp, Kp), W.dtype).at[
        :, c_i * 4 + ah * 2 + aw, th - t_min, tw - t_min].set(
        W[:, c_i, ph_i, pw_i])
    pad = ((-t_min, t_max), (-t_min, t_max))
    if h.layout == "NHWC":
        N, H, Wd, _ = x.shape
        xb = x.reshape(N, H // 2, 2, Wd // 2, 2, C) \
            .transpose(0, 1, 3, 5, 2, 4).reshape(N, H // 2, Wd // 2,
                                                 C * 4)
    else:
        N, _, H, Wd = x.shape
        xb = x.reshape(N, C, H // 2, 2, Wd // 2, 2) \
            .transpose(0, 1, 3, 5, 2, 4).reshape(N, C * 4, H // 2,
                                                 Wd // 2)
    return lax.conv_general_dilated(
        xb, W4, window_strides=(1, 1), padding=pad,
        dimension_numbers=h.dimension_numbers)


class _Conv2d(Operator):
    """Forward via one MXU conv; backward via vjp (reference
    GpuConvForward/Backwardx/W/b convolution.h:131-141)."""

    def __init__(self, handle: ConvHandle, odd_padding=None):
        super().__init__()
        self.handle = handle
        self.odd_padding = odd_padding  # extra (t,b,l,r) pad, reference util

    def forward(self, x, W, b=None):
        # an active precision policy runs the conv in its compute dtype
        # (x is cast here too — the stem conv is where an f32 input
        # becomes a 16-bit activation); the trailing astype(x.dtype)
        # then keeps the whole trunk in that precision class
        x, W, b = _cast_compute(x, W, b)
        h = self.handle
        if getattr(h, "space_to_depth", False):
            y = _add_bias(_space_to_depth_conv(x, W, h), b, h.layout)
            return y.astype(x.dtype)
        padding = h.pad_mode if h.pad_mode else h.padding
        if self.odd_padding is not None:
            t, bo, l, r = self.odd_padding
            (p0, p1), (q0, q1) = h.padding
            padding = ((p0 + t, p1 + bo), (q0 + l, q1 + r))
        y = lax.conv_general_dilated(
            x, W,
            window_strides=h.stride,
            padding=padding,
            rhs_dilation=h.dilation,
            dimension_numbers=h.dimension_numbers,
            feature_group_count=h.group,
        )
        return _add_bias(y, b, h.layout).astype(x.dtype)


def conv2d(handle: ConvHandle, x, W, b=None, odd_padding=None):
    """Functional wrapper (parity: reference autograd.conv2d:1721)."""
    if b is None:
        return _Conv2d(handle, odd_padding)(x, W)
    return _Conv2d(handle, odd_padding)(x, W, b)


class ConvTransposeHandle:
    """Static transposed-conv config (ONNX ConvTranspose semantics — the
    capability the reference exposes through its ONNX backend,
    python/singa/sonnx.py ConvTranspose handling).

    Weight layout is (C_in, C_out/group, kH, kW) (ONNX/torch convention).
    """

    def __init__(self, x, kernel_size, stride, padding, in_channels,
                 out_channels, bias=True, group=1, dilation=1,
                 output_padding=0, layout=None):
        from .layout import resolve as _resolve_layout
        self.kernel_size = _pair(kernel_size)
        self.stride = _pair(stride)
        self.dilation = _pair(dilation)
        self.output_padding = _pair(output_padding)
        if (isinstance(padding, (tuple, list)) and len(padding) == 2
                and isinstance(padding[0], (tuple, list))):
            self.padding = tuple(tuple(int(v) for v in p) for p in padding)
        else:
            ph, pw = _pair(padding)
            self.padding = ((ph, ph), (pw, pw))
        self.in_channels = int(in_channels)
        self.out_channels = int(out_channels)
        self.bias = bool(bias)
        self.group = int(group)
        self.layout = _resolve_layout(layout)
        self.dimension_numbers = (self.layout, "OIHW", self.layout)

    def output_shape(self, x_shape):
        if self.layout == "NHWC":
            n, h, w, _ = x_shape
        else:
            n, _, h, w = x_shape
        (p0, p1), (q0, q1) = self.padding
        kh, kw = self.kernel_size
        sh, sw = self.stride
        dh, dw = self.dilation
        oph, opw = self.output_padding
        oh = (h - 1) * sh - p0 - p1 + dh * (kh - 1) + 1 + oph
        ow = (w - 1) * sw - q0 - q1 + dw * (kw - 1) + 1 + opw
        if self.layout == "NHWC":
            return (n, oh, ow, self.out_channels)
        return (n, self.out_channels, oh, ow)


class _ConvTranspose2d(Operator):
    """Transposed conv = input-dilated conv with a spatially-flipped,
    IO-swapped kernel: one `conv_general_dilated` with ``lhs_dilation`` —
    the gradient-of-conv primitive XLA already maps onto the MXU, so
    forward and (vjp) backward are both single fused convs."""

    def __init__(self, handle: ConvTransposeHandle):
        super().__init__()
        self.handle = handle

    def forward(self, x, W, b=None):
        x, W, b = _cast_compute(x, W, b)
        h = self.handle
        kh, kw = h.kernel_size
        dh, dw = h.dilation
        keh, kew = dh * (kh - 1) + 1, dw * (kw - 1) + 1
        (p0, p1), (q0, q1) = h.padding
        oph, opw = h.output_padding
        Wf = jnp.flip(W, axis=(-2, -1))
        if h.group > 1:
            cg = h.in_channels // h.group
            og = h.out_channels // h.group
            Wf = Wf.reshape(h.group, cg, og, kh, kw)
            Wf = Wf.transpose(0, 2, 1, 3, 4).reshape(
                h.out_channels, cg, kh, kw)
        else:
            Wf = Wf.transpose(1, 0, 2, 3)
        y = lax.conv_general_dilated(
            x, Wf,
            window_strides=(1, 1),
            padding=((keh - 1 - p0, keh - 1 - p1 + oph),
                     (kew - 1 - q0, kew - 1 - q1 + opw)),
            lhs_dilation=h.stride,
            rhs_dilation=h.dilation,
            dimension_numbers=h.dimension_numbers,
            feature_group_count=h.group,
        )
        return _add_bias(y, b, h.layout).astype(x.dtype)


def conv_transpose2d(handle: ConvTransposeHandle, x, W, b=None):
    """Functional wrapper for transposed convolution."""
    if b is None:
        return _ConvTranspose2d(handle)(x, W)
    return _ConvTranspose2d(handle)(x, W, b)
