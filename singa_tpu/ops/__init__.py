"""Structured op set: shape-static Handle configs + autograd ops.

TPU-native equivalent of the reference's ``src/model/operation/`` kernels
(convolution.cc, batchnorm.cc, pooling.cc, rnn.cc): each ``*Handle``
precomputes static shape/config once per layer instance, and the op lowers to
a ``jax.lax`` primitive that XLA tiles onto the MXU.
"""

from .conv import ConvHandle, _Conv2d, conv2d
from .batchnorm import BatchNormHandle, _BatchNorm2d, batchnorm_2d
from .pooling import (PoolingHandle, _Pooling2d, pooling_2d,
                      GlobalAveragePool, globalaveragepool)
from .rnn import CudnnRNNHandle, _RNN, rnn_op
from .attention import (flash_attention, ring_attention, attention,
                        _FlashAttention, _RingAttention)

# the `attention` FUNCTION re-export above shadows the submodule
# attribute (`singa_tpu.ops.attention` resolves to the function); this
# alias gives module-level consumers (kernels knobs, FORCE_PALLAS_INTERPRET)
# a non-colliding handle
import sys as _sys
attention_mod = _sys.modules[__name__ + ".attention"]

__all__ = [
    "ConvHandle", "_Conv2d", "conv2d",
    "BatchNormHandle", "_BatchNorm2d", "batchnorm_2d",
    "PoolingHandle", "_Pooling2d", "pooling_2d",
    "GlobalAveragePool", "globalaveragepool",
    "CudnnRNNHandle", "_RNN", "rnn_op",
    "flash_attention", "ring_attention", "attention",
    "_FlashAttention", "_RingAttention",
]
