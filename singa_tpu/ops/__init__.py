"""Structured op set: shape-static Handle configs + autograd ops.

TPU-native equivalent of the reference's ``src/model/operation/`` kernels
(convolution.cc, batchnorm.cc, pooling.cc, rnn.cc): each ``*Handle``
precomputes static shape/config once per layer instance, and the op lowers to
a ``jax.lax`` primitive that XLA tiles onto the MXU.
"""

from .conv import ConvHandle, _Conv2d, conv2d
from .batchnorm import BatchNormHandle, _BatchNorm2d, batchnorm_2d
from .pooling import (PoolingHandle, _Pooling2d, pooling_2d,
                      GlobalAveragePool, globalaveragepool)
from .rnn import CudnnRNNHandle, _RNN, rnn_op
from .attention import (flash_attention, ring_attention, attention,
                        _FlashAttention, _RingAttention)

__all__ = [
    "ConvHandle", "_Conv2d", "conv2d",
    "BatchNormHandle", "_BatchNorm2d", "batchnorm_2d",
    "PoolingHandle", "_Pooling2d", "pooling_2d",
    "GlobalAveragePool", "globalaveragepool",
    "CudnnRNNHandle", "_RNN", "rnn_op",
    "flash_attention", "ring_attention", "attention",
    "_FlashAttention", "_RingAttention",
]
