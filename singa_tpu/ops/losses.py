"""Memory-lean loss kernels.

:func:`fused_ce_head` — the LM head matmul and softmax-cross-entropy
fused into one chunked computation: the (tokens, vocab) logits matrix —
the dominant HBM cost of large-vocab LM training (B·S·V floats, often
bigger than the whole model) — is NEVER materialised. The forward scans
vocab chunks with an online logsumexp; the backward (custom_vjp)
rescans, rebuilding each chunk's probabilities from the saved (O(tokens))
logsumexp, exactly the flash-attention residual trick applied to the
classifier head. No reference counterpart (the reference computes full
logits then CrossEntropyFwd, src/model/operation/../autograd).

Vocab-parallel: pass ``axis_name`` when the head weight's columns are
sharded over a mesh axis (``ColumnParallelLinear``-style). Each rank
scans only its own V/tp vocab slice; the per-rank online logsumexp
states are merged with one pmax+psum pair and the target logit with one
psum, so no rank ever materialises — or even scans — another rank's
vocab columns. The backward psums the (D-wide) hidden-state cotangent
only; dW/db stay rank-local. Outside a mesh the collectives vanish and
the same code is the single-device kernel.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator

_NEG = -1e30


def _chunks(W, b, chunk):
    """(D, V), (V,) -> per-chunk xs (n, D, c) / (n, c), -inf-padded bias
    so padded columns never contribute to the logsumexp."""
    D, V = W.shape
    n = (V + chunk - 1) // chunk
    pad = n * chunk - V
    if pad:
        W = jnp.pad(W, ((0, 0), (0, pad)))
        b = jnp.pad(b, (0, pad), constant_values=_NEG)
    return (W.reshape(D, n, chunk).transpose(1, 0, 2),
            b.reshape(n, chunk), n, pad)


def _shard_ctx(axis_name, W):
    """(live?, column offset of this rank's vocab slice). ``W`` is the
    rank-local slice inside shard_map, so the offset is index * local-V."""
    if not axis_name:
        return False, 0
    from ..parallel.communicator import active_axis
    if not active_axis(axis_name):
        return False, 0
    return True, lax.axis_index(axis_name) * W.shape[1]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_ce_head(h, W, b, ids, chunk=8192, axis_name=None):
    """Mean cross-entropy of ``softmax(h @ W + b)`` against ``ids``.

    h: (N, D) flattened tokens; W: (D, V); b: (V,); ids: (N,) integer
    (or float-encoded) target ids. Peak memory is O(N·chunk), not O(N·V).
    With ``axis_name`` and a live mesh axis, W/b hold this rank's vocab
    slice and ids stay global — see the module docstring.
    """
    return _fwd(h, W, b, ids, chunk, axis_name)[0]


def _zero_ct(x):
    """Cotangent of a non-differentiable input: float zeros for float
    encodings of ids, float0 for true integer ids."""
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return jnp.zeros_like(x)
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _fwd(h, W, b, ids, chunk, axis_name=None):
    sharded, offset = _shard_ctx(axis_name, W)
    hf = h.astype(jnp.float32)
    idi = ids.astype(jnp.int32) - offset        # local coords of targets
    Wc, bc, n, _pad = _chunks(W.astype(jnp.float32),
                              b.astype(jnp.float32), chunk)
    N = hf.shape[0]

    # a target this rank does not own may still land inside the last
    # chunk's -1e30-padded tail (local V < n*chunk): without the bound
    # below it would accumulate the pad bias into tgt and blow up the
    # loss by ~1e30 after the cross-rank psum
    owned = (idi >= 0) & (idi < W.shape[1])

    def step(carry, inputs):
        m, l, tgt = carry
        ci, Wk, bk = inputs
        logits = hf @ Wk + bk                        # (N, chunk)
        m_new = jnp.maximum(m, jnp.max(logits, -1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), -1)
        loc = idi - ci * chunk
        hit = (loc >= 0) & (loc < chunk) & owned
        got = jnp.take_along_axis(
            logits, jnp.clip(loc, 0, chunk - 1)[:, None], 1)[:, 0]
        tgt = tgt + jnp.where(hit, got, 0.0)
        return (m_new, l, tgt), None

    zero = jnp.zeros((N,), jnp.float32) + 0.0 * jnp.sum(hf, -1)
    init = (zero + _NEG, zero, zero)
    (m, l, tgt), _ = lax.scan(step, init,
                              (jnp.arange(n), Wc, bc))
    if sharded:
        # merge per-rank online-softmax states: one pmax + two psums
        # total, all O(N) — never O(V)
        m_all = lax.pmax(m, axis_name)
        l = lax.psum(l * jnp.exp(m - m_all), axis_name)
        tgt = lax.psum(tgt, axis_name)          # exactly one rank hit
        m = m_all
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    loss = jnp.mean(lse - tgt)
    return loss, (h, W, b, ids, lse)


def _bwd(chunk, axis_name, res, g):
    h, W, b, ids, lse = res
    sharded, offset = _shard_ctx(axis_name, W)
    idi = ids.astype(jnp.int32) - offset
    hf = h.astype(jnp.float32)
    Wc, bc, n, pad = _chunks(W.astype(jnp.float32),
                             b.astype(jnp.float32), chunk)
    N = hf.shape[0]
    gN = (g / N).astype(jnp.float32)

    owned = (idi >= 0) & (idi < W.shape[1])   # same bound as forward

    def step(dh, inputs):
        ci, Wk, bk = inputs
        logits = hf @ Wk + bk
        p = jnp.exp(logits - lse[:, None])          # chunk of softmax
        loc = idi - ci * chunk
        hit = (loc >= 0) & (loc < chunk) & owned
        onehot = jax.nn.one_hot(jnp.clip(loc, 0, chunk - 1), chunk,
                                dtype=jnp.float32) * hit[:, None]
        dlog = (p - onehot) * gN
        dh = dh + dlog @ Wk.T
        dWk = hf.T @ dlog
        dbk = jnp.sum(dlog, 0)
        return dh, (dWk, dbk)

    dh, (dWks, dbks) = lax.scan(step, hf * 0.0,
                                (jnp.arange(n), Wc, bc))
    if sharded:
        # h is replicated over the vocab axis; each rank produced only
        # its slice's contribution to dh. dW/db stay rank-local.
        dh = lax.psum(dh, axis_name)
    V = W.shape[1]
    dW = dWks.transpose(1, 0, 2).reshape(W.shape[0],
                                         n * chunk)[:, :V]
    db = dbks.reshape(n * chunk)[:V]
    return (dh.astype(h.dtype), dW.astype(W.dtype), db.astype(b.dtype),
            _zero_ct(ids))


fused_ce_head.defvjp(_fwd, _bwd)


class _FusedCEHead(Operator):
    """Tape op: (hidden, W, b, ids) -> scalar mean CE, never
    materialising the logits. ``axis_name``: vocab-parallel mesh axis
    (W/b columns sharded over it) or None."""

    def __init__(self, chunk=8192, axis_name=None):
        super().__init__()
        self.chunk = chunk
        self.axis_name = axis_name

    def forward(self, h, W, b, ids):
        flat = h.reshape(-1, h.shape[-1])
        return fused_ce_head(flat, W, b, ids.reshape(-1), self.chunk,
                             self.axis_name)


def fused_softmax_cross_entropy(hidden, W, b, ids, chunk=8192,
                                axis_name=None):
    """Functional tape API over :class:`_FusedCEHead`; ``hidden`` may be
    (B, S, D) with (B, S) ids. ``axis_name`` turns on the vocab-parallel
    cross-shard reduction when W's columns live sharded over that mesh
    axis."""
    return _FusedCEHead(chunk, axis_name)(hidden, W, b, ids)


# the Layer-shaped fused heads live in singa_tpu.layer (FusedCEHead for
# Model code, FusedCEHeadStage for heterogeneous pipelines); this module
# stays layer-free so the kernel imports without the zoo
