"""Memory-lean loss kernels.

:func:`fused_ce_head` — the LM head matmul and softmax-cross-entropy
fused into one chunked computation: the (tokens, vocab) logits matrix —
the dominant HBM cost of large-vocab LM training (B·S·V floats, often
bigger than the whole model) — is NEVER materialised. The forward scans
vocab chunks with an online logsumexp; the backward (custom_vjp)
rescans, rebuilding each chunk's probabilities from the saved (O(tokens))
logsumexp, exactly the flash-attention residual trick applied to the
classifier head. No reference counterpart (the reference computes full
logits then CrossEntropyFwd, src/model/operation/../autograd).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator

_NEG = -1e30


def _chunks(W, b, chunk):
    """(D, V), (V,) -> per-chunk xs (n, D, c) / (n, c), -inf-padded bias
    so padded columns never contribute to the logsumexp."""
    D, V = W.shape
    n = (V + chunk - 1) // chunk
    pad = n * chunk - V
    if pad:
        W = jnp.pad(W, ((0, 0), (0, pad)))
        b = jnp.pad(b, (0, pad), constant_values=_NEG)
    return (W.reshape(D, n, chunk).transpose(1, 0, 2),
            b.reshape(n, chunk), n, pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def fused_ce_head(h, W, b, ids, chunk=8192):
    """Mean cross-entropy of ``softmax(h @ W + b)`` against ``ids``.

    h: (N, D) flattened tokens; W: (D, V); b: (V,); ids: (N,) integer
    (or float-encoded) target ids. Peak memory is O(N·chunk), not O(N·V).
    """
    return _fwd(h, W, b, ids, chunk)[0]


def _zero_ct(x):
    """Cotangent of a non-differentiable input: float zeros for float
    encodings of ids, float0 for true integer ids."""
    if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return jnp.zeros_like(x)
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _fwd(h, W, b, ids, chunk):
    hf = h.astype(jnp.float32)
    idi = ids.astype(jnp.int32)
    Wc, bc, n, _pad = _chunks(W.astype(jnp.float32),
                              b.astype(jnp.float32), chunk)
    N = hf.shape[0]

    def step(carry, inputs):
        m, l, tgt = carry
        ci, Wk, bk = inputs
        logits = hf @ Wk + bk                        # (N, chunk)
        m_new = jnp.maximum(m, jnp.max(logits, -1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), -1)
        loc = idi - ci * chunk
        hit = (loc >= 0) & (loc < chunk)
        got = jnp.take_along_axis(
            logits, jnp.clip(loc, 0, chunk - 1)[:, None], 1)[:, 0]
        tgt = tgt + jnp.where(hit, got, 0.0)
        return (m_new, l, tgt), None

    zero = jnp.zeros((N,), jnp.float32) + 0.0 * jnp.sum(hf, -1)
    init = (zero + _NEG, zero, zero)
    (m, l, tgt), _ = lax.scan(step, init,
                              (jnp.arange(n), Wc, bc))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    loss = jnp.mean(lse - tgt)
    return loss, (h, W, b, ids, lse)


def _bwd(chunk, res, g):
    h, W, b, ids, lse = res
    idi = ids.astype(jnp.int32)
    hf = h.astype(jnp.float32)
    Wc, bc, n, pad = _chunks(W.astype(jnp.float32),
                             b.astype(jnp.float32), chunk)
    N = hf.shape[0]
    gN = (g / N).astype(jnp.float32)

    def step(dh, inputs):
        ci, Wk, bk = inputs
        logits = hf @ Wk + bk
        p = jnp.exp(logits - lse[:, None])          # chunk of softmax
        loc = idi - ci * chunk
        hit = (loc >= 0) & (loc < chunk)
        onehot = jax.nn.one_hot(jnp.clip(loc, 0, chunk - 1), chunk,
                                dtype=jnp.float32) * hit[:, None]
        dlog = (p - onehot) * gN
        dh = dh + dlog @ Wk.T
        dWk = hf.T @ dlog
        dbk = jnp.sum(dlog, 0)
        return dh, (dWk, dbk)

    dh, (dWks, dbks) = lax.scan(step, hf * 0.0,
                                (jnp.arange(n), Wc, bc))
    V = W.shape[1]
    dW = dWks.transpose(1, 0, 2).reshape(W.shape[0],
                                         n * chunk)[:, :V]
    db = dbks.reshape(n * chunk)[:V]
    return (dh.astype(h.dtype), dW.astype(W.dtype), db.astype(b.dtype),
            _zero_ct(ids))


fused_ce_head.defvjp(_fwd, _bwd)


class _FusedCEHead(Operator):
    """Tape op: (hidden, W, b, ids) -> scalar mean CE, never
    materialising the logits."""

    def __init__(self, chunk=8192):
        super().__init__()
        self.chunk = chunk

    def forward(self, h, W, b, ids):
        flat = h.reshape(-1, h.shape[-1])
        return fused_ce_head(flat, W, b, ids.reshape(-1), self.chunk)


def fused_softmax_cross_entropy(hidden, W, b, ids, chunk=8192):
    """Functional tape API over :class:`_FusedCEHead`; ``hidden`` may be
    (B, S, D) with (B, S) ids."""
    return _FusedCEHead(chunk)(hidden, W, b, ids)
