"""Fused conv epilogue: inference BN scale/shift + ReLU in one pass.

At inference/serving the BN that follows a conv is a frozen per-channel
affine: ``y = x * scale' + shift'`` with ``scale' = scale *
rsqrt(running_var + eps)`` and ``shift' = bias - running_mean * scale'``
— the folding math stays f32 (the mixed-precision contract for norm
statistics) and only the final elementwise pass touches the activation
dtype. The Pallas kernel applies that affine AND the ReLU that follows
in ONE HBM pass over the conv output, instead of BN and ReLU each
re-reading the full activation. The RESIDUAL tail
(conv→BN→add→ReLU — every ResNet block's exit) fuses the same way:
``autograd.add`` tags a sum whose operand is a tagged BN output, and
the consuming ReLU emits the scale/shift + skip-add + relu as one
pass (two full-size tiles per block, so the VMEM budget halves the
row block).

Wiring is a peephole, not a graph rewrite: the inference BN op tags its
output Tensor with the folding ingredients (``ops/batchnorm.py``), and
``autograd.relu`` — when the module is :func:`enabled`, the pass is
traced (serving programs, compiled eval; eager eval skips it so nothing
computes twice), training is off, and the kernel-eligibility gate
accepts — consumes the tag and emits the fused kernel on the conv
output directly. Everything else falls through to the reference ops.

House pattern as ``ops/attention.py``/``ops/fused_optim.py``:
``FORCE_PALLAS_INTERPRET`` runs the exact kernel on CPU for the
``pallas`` CI tier; selection is measured-not-guessed (OFF by default,
bench steers it through the banked ``conv_epilogue_ab`` A/B record).
"""

from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from . import fused_optim
from .fused_optim import HAS_PALLAS

if HAS_PALLAS:
    from jax.experimental import pallas as pl

_ENABLED = False


def enable(on=True):
    """Process-wide opt-in (bench/serving set it from the measured A/B
    winner; never on by default). Returns the previous value."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    return prev


@contextlib.contextmanager
def enabled_scope(on=True):
    prev = enable(on)
    try:
        yield
    finally:
        enable(prev)


def enabled():
    return _ENABLED


def _interpret():
    return fused_optim.FORCE_PALLAS_INTERPRET or \
        jax.default_backend() != "tpu"


def _available(n_elems):
    # one eligibility policy for every fused kernel (backend, force-
    # reference scope, interpret hook, min size) — fused_optim owns it
    return fused_optim.available(n_elems)


def _affine_relu_cols_kernel(x_ref, s_ref, b_ref, o_ref):
    """Channels-last rows: scale/shift broadcast over rows."""
    y = x_ref[...].astype(jnp.float32) * s_ref[...] + b_ref[...]
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


def _affine_relu_rows_kernel(x_ref, s_ref, b_ref, o_ref):
    """Channel-per-row (NCHW collapsed to (N*C, H*W)): scale/shift are
    per-row columns."""
    y = x_ref[...].astype(jnp.float32) * s_ref[...] + b_ref[...]
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


def _affine_add_relu_cols_kernel(x_ref, r_ref, s_ref, b_ref, o_ref):
    """Residual tail, channels-last: scale/shift + residual add + relu
    in the one pass."""
    y = x_ref[...].astype(jnp.float32) * s_ref[...] + b_ref[...] \
        + r_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


def _affine_add_relu_rows_kernel(x_ref, r_ref, s_ref, b_ref, o_ref):
    """Residual tail, channel-per-row (NCHW collapsed)."""
    y = x_ref[...].astype(jnp.float32) * s_ref[...] + b_ref[...] \
        + r_ref[...].astype(jnp.float32)
    o_ref[...] = jnp.maximum(y, 0.0).astype(o_ref.dtype)


# per-block VMEM budget: input + output tiles must fit comfortably in
# the ~16 MB of VMEM alongside scratch; 4 MB for the input block keeps
# the pair under half of it
_BLOCK_BYTE_BUDGET = 4 << 20


def _block_rows(rows, row_elems, itemsize=4, n_inputs=1):
    """Largest row-block that tiles ``rows`` AND fits the VMEM budget
    (a (32, 64, 112, 112) NCHW activation has 12544-element rows — an
    uncapped 256-row block would be 12.8 MB and fail Mosaic on real
    hardware even though interpret-mode CI accepts it). ``n_inputs``
    counts the FULL-SIZE input tiles resident at once (2 for the
    residual-tail kernel: activation + residual), so the budget stays
    honest when the kernel reads two big arrays. None when even the
    minimum legal block exceeds the budget — the caller falls back to
    the reference elementwise math."""
    for b in (256, 128, 64, 32, 16, 8):
        if rows % b == 0 and n_inputs * b * row_elems * itemsize <= \
                _BLOCK_BYTE_BUDGET:
            return b
    return None


def _pad_axis0(arr, rows):
    pad = rows - arr.shape[0]
    if pad:
        arr = jnp.concatenate(
            [arr, jnp.zeros((pad,) + arr.shape[1:], arr.dtype)])
    return arr


def _reference(x, scale, shift, layout, residual=None):
    b = (1, x.shape[1], 1, 1) if layout == "NCHW" \
        else (1, 1, 1, x.shape[-1])
    y = x.astype(jnp.float32) * scale.reshape(b) + shift.reshape(b)
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    return jnp.maximum(y, 0.0).astype(x.dtype)


def _scale_shift_relu_impl(x, scale, shift, layout, residual):
    """One tiling for both tails: ``max(x*s + b [+ residual], 0)`` in a
    single Pallas pass. ``residual`` (same shape as ``x``) turns the
    plain affine+relu into the conv→BN→add→ReLU residual tail; the
    VMEM budget then accounts for TWO full-size tiles per block."""
    N = x.shape[0]
    scale = jnp.asarray(scale, jnp.float32)
    shift = jnp.asarray(shift, jnp.float32)
    n_inputs = 1 if residual is None else 2
    if layout == "NHWC":
        C = x.shape[-1]
        m = x.size // C
        rows = -(-m // 8) * 8
        br = _block_rows(rows, C, x.dtype.itemsize, n_inputs)
        if br is None:
            return _reference(x, scale, shift, layout, residual)
        # a custom call cost analysis can't count — the step_flops
        # reference twin keys off this mark, same as the optimizer
        # kernels
        fused_optim._mark("epilogue")
        xr = _pad_axis0(x.reshape(m, C), rows)
        blk = pl.BlockSpec((br, C), lambda i: (i, 0))
        vec = pl.BlockSpec((1, C), lambda i: (0, 0))
        args = [xr]
        specs = [blk]
        kernel = _affine_relu_cols_kernel
        if residual is not None:
            args.append(_pad_axis0(residual.reshape(m, C), rows))
            specs.append(blk)
            kernel = _affine_add_relu_cols_kernel
        out = pl.pallas_call(
            kernel,
            grid=(rows // br,),
            in_specs=specs + [vec, vec],
            out_specs=blk,
            out_shape=jax.ShapeDtypeStruct((rows, C), x.dtype),
            interpret=_interpret(),
        )(*args, scale.reshape(1, C), shift.reshape(1, C))
        return out[:m].reshape(x.shape)
    # NCHW: collapse to one row per (image, channel); the per-row
    # scale/shift columns are a tiny (N*C, 1) tile
    C = x.shape[1]
    L = x.size // (N * C)
    rows = -(-(N * C) // 8) * 8
    br = _block_rows(rows, L, x.dtype.itemsize, n_inputs)
    if br is None:
        return _reference(x, scale, shift, layout, residual)
    fused_optim._mark("epilogue")
    xr = _pad_axis0(x.reshape(N * C, L), rows)
    s_rows = _pad_axis0(jnp.tile(scale, N).reshape(N * C, 1), rows)
    b_rows = _pad_axis0(jnp.tile(shift, N).reshape(N * C, 1), rows)
    blk = pl.BlockSpec((br, L), lambda i: (i, 0))
    vec = pl.BlockSpec((br, 1), lambda i: (i, 0))
    args = [xr]
    specs = [blk]
    kernel = _affine_relu_rows_kernel
    if residual is not None:
        args.append(_pad_axis0(residual.reshape(N * C, L), rows))
        specs.append(blk)
        kernel = _affine_add_relu_rows_kernel
    out = pl.pallas_call(
        kernel,
        grid=(rows // br,),
        in_specs=specs + [vec, vec],
        out_specs=blk,
        out_shape=jax.ShapeDtypeStruct((rows, L), x.dtype),
        interpret=_interpret(),
    )(*args, s_rows, b_rows)
    return out[:N * C].reshape(x.shape)


def scale_shift_relu(x, scale, shift, layout="NCHW"):
    """``max(x * scale + shift, 0)`` with per-channel f32 scale/shift in
    one Pallas pass over a 4-D activation. ``layout`` names where the
    channel axis lives. Returns an array of x's shape/dtype. Shapes
    whose minimum legal block would blow the VMEM budget compute the
    same math with plain XLA ops instead."""
    return _scale_shift_relu_impl(x, scale, shift, layout, None)


def scale_shift_add_relu(x, scale, shift, residual, layout="NCHW"):
    """The residual tail: ``max(x * scale + shift + residual, 0)`` in
    ONE pass over the conv output — BN fold, skip-connection add, and
    ReLU without re-reading the activation three times. ``residual``
    must match ``x``'s shape; same decline-to-reference rules as
    :func:`scale_shift_relu` (the block budget counts both tiles)."""
    if tuple(residual.shape) != tuple(x.shape):
        return _reference(x, jnp.asarray(scale, jnp.float32),
                          jnp.asarray(shift, jnp.float32), layout,
                          residual)
    return _scale_shift_relu_impl(x, scale, shift, layout, residual)


def fold_bn(scale, bias, rmean, rvar, eps):
    """Frozen-BN folding in f32 (the norm-statistics precision
    contract): returns per-channel ``(scale', shift')`` such that
    ``bn(x) == x * scale' + shift'``."""
    scale = jnp.asarray(scale, jnp.float32)
    inv = jax.lax.rsqrt(jnp.asarray(rvar, jnp.float32) + eps)
    s2 = scale * inv
    b2 = jnp.asarray(bias, jnp.float32) \
        - jnp.asarray(rmean, jnp.float32) * s2
    return s2, b2


def try_relu_epilogue(x_tensor):
    """ReLU peephole: when ``x_tensor`` is a tagged inference-BN output
    — or a tagged ``bn_out + residual`` sum (the conv→BN→add→ReLU
    residual tail, ``autograd.add`` sets the tag) — and the fused
    epilogue is both enabled and eligible, return the tail computed by
    the one-pass kernel on the BN's INPUT (+ the residual); else None
    (caller runs the reference ReLU op). Only fires inside a trace —
    in eager evaluation the BN output already exists concretely, so
    recomputing it fused would double the work; under a jit the
    reference BN/add outputs this peephole bypasses are dead code XLA
    eliminates."""
    residual = None
    tag = getattr(x_tensor, "_bn_epilogue", None)
    if tag is None:
        add_tag = getattr(x_tensor, "_bn_add_epilogue", None)
        if add_tag is None:
            return None
        tag, residual = add_tag
    if not _ENABLED:
        return None
    from ..autograd_base import is_training
    if is_training():
        # a frozen-stats BN (use_global_stats) still BACKPROPS through
        # scale/bias in training — and the residual branch backprops
        # too; the fused output carries no tape creator, so fusing
        # here would silently drop those gradients
        return None
    xin, scale, bias, rmean, rvar, eps, layout = tag
    arr = getattr(xin, "data", xin)
    if arr.ndim != 4 or not _available(arr.size):
        return None
    if not isinstance(arr, jax.core.Tracer):
        return None
    res_arr = None
    if residual is not None:
        res_arr = getattr(residual, "data", residual)
        if tuple(res_arr.shape) != tuple(arr.shape):
            # a broadcasting skip-connection is not the tail this
            # kernel fuses — decline to the reference add+relu
            return None
    s2, b2 = fold_bn(getattr(scale, "data", scale),
                     getattr(bias, "data", bias),
                     getattr(rmean, "data", rmean),
                     getattr(rvar, "data", rvar), eps)
    from ..tensor import Tensor
    if res_arr is not None:
        out = scale_shift_add_relu(arr, s2, b2, res_arr, layout=layout)
    else:
        out = scale_shift_relu(arr, s2, b2, layout=layout)
    return Tensor(data=out, device=getattr(x_tensor, "device", None),
                  requires_grad=False)
