"""Attention: fused flash kernel + ring (sequence-parallel) attention.

TPU-first components with no reference equivalent (the reference composes
attention from primitive autograd ops in examples and has no sequence
parallelism — SURVEY.md §5 'long-context: absent'); these are the
long-context machinery the TPU build makes first-class:

- :func:`flash_attention` — blocked online-softmax attention. On TPU the
  forward runs as a Pallas kernel (grid over (batch*heads, q-blocks),
  streaming k/v blocks through VMEM with running max/sum accumulators, so
  the S×S score matrix never hits HBM). Elsewhere (CPU mesh tests) an
  identical-math `lax.scan` implementation runs. Backward recomputes
  per-block scores (flash style) via the scan path under `jax.custom_vjp`.
- :func:`ring_attention` — q/k/v sharded over a 'seq' mesh axis inside
  `shard_map`; k/v blocks rotate around the ICI ring via `lax.ppermute`
  while each device folds them into its online-softmax accumulator.
  Communication overlaps compute; memory per chip is O(S/n · S/n).
"""

from __future__ import annotations

import functools
import math
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator
from ..mixed_precision import cast_compute as _cast_compute
from ..parallel.communicator import axis_size as _axis_size

_NEG_INF = -1e30


def _block_scan_attention(q, k, v, causal, scale, block_k,
                          q_offset=0, k_offset=0):
    """Online-softmax attention, scanning over key blocks.

    q: (B, H, Sq, D), k/v: (B, H, Sk, D). Returns (out, m, l) so partial
    results can be merged (ring attention needs the accumulators).
    ``q_offset``/``k_offset`` are global position offsets for causal
    masking of sharded sequences.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_k = min(block_k, Sk)
    nblocks = (Sk + block_k - 1) // block_k
    pad = nblocks * block_k - Sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)

    q_pos = q_offset + jnp.arange(Sq)

    def step(carry, inputs):
        out, m, l = carry
        blk_idx, kblk, vblk = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * scale
        k_pos = k_offset + blk_idx * block_k + jnp.arange(block_k)
        mask = k_pos[None, :] < (Sk + k_offset)  # padding mask
        if causal:
            mask = mask & (k_pos[None, :] <= q_pos[:, None])
        s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        out_new = out * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return (out_new, m_new, l_new), None

    # derive accumulators from q so they carry its shard_map varying-axes
    # type (fresh zeros would be 'unvarying' and fail the scan typecheck)
    zero = q.astype(jnp.float32) * 0.0
    init = (zero,
            jnp.max(zero, axis=-1) + _NEG_INF,
            jnp.sum(zero, axis=-1))
    (out, m, l), _ = lax.scan(
        step, init, (jnp.arange(nblocks), kb, vb))
    return out, m, l


def _merge_partials(out, m, l):
    """Normalise a streamed accumulator into the final attention output."""
    return (out / jnp.maximum(l, 1e-30)[..., None])


def _scan_flash_fwd(q, k, v, causal, scale, block_k=512):
    """Scan-path forward returning (out, lse). lse = m + log(l) is the
    log-sum-exp of each query row — the O(S) residual the flash backward
    rebuilds probabilities from."""
    out, m, l = _block_scan_attention(q.astype(jnp.float32),
                                      k.astype(jnp.float32),
                                      v.astype(jnp.float32),
                                      causal, scale, block_k)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return _merge_partials(out, m, l).astype(q.dtype), lse


def _reference_attention(q, k, v, causal, scale, block_k=512):
    return _scan_flash_fwd(q, k, v, causal, scale, block_k)[0]


def _scan_flash_bwd(q, k, v, out, lse, g, causal, scale, block_k):
    """Blocked flash backward (everywhere-correct math; the Pallas TPU
    kernels below implement the same recurrence). Probabilities are
    recomputed per k-block from (q, k, lse) — never an S×S matrix — so
    residual memory stays O(S·D):

        delta = rowsum(dO * O)
        P     = exp(S - lse)           (block recompute)
        dV    = Pᵀ dO
        dS    = P * (dO Vᵀ - delta) * scale
        dQ    = dS K ;  dK = dSᵀ Q
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_k = min(block_k, Sk)
    nblocks = (Sk + block_k - 1) // block_k
    pad = nblocks * block_k - Sk
    kp, vp = k, v
    if pad:
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = kp.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = vp.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    delta = jnp.sum(gf * out.astype(jnp.float32), axis=-1)   # (B, H, Sq)
    q_pos = jnp.arange(Sq)

    def step(dq, inputs):
        blk, kblk, vblk = inputs
        kblk = kblk.astype(jnp.float32)
        vblk = vblk.astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kblk,
                       preferred_element_type=jnp.float32) * scale
        k_pos = blk * block_k + jnp.arange(block_k)
        mask = k_pos[None, :] < Sk
        if causal:
            mask = mask & (k_pos[None, :] <= q_pos[:, None])
        s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])           # masked entries -> 0
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vblk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kblk,
                             preferred_element_type=jnp.float32)
        dkb = jnp.einsum("bhqk,bhqd->bhkd", ds, qf,
                         preferred_element_type=jnp.float32)
        dvb = jnp.einsum("bhqk,bhqd->bhkd", p, gf,
                         preferred_element_type=jnp.float32)
        return dq, (dkb, dvb)

    # qf * 0.0 (not fresh zeros) so the carry inherits qf's shard_map
    # varying-axes type — same workaround as the forward scan init
    dq, (dkbs, dvbs) = lax.scan(
        step, qf * 0.0, (jnp.arange(nblocks), kb, vb))
    dk = dkbs.transpose(1, 2, 0, 3, 4).reshape(
        B, H, nblocks * block_k, D)[:, :, :Sk]
    dv = dvbs.transpose(1, 2, 0, 3, 4).reshape(
        B, H, nblocks * block_k, D)[:, :, :Sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernels (forward + backward)
#
# All kernels run a 3-D grid whose innermost dimension streams the far-side
# blocks through VMEM — K/V blocks for the forward/dQ kernels, Q blocks for
# the dK/dV kernel — so VMEM holds O(block · D) regardless of sequence
# length (the whole point of the long-context path). TPU grids iterate the
# trailing dimension sequentially, which is what makes the scratch-ref
# accumulator pattern below sound.
# ---------------------------------------------------------------------------

try:  # pallas import is TPU-oriented; keep CPU-only installs working
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False

# Test hook: run kernels in interpreter mode so CPU CI validates the exact
# kernel math the TPU executes (tests/test_attention.py flips this).
FORCE_PALLAS_INTERPRET = False


_DECLINE_LOGGED = set()

# Mosaic requires the last two dims of every block to be (8k, 128k) or
# equal to the array's dims, so per-row statistics (m/l/lse/delta) are
# carried lane-broadcast at this width — the same layout the canonical
# TPU flash kernels use. Interpreter mode never enforced this; the real
# chip does.
_LANES = 128


_ENV_BLOCK_CACHE = {}
_ENV_BLOCK_WARNED = set()


def _env_block(name):
    """Validated SINGA_FLASH_BLOCK_* override, or None. A value that is
    not a positive integer is warned about ONCE and ignored (the
    adaptive pick stands) instead of raising inside every attention
    dispatch; validation is memoized per raw value so the hot path pays
    one dict lookup."""
    v = os.environ.get(name)
    if not v:
        return None
    key = (name, v)
    if key not in _ENV_BLOCK_CACHE:
        val = None
        try:
            iv = int(v)
            if iv > 0:
                val = iv
        except ValueError:
            pass
        if val is None:
            import warnings
            warnings.warn(f"{name}={v!r} is not a positive integer; "
                          "ignoring the override", stacklevel=3)
        _ENV_BLOCK_CACHE[key] = val
    return _ENV_BLOCK_CACHE[key]


def _pick_blocks(Sq, Sk):
    """Largest Pallas block sizes that tile the sequence lengths.

    Measured on TPU v5e (B8 H8 S1024 D64, fwd+bwd, slope-readback
    timing): (512, 256) runs 3.1x faster than the (128, 128) minimum —
    bigger q tiles amortise the k/v stream and keep the MXU busy.
    Falls back through 256 to the 128-lane minimum when the sequence
    length doesn't divide, so short or odd-length shapes still get the
    fused kernel whenever a legal tiling exists. Override for tuning
    with SINGA_FLASH_BLOCK_Q / SINGA_FLASH_BLOCK_K — an override that
    does not divide the sequence length is warned about (once per
    shape) and ignored, so a bad knob can never silently cost the
    fused kernel."""
    bq = min(next((b for b in (512, 256, 128) if Sq % b == 0), 128), Sq)
    bk = min(next((b for b in (256, 128) if Sk % b == 0), 128), Sk)
    # a partial override keeps the adaptive pick for the other axis
    out = []
    for name, env, adaptive, S in (("Q", _env_block("SINGA_FLASH_BLOCK_Q"),
                                    bq, Sq),
                                   ("K", _env_block("SINGA_FLASH_BLOCK_K"),
                                    bk, Sk)):
        if env is not None:
            # clamp to the sequence length FIRST: an oversized override
            # would otherwise reach the kernel unclamped and launch a
            # zero-size grid (output never written)
            env = min(env, S)
            if S % env:
                # a non-dividing override would silently cost the fused
                # kernel (_use_pallas declines): warn once per shape and
                # keep the adaptive pick instead
                key = (name, env, S)
                if key not in _ENV_BLOCK_WARNED:
                    _ENV_BLOCK_WARNED.add(key)
                    import warnings
                    warnings.warn(
                        f"SINGA_FLASH_BLOCK_{name}={env} does not divide "
                        f"sequence length {S}; using the adaptive "
                        f"{adaptive} instead", stacklevel=3)
                env = None
        out.append(env if env is not None else adaptive)
    return tuple(out)


def _pallas_blocks(q, k):
    """Adaptive block pick + kernel-eligibility check in one step:
    (block_q, block_k) when the Pallas kernels should run for these
    shapes, else None (scan-path fallback)."""
    bq, bk = _pick_blocks(q.shape[2], k.shape[2])
    return (bq, bk) if _use_pallas(q, k, bq, bk) else None


def _use_pallas(q, k, block_q, block_k):
    if not HAS_PALLAS:
        return False
    bq = min(block_q, q.shape[2])
    bk = min(block_k, k.shape[2])
    if q.shape[2] % bq or k.shape[2] % bk:
        if jax.default_backend() == "tpu":
            # on TPU this silently costs the fused kernel — say so once
            # per shape so an odd sequence length is a visible choice,
            # not a hidden perf cliff
            sig = (q.shape[2], k.shape[2], bq, bk)
            if sig not in _DECLINE_LOGGED:
                _DECLINE_LOGGED.add(sig)
                import warnings
                warnings.warn(
                    f"flash attention: sequence lengths q={q.shape[2]} "
                    f"k={k.shape[2]} not divisible by blocks "
                    f"({bq},{bk}); using the unfused scan path — pad "
                    "the sequence to a multiple of 128 to get the "
                    "Pallas kernel", stacklevel=3)
        return False
    return jax.default_backend() == "tpu" or FORCE_PALLAS_INTERPRET


def _interpret():
    return FORCE_PALLAS_INTERPRET or jax.default_backend() != "tpu"


def _causal_positions(qi, kj, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos, k_pos


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      acc_ref, m_ref, l_ref, *,
                      causal, scale, block_q, block_k, nkb,
                      offset_ref=None):
    """``offset_ref`` (optional (1,1) i32 input placed before q_ref by the
    caller): global-position delta ``q_offset - k_offset`` for causal
    masking when q and k come from different sequence shards (ring
    attention). With a delta the k-grid is not pruned — masking handles
    everything — so the write happens at the final k block."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    if causal and offset_ref is None:
        run = kj * block_k <= (qi + 1) * block_q - 1
    else:
        run = kj >= 0

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, D)
        kblk = k_ref[0].astype(jnp.float32)       # (block_k, D)
        vblk = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        mask = None
        if causal:
            q_pos, k_pos = _causal_positions(qi, kj, block_q, block_k)
            if offset_ref is not None:
                q_pos = q_pos + offset_ref[0, 0]
            mask = k_pos <= q_pos
            s = jnp.where(mask, s, _NEG_INF)
        # m/l live lane-broadcast as (block_q, _LANES); every lane of a
        # row holds the same scalar
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new[:, :1])
        if mask is not None and offset_ref is not None:
            # a FULLY-masked row has m_new == _NEG_INF (finite), making
            # exp(s - m_new) == 1 on masked entries — zero them explicitly
            # (offset grids are not pruned, so such blocks do occur)
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    # the last k-block this q-block attends to writes the result
    last = jnp.minimum(nkb - 1, ((qi + 1) * block_q - 1) // block_k) \
        if (causal and offset_ref is None) else nkb - 1

    @pl.when(kj == last)
    def _write():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l[:, :1]).astype(o_ref.dtype)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                         dq_ref, acc_ref, *,
                         causal, scale, block_q, block_k, nkb):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    run = (kj * block_k <= (qi + 1) * block_q - 1) if causal else kj >= 0

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos, k_pos = _causal_positions(qi, kj, block_q, block_k)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jnp.dot(g, vblk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        acc_ref[...] += jnp.dot(ds, kblk,
                                preferred_element_type=jnp.float32)

    last = jnp.minimum(nkb - 1, ((qi + 1) * block_q - 1) // block_k) \
        if causal else nkb - 1

    @pl.when(kj == last)
    def _write():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *,
                          causal, scale, block_q, block_k, nqb):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = ((qi + 1) * block_q - 1 >= kj * block_k) if causal else qi >= 0

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        kblk = k_ref[0].astype(jnp.float32)
        vblk = v_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos, k_pos = _causal_positions(qi, kj, block_q, block_k)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jnp.dot(g, vblk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_acc[...] += jnp.dot(ds.T, q,
                               preferred_element_type=jnp.float32)
        dv_acc[...] += jnp.dot(p.T, g,
                               preferred_element_type=jnp.float32)

    @pl.when(qi == nqb - 1)
    def _write():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _pallas_flash_fwd(q, k, v, causal, scale, block_q=128, block_k=128,
                      pos_delta=None):
    """(B, H, S, D) fused attention forward on the MXU -> (out, lse).

    ``pos_delta`` (traced i32 scalar, optional): global-position delta
    ``q_offset - k_offset`` when q and k come from different sequence
    shards (ring attention feeds the visiting k/v block's offset per ring
    step). With a delta, causal masking uses global positions and the
    k grid is not pruned."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, \
        "flash kernel needs sequence divisible by block size"
    nkb = Sk // block_k
    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    with_off = pos_delta is not None

    def kernel(*refs):
        if with_off:
            off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, acc, mr, lr = refs
        else:
            q_ref, k_ref, v_ref, o_ref, lse_ref, acc, mr, lr = refs
            off_ref = None
        _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                          acc, mr, lr, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, nkb=nkb,
                          offset_ref=off_ref)

    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
    ]
    operands = [qr, kr, vr]
    if with_off:
        in_specs = [pl.BlockSpec((1, 1), lambda b, i, j: (0, 0))] + in_specs
        operands = [jnp.asarray(pos_delta, jnp.int32).reshape(1, 1)] + \
            operands
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // block_q, nkb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(*operands)
    return out.reshape(B, H, Sq, D), lse[..., 0].reshape(B, H, Sq)


def _pallas_flash_bwd(q, k, v, out, lse, g, causal, scale,
                      block_q=128, block_k=128):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, \
        "flash kernel needs sequence divisible by block size"
    nqb, nkb = Sq // block_q, Sk // block_k
    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    gr = g.reshape(B * H, Sq, D)
    # row stats enter the kernels lane-broadcast (see _LANES)
    lser = jnp.broadcast_to(lse.reshape(B * H, Sq)[..., None],
                            (B * H, Sq, _LANES))
    delta = jnp.broadcast_to(
        jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                axis=-1).reshape(B * H, Sq)[..., None],
        (B * H, Sq, _LANES))

    qspec = pl.BlockSpec((1, block_q, D), lambda b, x, y: (b, x, 0))
    rowspec = pl.BlockSpec((1, block_q, _LANES), lambda b, x, y: (b, x, 0))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, nkb=nkb),
        grid=(B * H, nqb, nkb),
        in_specs=[
            qspec,
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            qspec, rowspec, rowspec,
        ],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_interpret(),
    )(qr, kr, vr, gr, lser, delta)

    kvspec = pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k, nqb=nqb),
        grid=(B * H, nkb, nqb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            kvspec, kvspec,
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[kvspec, kvspec],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Sk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=_interpret(),
    )(qr, kr, vr, gr, lser, delta)
    return (dq.reshape(B, H, Sq, D), dk.reshape(B, H, Sk, D),
            dv.reshape(B, H, Sk, D))


def _flash_fwd_impl(q, k, v, causal, scale, block_k):
    blocks = _pallas_blocks(q, k)
    if blocks:
        return _pallas_flash_fwd(q, k, v, causal, scale,
                                 block_q=blocks[0], block_k=blocks[1])
    return _scan_flash_fwd(q, k, v, causal, scale, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, scale=None, block_k=512):
    """Fused multi-head attention: softmax(q·kᵀ·scale [+ causal mask])·v.

    q/k/v: (batch, heads, seq, head_dim). The S×S score matrix is never
    materialised in either direction — forward keeps online-softmax
    accumulators, backward recomputes per-block probabilities from the
    saved lse — so train-mode memory is O(S·D). On TPU both directions run
    as Pallas kernels (primal path included, so inference uses the fused
    kernel too); elsewhere identical-math `lax.scan` implementations run.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd_impl(q, k, v, causal, scale, block_k)[0]


def _flash_fwd(q, k, v, causal, scale, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, block_k)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_k, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    blocks = _pallas_blocks(q, k)
    if blocks:
        return _pallas_flash_bwd(q, k, v, out, lse, g, causal, scale,
                                 block_q=blocks[0], block_k=blocks[1])
    return _scan_flash_bwd(q, k, v, out, lse, g, causal, scale, block_k)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# ring attention (sequence parallel over a mesh axis)
# ---------------------------------------------------------------------------

def _ring_partials_scan(qf, kr, vr, delta, causal, scale, block_k):
    """Normalized block-attention partials via the differentiable scan
    path: (out / l, m + log l). Only the position DELTA matters for
    causal masking, so (q_offset=delta, k_offset=0) is equivalent to any
    (q_off, k_off) with the same difference."""
    po, pm, pl = _block_scan_attention(qf, kr, vr, causal, scale, block_k,
                                       q_offset=delta, k_offset=0)
    lsafe = jnp.maximum(pl, 1e-30)
    return po / lsafe[..., None], pm + jnp.log(lsafe)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ring_partials(qf, kr, vr, delta, causal, scale, block_k):
    """One ring step's block attention -> normalized (out, lse) partials.

    Primal dispatches to the fused Pallas kernel when available (the MXU
    path; the per-step position delta rides in as a traced scalar);
    backward recomputes through the differentiable scan path — same
    O(S/n) activation footprint, exact same masking semantics."""
    blocks = _pallas_blocks(qf, kr)
    if blocks:
        return _pallas_flash_fwd(qf, kr, vr, causal, scale,
                                 block_q=blocks[0], block_k=blocks[1],
                                 pos_delta=delta)
    return _ring_partials_scan(qf, kr, vr, delta, causal, scale, block_k)


def _ring_partials_fwd(qf, kr, vr, delta, causal, scale, block_k):
    out = _ring_partials(qf, kr, vr, delta, causal, scale, block_k)
    return out, (qf, kr, vr, delta)


def _ring_partials_bwd(causal, scale, block_k, res, cots):
    qf, kr, vr, delta = res
    _, vjp_fn = jax.vjp(
        lambda q, kk, vv: _ring_partials_scan(q, kk, vv, delta, causal,
                                              scale, block_k),
        qf, kr, vr)
    dq, dk, dv = vjp_fn(cots)
    ddelta = np.zeros((), dtype=jax.dtypes.float0)
    return dq, dk, dv, ddelta


_ring_partials.defvjp(_ring_partials_fwd, _ring_partials_bwd)


def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   block_k=512):
    """Sequence-parallel attention inside ``shard_map``.

    Each device holds the (B, H, S/n, D) shard of q/k/v for its sequence
    slice. k/v rotate around the ring (`lax.ppermute` over ICI) for n
    steps; every step folds the visiting block into the local
    online-softmax accumulator, so activations stay O(S/n) per chip and
    the transfers overlap the einsums. Causal masking uses global
    positions, so results equal single-device causal attention.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S_local, D = q.shape
    q_off = idx * S_local

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, r):
        out, m, l, kr, vr = carry
        # the (idx - r)-th device's block is visiting us this round
        src = (idx - r) % n
        o_n, lse = _ring_partials(qf, kr, vr, q_off - src * S_local,
                                  causal, scale, block_k)
        # normalized partial + lse is merge-equivalent to
        # (unnormalized out, m, l) with m := lse, l := 1
        po, pm, plgt = o_n, lse, jnp.ones_like(lse)
        # merge the visiting block's partial into the accumulator
        m_new = jnp.maximum(m, pm)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(pm - m_new)
        out = out * a1[..., None] + po * a2[..., None]
        l = l * a1 + plgt * a2
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        return (out, m_new, l, kr, vr), None

    zero = qf * 0.0  # inherits qf's varying-axes type (see above)
    init = (zero,
            jnp.max(zero, axis=-1) + _NEG_INF,
            jnp.sum(zero, axis=-1),
            k.astype(jnp.float32), v.astype(jnp.float32))
    (out, m, l, _, _), _ = lax.scan(step, init, jnp.arange(n))
    return _merge_partials(out, m, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# tape ops
# ---------------------------------------------------------------------------

class _FlashAttention(Operator):
    """Tape op wrapping :func:`flash_attention`."""

    def __init__(self, causal=False, scale=None):
        super().__init__()
        self.causal = causal
        self.scale = scale

    def forward(self, q, k, v):
        # policy discipline: attention matmuls run in the compute dtype;
        # the kernel's own online-softmax statistics are f32 regardless
        q, k, v = _cast_compute(q, k, v)
        return flash_attention(q, k, v, self.causal, self.scale)


def ulysses_attention(q, k, v, axis_name, causal=False, scale=None,
                      block_k=512):
    """All-to-all sequence parallelism (Ulysses-style) inside
    ``shard_map``: each device holds the (B, H, S/n, D) shard of its
    sequence slice; ONE all_to_all re-shards HEADS over the axis while
    gathering the FULL sequence locally ((B, H/n, S, D)), the fused
    flash kernel then runs unchanged on the full sequence — plain causal
    masking, no position offsets — and a second all_to_all restores
    sequence sharding.

    Two collectives per attention call versus ring attention's n
    ppermute hops: the better trade when the axis is large and heads are
    plentiful; ring wins when H < n or the gathered (S, S)-block
    workspace per head would not fit. Requires H % n == 0 — the
    :func:`attention` dispatcher falls back to ring otherwise.
    """
    def a2a(x, split, concat):
        return lax.all_to_all(x, axis_name, split_axis=split,
                              concat_axis=concat, tiled=True)

    qh, kh, vh = (a2a(t, 1, 2) for t in (q, k, v))
    out = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                          block_k=block_k)
    return a2a(out, 2, 1)


class _UlyssesAttention(Operator):
    """Tape op wrapping :func:`ulysses_attention` (inside shard_map)."""

    def __init__(self, axis_name, causal=False, scale=None):
        super().__init__()
        self.axis_name = axis_name
        self.causal = causal
        self.scale = scale

    def forward(self, q, k, v):
        q, k, v = _cast_compute(q, k, v)
        return ulysses_attention(q, k, v, self.axis_name, self.causal,
                                 self.scale)


class _RingAttention(Operator):
    """Tape op wrapping :func:`ring_attention` (inside shard_map)."""

    def __init__(self, axis_name, causal=False, scale=None):
        super().__init__()
        self.axis_name = axis_name
        self.causal = causal
        self.scale = scale

    def forward(self, q, k, v):
        q, k, v = _cast_compute(q, k, v)
        return ring_attention(q, k, v, self.axis_name, self.causal,
                              self.scale)


def attention(q, k, v, causal=False, scale=None, seq_axis=None,
              seq_mode="ring"):
    """Functional tape API. With ``seq_axis`` an active
    sequence-parallel mesh axis, ``seq_mode`` picks the long-context
    strategy: ``'ring'`` (k/v rotate over ICI, O(S/n) workspace) or
    ``'ulysses'`` (one all_to_all head re-shard, full local sequence).
    Ulysses needs the local head count divisible by the axis size and
    falls back to ring otherwise (one-time warning)."""
    from ..parallel.communicator import active_axis
    if seq_mode not in ("ring", "ulysses", "alltoall", "all_to_all"):
        raise ValueError(f"unknown seq_mode {seq_mode!r} "
                         "(expected 'ring' or 'ulysses')")
    if seq_axis is not None and active_axis(seq_axis):
        if seq_mode in ("ulysses", "alltoall", "all_to_all"):
            n = _axis_size(seq_axis)
            H = q.shape[1]
            if H % n == 0:
                return _UlyssesAttention(seq_axis, causal, scale)(q, k, v)
            sig = ("ulysses-fallback", H, n)
            if sig not in _DECLINE_LOGGED:
                _DECLINE_LOGGED.add(sig)
                import warnings
                warnings.warn(
                    f"ulysses attention needs heads ({H}) divisible by "
                    f"the '{seq_axis}' axis size ({n}); falling back to "
                    "ring attention", stacklevel=2)
        return _RingAttention(seq_axis, causal, scale)(q, k, v)
    return _FlashAttention(causal, scale)(q, k, v)
