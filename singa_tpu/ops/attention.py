"""Attention: fused flash kernel + ring (sequence-parallel) attention.

TPU-first components with no reference equivalent (the reference composes
attention from primitive autograd ops in examples and has no sequence
parallelism — SURVEY.md §5 'long-context: absent'); these are the
long-context machinery the TPU build makes first-class:

- :func:`flash_attention` — blocked online-softmax attention. On TPU the
  forward runs as a Pallas kernel (grid over (batch*heads, q-blocks),
  streaming k/v blocks through VMEM with running max/sum accumulators, so
  the S×S score matrix never hits HBM). Elsewhere (CPU mesh tests) an
  identical-math `lax.scan` implementation runs. Backward recomputes
  per-block scores (flash style) via the scan path under `jax.custom_vjp`.
- :func:`ring_attention` — q/k/v sharded over a 'seq' mesh axis inside
  `shard_map`; k/v blocks rotate around the ICI ring via `lax.ppermute`
  while each device folds them into its online-softmax accumulator.
  Communication overlaps compute; memory per chip is O(S/n · S/n).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..autograd_base import Operator

_NEG_INF = -1e30


def _block_scan_attention(q, k, v, causal, scale, block_k,
                          q_offset=0, k_offset=0):
    """Online-softmax attention, scanning over key blocks.

    q: (B, H, Sq, D), k/v: (B, H, Sk, D). Returns (out, m, l) so partial
    results can be merged (ring attention needs the accumulators).
    ``q_offset``/``k_offset`` are global position offsets for causal
    masking of sharded sequences.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_k = min(block_k, Sk)
    nblocks = (Sk + block_k - 1) // block_k
    pad = nblocks * block_k - Sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(B, H, nblocks, block_k, D).transpose(2, 0, 1, 3, 4)

    q_pos = q_offset + jnp.arange(Sq)

    def step(carry, inputs):
        out, m, l = carry
        blk_idx, kblk, vblk = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kblk,
                       preferred_element_type=jnp.float32) * scale
        k_pos = k_offset + blk_idx * block_k + jnp.arange(block_k)
        mask = k_pos[None, :] < (Sk + k_offset)  # padding mask
        if causal:
            mask = mask & (k_pos[None, :] <= q_pos[:, None])
        s = jnp.where(mask[None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        out_new = out * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vblk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return (out_new, m_new, l_new), None

    # derive accumulators from q so they carry its shard_map varying-axes
    # type (fresh zeros would be 'unvarying' and fail the scan typecheck)
    zero = q.astype(jnp.float32) * 0.0
    init = (zero,
            jnp.max(zero, axis=-1) + _NEG_INF,
            jnp.sum(zero, axis=-1))
    (out, m, l), _ = lax.scan(
        step, init, (jnp.arange(nblocks), kb, vb))
    return out, m, l


def _merge_partials(out, m, l):
    """Normalise a streamed accumulator into the final attention output."""
    return (out / jnp.maximum(l, 1e-30)[..., None])


def _reference_attention(q, k, v, causal, scale, block_k=512):
    out, m, l = _block_scan_attention(q.astype(jnp.float32),
                                      k.astype(jnp.float32),
                                      v.astype(jnp.float32),
                                      causal, scale, block_k)
    return _merge_partials(out, m, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal,
                      scale, seq_k, block_q):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)            # (block_q, D)
    nkb = seq_k // block_k

    def body(j, carry):
        out, m, l = carry
        kblk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kblk.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        out_new = out * alpha[:, None] + jnp.dot(
            p, vblk, preferred_element_type=jnp.float32)
        return out_new, m_new, l_new

    D = q.shape[-1]
    init = (jnp.zeros((q.shape[0], D), jnp.float32),
            jnp.full((q.shape[0],), _NEG_INF, jnp.float32),
            jnp.zeros((q.shape[0],), jnp.float32))
    out, m, l = jax.lax.fori_loop(0, nkb, body, init)
    o_ref[0] = (out / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


try:  # pallas import is TPU-oriented; keep CPU-only installs working
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False


def _pallas_flash_fwd(q, k, v, causal, scale, block_q=128, block_k=128):
    """(B, H, S, D) fused attention forward on the MXU."""
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_k = min(block_k, Sk)
    assert Sq % block_q == 0 and Sk % block_k == 0, \
        "flash kernel needs sequence divisible by block size"
    qr = q.reshape(B * H, Sq, D)
    kr = k.reshape(B * H, Sk, D)
    vr = v.reshape(B * H, Sk, D)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, seq_k=Sk,
                               block_q=block_q)
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Sk, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
    )(qr, kr, vr)
    return out.reshape(B, H, Sq, D)


def _on_tpu(*arrays):
    # backend-level dispatch: under jit/shard_map tracing the operands are
    # Tracers (no .devices()), but the computation compiles for the default
    # backend, which is what decides whether the Pallas kernel can run
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, scale=None, block_k=512):
    """Fused multi-head attention: softmax(q·kᵀ·scale [+ causal mask])·v.

    q/k/v: (batch, heads, seq, head_dim). The S×S score matrix is never
    materialised (blocked online softmax), so memory is O(S·D) — the
    long-context path. Differentiable (custom vjp recomputes block scores).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _reference_attention(q, k, v, causal, scale, block_k)


def _flash_fwd(q, k, v, causal, scale, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if HAS_PALLAS and _on_tpu(q, k, v) and q.shape[2] % 128 == 0 \
            and k.shape[2] % 128 == 0:
        out = _pallas_flash_fwd(q, k, v, causal, scale)
    else:
        out = _reference_attention(q, k, v, causal, scale, block_k)
    return out, (q, k, v)


def _flash_bwd(causal, scale, block_k, res, g):
    q, k, v = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _reference_attention(q_, k_, v_, causal, scale,
                                                block_k), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# ring attention (sequence parallel over a mesh axis)
# ---------------------------------------------------------------------------

def ring_attention(q, k, v, axis_name, causal=False, scale=None,
                   block_k=512):
    """Sequence-parallel attention inside ``shard_map``.

    Each device holds the (B, H, S/n, D) shard of q/k/v for its sequence
    slice. k/v rotate around the ring (`lax.ppermute` over ICI) for n
    steps; every step folds the visiting block into the local
    online-softmax accumulator, so activations stay O(S/n) per chip and
    the transfers overlap the einsums. Causal masking uses global
    positions, so results equal single-device causal attention.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S_local, D = q.shape
    q_off = idx * S_local

    qf = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, r):
        out, m, l, kr, vr = carry
        # the (idx - r)-th device's block is visiting us this round
        src = (idx - r) % n
        po, pm, plgt = _block_scan_attention(
            qf, kr, vr, causal, scale, block_k,
            q_offset=q_off, k_offset=src * S_local)
        # merge the visiting block's partial into the accumulator
        m_new = jnp.maximum(m, pm)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.exp(pm - m_new)
        out = out * a1[..., None] + po * a2[..., None]
        l = l * a1 + plgt * a2
        kr = lax.ppermute(kr, axis_name, perm)
        vr = lax.ppermute(vr, axis_name, perm)
        return (out, m_new, l, kr, vr), None

    zero = qf * 0.0  # inherits qf's varying-axes type (see above)
    init = (zero,
            jnp.max(zero, axis=-1) + _NEG_INF,
            jnp.sum(zero, axis=-1),
            k.astype(jnp.float32), v.astype(jnp.float32))
    (out, m, l, _, _), _ = lax.scan(step, init, jnp.arange(n))
    return _merge_partials(out, m, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# tape ops
# ---------------------------------------------------------------------------

class _FlashAttention(Operator):
    """Tape op wrapping :func:`flash_attention`."""

    def __init__(self, causal=False, scale=None):
        super().__init__()
        self.causal = causal
        self.scale = scale

    def forward(self, q, k, v):
        return flash_attention(q, k, v, self.causal, self.scale)


class _RingAttention(Operator):
    """Tape op wrapping :func:`ring_attention` (inside shard_map)."""

    def __init__(self, axis_name, causal=False, scale=None):
        super().__init__()
        self.axis_name = axis_name
        self.causal = causal
        self.scale = scale

    def forward(self, q, k, v):
        return ring_attention(q, k, v, self.axis_name, self.causal,
                              self.scale)


def attention(q, k, v, causal=False, scale=None, seq_axis=None):
    """Functional tape API; picks ring attention when ``seq_axis`` is an
    active sequence-parallel mesh axis."""
    from ..parallel.communicator import active_axis
    if seq_axis is not None and active_axis(seq_axis):
        return _RingAttention(seq_axis, causal, scale)(q, k, v)
    return _FlashAttention(causal, scale)(q, k, v)
