"""Activation-layout selection for the 2-D CNN stack (NCHW vs NHWC).

The reference API is NCHW end-to-end (cuDNN's native layout,
src/model/operation/convolution.h:43-90). On TPU the MXU wants the
channel dimension in the 128-lane minor position, so NHWC activations
avoid the relayout copies XLA otherwise inserts around every conv/BN
fusion. This module provides the one switch the conv/pool/BN handles
consult at construction time:

- the *public* tensor API stays NCHW (reference parity);
- a model that opts in (e.g. ``models.resnet.create_model(layout="NHWC")``)
  transposes its input once at the stem and runs its whole conv trunk
  channels-last, with weights still stored OIHW so checkpoints are
  layout-independent.

Which layout is faster is a hardware question, answered by the banked
``resnet_layout_ab`` probe (tools/tpu_probe_extra.py) — bench.py picks
the measured winner, never a guess.
"""

from __future__ import annotations

import contextlib
import os
from contextvars import ContextVar

_VALID = ("NCHW", "NHWC")


def _env_default() -> str:
    v = os.environ.get("SINGA_CONV_LAYOUT", "NCHW").upper()
    return v if v in _VALID else "NCHW"


# Per-context (thread/task) scope stack: a ContextVar instead of a
# process-global list, so an NHWC scope entered while one model builds
# (e.g. training) can never leak into handle construction on another
# thread (e.g. a concurrent serving model) — each thread/asyncio task
# sees only its own scopes, falling back to the env default.
_stack: ContextVar[tuple] = ContextVar("singa_tpu_conv_layout",
                                       default=(_env_default(),))


def current_layout() -> str:
    """Layout new conv/pool/BN handles capture (handles read this once
    at construction; op forward paths use the captured value)."""
    return _stack.get()[-1]


def channel_axis(ndim: int = 4) -> int:
    """Channel axis of an activation under the current layout."""
    return 1 if current_layout() == "NCHW" or ndim == 2 else ndim - 1


def resolve(layout) -> str:
    """Normalise a handle's layout argument: explicit value (validated)
    or the ambient default. The one place every handle resolves through,
    so a typo'd layout= fails loudly instead of silently meaning NCHW."""
    v = (str(layout).upper() if layout else current_layout())
    if v not in _VALID:
        raise ValueError(f"layout must be one of {_VALID}, got {layout!r}")
    return v


@contextlib.contextmanager
def use_layout(layout: str):
    """Scope a layout for handle construction and deferred layer init —
    a model's forward wraps its conv trunk in this so its layers
    initialize channels-last without any global state leaking out."""
    layout = str(layout).upper()
    if layout not in _VALID:
        raise ValueError(f"layout must be one of {_VALID}, got {layout!r}")
    token = _stack.set(_stack.get() + (layout,))
    try:
        yield
    finally:
        _stack.reset(token)
