"""Batch normalization with functional running-stat state.

Capability parity with the reference BN operation
(src/model/operation/batchnorm.h:49-115): training mode normalises by batch
statistics and updates the running mean/var "in place" (the reference mutates
the running blocks on device; here the update rebinds the state Tensors'
values, which the Model layer threads through jit as donated state), and
inference mode normalises by the running statistics.

Backward (dx, dscale, dbias) is the vjp of the batch-stat normalisation —
the same math as cudnnBatchNormalizationBackward, emitted by XLA as a fused
reduction + elementwise kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd_base import Operator, is_training
from ..tensor import Tensor


class BatchNormHandle:
    """Static BN config (reference BatchNormHandle batchnorm.h:49-73).

    Supports 2D (N, C) and 4D (N, C, H, W) inputs like the reference.
    """

    def __init__(self, momentum, x, eps: float = 1e-5, layout=None):
        from .layout import resolve as _resolve_layout
        self.factor = float(momentum)
        self.layout = _resolve_layout(layout)
        xs = x.shape if hasattr(x, "shape") else tuple(x)
        self.is_2d = len(xs) == 2
        self.channels = int(xs[-1]) \
            if self.layout == "NHWC" and not self.is_2d else int(xs[1])
        self.eps = eps
        self.batchsize = int(xs[0])

    def _axes(self, ndim):
        if ndim == 2:
            return (0,)
        return (0, 1, 2) if self.layout == "NHWC" else (0, 2, 3)

    def _bshape(self, ndim):
        if ndim == 2:
            return (1, self.channels)
        return (1, 1, 1, self.channels) if self.layout == "NHWC" \
            else (1, self.channels, 1, 1)


def _global_moments(xb, axes):
    """Batch mean/var, pmean-synchronised across every mesh axis the
    batch is sharded over (identity outside a mesh context). Inside a
    shard_map'd step each replica sees only its local batch shard;
    sync-BN pmeans the moments so both normalisation and the
    running-stat update use GLOBAL batch statistics — making the sharded
    step numerically identical to a single-device full-batch step (the
    SPMD-correct form of the reference's in-place running stats,
    src/model/operation/batchnorm.h:103-115). The axes come from the
    Model step's declared input batch sharding, NOT a hardcoded 'data'
    (the batch may shard over ('data','expert') or a renamed axis).
    Two-pass: variance is the mean squared deviation around the GLOBAL
    mean — numerically stable (never negative) and, with equal-sized
    shards, exactly the full-batch biased variance."""
    from ..parallel.communicator import active_batch_axes
    paxes = active_batch_axes()
    # accumulate moments in f32 regardless of activation dtype: a bf16
    # sum over N*H*W elements (~1.6M at the bench shapes) loses most of
    # its mantissa; the cast fuses into the reduction, so this is the
    # "stats stay f32" contract at zero cost
    xb = xb.astype(jnp.float32)
    mean = jnp.mean(xb, axis=axes)
    if paxes:
        mean = jax.lax.pmean(mean, paxes)
    var = jnp.mean(jnp.square(xb - jnp.expand_dims(mean, axes)), axis=axes)
    if paxes:
        var = jax.lax.pmean(var, paxes)
    return mean, var


class _BatchNorm2d(Operator):
    """Training-mode BN over batch stats; grads for (x, scale, bias)."""

    def __init__(self, handle: BatchNormHandle):
        super().__init__()
        self.handle = handle

    def forward(self, x, scale, bias):
        h = self.handle
        axes = h._axes(x.ndim)
        mean, var = _global_moments(x, axes)
        bshape = h._bshape(x.ndim)
        inv = jax.lax.rsqrt(var + h.eps).reshape(bshape)
        y = (x - mean.reshape(bshape)) * inv * scale.reshape(bshape) \
            + bias.reshape(bshape)
        # stats/params stay f32 for stability; activations keep the
        # input's precision class (bf16 nets must not upcast here)
        return y.astype(x.dtype)


class _BatchNorm2dInference(Operator):
    """Inference-mode BN with frozen running stats
    (reference GpuBatchNormForwardInference batchnorm.h:103-115)."""

    def __init__(self, handle: BatchNormHandle):
        super().__init__()
        self.handle = handle

    def forward(self, x, scale, bias, rmean, rvar):
        h = self.handle
        bshape = h._bshape(x.ndim)
        rmean = jax.lax.stop_gradient(rmean)
        rvar = jax.lax.stop_gradient(rvar)
        inv = jax.lax.rsqrt(rvar + h.eps).reshape(bshape)
        y = (x - rmean.reshape(bshape)) * inv * scale.reshape(bshape) \
            + bias.reshape(bshape)
        return y.astype(x.dtype)


def batchnorm_2d(handle: BatchNormHandle, x, scale, bias,
                 running_mean: Tensor, running_var: Tensor,
                 freeze_stats=False):
    """Functional wrapper (parity: reference autograd.batchnorm_2d:1740).

    In training mode the running statistics are updated in place (rebinding
    the state Tensors), exactly mirroring the reference's in-place block
    mutation semantics. ``freeze_stats`` forces the frozen-stats inference
    path even in training (caffe's use_global_stats).
    """
    if is_training() and not freeze_stats:
        h = handle
        axes = h._axes(x.ndim)
        xb = x.data if isinstance(x, Tensor) else x
        batch_mean, batch_var = _global_moments(xb, axes)
        m = h.factor
        # running stats keep their own (f32) dtype under EVERY precision
        # mode — _global_moments already accumulates f32, and the astype
        # pins the threaded state's dtype so a precision policy (or a
        # stat tensor restored from an older checkpoint) can never flip
        # it mid-training and break step donation
        running_mean.data = (m * running_mean.data.astype(jnp.float32)
                             + (1 - m) * batch_mean
                             ).astype(running_mean.data.dtype)
        running_var.data = (m * running_var.data.astype(jnp.float32)
                            + (1 - m) * batch_var
                            ).astype(running_var.data.dtype)
        op, args = _BatchNorm2d(handle), (x, scale, bias)
    else:
        op, args = _BatchNorm2dInference(handle), \
            (x, scale, bias, running_mean, running_var)
    # keep references for ONNX export (BatchNormalization's mean/var inputs)
    op.running_mean, op.running_var = running_mean, running_var
    out = op(*args)
    if isinstance(op, _BatchNorm2dInference) and not handle.is_2d:
        # tag the frozen-stats output with its folding ingredients: a
        # ReLU consuming it may fuse the whole scale/shift+relu epilogue
        # into one pass over the conv output (ops/fused_epilogue.py —
        # opt-in, traced inference only; the tag itself is one attr)
        out._bn_epilogue = (x, scale, bias, running_mean, running_var,
                            handle.eps, handle.layout)
    return out
