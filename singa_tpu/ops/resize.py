"""ONNX-semantics Resize (nearest / linear / cubic) as one gather op.

The reference supports resize only through its ONNX backend
(python/singa/sonnx.py UpSample/Resize handling, nearest-integer scales
only). This op implements the full ONNX-spec sampling semantics —
coordinate_transformation_mode half_pixel / asymmetric / align_corners,
nearest_mode round_prefer_floor / floor, and separable linear / cubic
(Keys kernel, spec-default cubic_coeff_a=-0.75, exclude_outside=0 via
index clamping) — the TPU-first way: all index/weight tables are
precomputed with numpy at trace time (shapes are static under jit), so
the forward is a chain of per-axis ``jnp.take`` + weighted sums that XLA
fuses, and backward falls out of the vjp (a scatter-add XLA also maps
natively).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..autograd_base import Operator

_COORD_MODES = ("half_pixel", "asymmetric", "align_corners",
                "pytorch_half_pixel")


def _src_coords(out_size, in_size, scale, coord_mode):
    i = np.arange(out_size, dtype=np.float64)
    if coord_mode == "align_corners":
        if out_size == 1:
            return np.zeros(1)
        return i * (in_size - 1) / (out_size - 1)
    if coord_mode == "asymmetric":
        return i / scale
    if coord_mode == "pytorch_half_pixel":
        if out_size == 1:
            return np.zeros(1)
        return (i + 0.5) / scale - 0.5
    # half_pixel (ONNX default)
    return (i + 0.5) / scale - 0.5


def _nearest_table(x, in_size, nearest_mode):
    if nearest_mode == "floor":
        idx = np.floor(x)
    elif nearest_mode == "ceil":
        idx = np.ceil(x)
    elif nearest_mode == "round_prefer_ceil":
        idx = np.floor(x + 0.5)
    else:  # round_prefer_floor (ONNX default)
        idx = np.ceil(x - 0.5)
    return np.clip(idx, 0, in_size - 1).astype(np.int32), None


def _linear_table(x, in_size):
    lo = np.floor(x)
    w_hi = (x - lo).astype(np.float32)
    idx = np.stack([np.clip(lo, 0, in_size - 1),
                    np.clip(lo + 1, 0, in_size - 1)]).astype(np.int32)
    w = np.stack([1.0 - w_hi, w_hi]).astype(np.float32)
    return idx, w


def _cubic_kernel(t, a):
    """Keys cubic convolution weight at |distance| t (0..2)."""
    t = np.abs(t)
    return np.where(
        t <= 1, (a + 2) * t ** 3 - (a + 3) * t ** 2 + 1,
        np.where(t < 2, a * t ** 3 - 5 * a * t ** 2 + 8 * a * t - 4 * a,
                 0.0))


def _cubic_table(x, in_size, a):
    base = np.floor(x).astype(np.int64)
    frac = x - base
    idx, w = [], []
    for k in (-1, 0, 1, 2):
        idx.append(np.clip(base + k, 0, in_size - 1))
        w.append(_cubic_kernel(k - frac, a))
    return (np.stack(idx).astype(np.int32),
            np.stack(w).astype(np.float32))


class ResizeHandle:
    """Static sampling config: one (idx, weights) table per resized axis
    (the Operator is rebuilt per call — tape nodes are single-use — but
    the numpy table computation happens once per handle, mirroring the
    ConvHandle pattern)."""

    def __init__(self, in_shape, out_shape, mode="nearest",
                 coord_mode="half_pixel",
                 nearest_mode="round_prefer_floor", cubic_a=-0.75,
                 scales=None):
        if coord_mode not in _COORD_MODES:
            raise NotImplementedError(
                f"Resize coordinate_transformation_mode {coord_mode!r}")
        self.out_shape = tuple(int(s) for s in out_shape)
        self.tables = []   # (axis, idx, weights-or-None)
        for ax, (si, so) in enumerate(zip(in_shape, self.out_shape)):
            scale = (scales[ax] if scales is not None
                     else so / float(si))
            # an axis is a passthrough only when the SCALE is 1 — with
            # an explicit non-unit scale whose floor(in*s) == in, the
            # spec still maps coordinates through s (e.g. s=1.4 on 2
            # elements resamples, it does not copy)
            if si == so and abs(float(scale) - 1.0) < 1e-9:
                continue
            x = _src_coords(so, si, scale, coord_mode)
            if mode == "nearest":
                idx, w = _nearest_table(x, si, nearest_mode)
            elif mode == "linear":
                idx, w = _linear_table(x, si)
            elif mode == "cubic":
                idx, w = _cubic_table(x, si, cubic_a)
            else:
                raise NotImplementedError(f"Resize mode {mode!r}")
            self.tables.append((ax, idx, w))


class _Resize(Operator):
    """Separable resample over a :class:`ResizeHandle`'s tables."""

    def __init__(self, handle: ResizeHandle):
        super().__init__()
        self.handle = handle

    def forward(self, x):
        dtype = x.dtype
        for ax, idx, w in self.handle.tables:
            if w is None:   # nearest: one gather
                x = jnp.take(x, jnp.asarray(idx), axis=ax)
            else:           # linear/cubic: weighted taps along the axis
                wshape = [1] * x.ndim
                wshape[ax] = w.shape[1]
                acc = None
                for k in range(idx.shape[0]):
                    tap = jnp.take(x, jnp.asarray(idx[k]), axis=ax) \
                        * jnp.asarray(w[k]).reshape(wshape)
                    acc = tap if acc is None else acc + tap
                x = acc
        return x.astype(dtype)


def resize(x, out_shape=None, mode="nearest", coord_mode="half_pixel",
           nearest_mode="round_prefer_floor", cubic_a=-0.75, scales=None,
           handle=None):
    """Functional wrapper: resample ``x`` to ``out_shape`` with ONNX
    Resize semantics. ``scales`` (per-axis, optional) pins the scale
    used in the coordinate transform when the caller got out_shape from
    a scales input (ONNX computes out = floor(in * scale) but maps
    coordinates with the ORIGINAL scale, not the ratio). Pass a
    prebuilt ``handle`` to reuse its tables across calls instead of
    the shape/mode arguments."""
    if handle is None:
        if out_shape is None:
            raise ValueError("resize needs out_shape or a handle")
        handle = ResizeHandle(x.shape, out_shape, mode, coord_mode,
                              nearest_mode, cubic_a, scales)
    return _Resize(handle)(x)
