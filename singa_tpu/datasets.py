"""Standard-dataset ingestion: CIFAR-10/100 and MNIST wire formats.

Parity with the reference's examples/cnn/data/ loaders
(cifar10.py:30-83, cifar100.py, mnist.py:36-76), redesigned for a TPU
input pipeline: everything is parsed straight into contiguous NCHW
float32 arrays, and augmentation/resize are VECTORIZED over the batch
(the reference loops per-sample through PIL/numpy, train_cnn.py:35-45,
84-94) so the host never becomes the bottleneck feeding the chip.

No network egress happens here: loaders read the files the reference's
download scripts would have fetched (``cifar-10-batches-py/``,
``cifar-10-batches-bin/``, ``*-ubyte[.gz]``) from a local directory.
"""

import gzip
import os
import pickle
import struct

import numpy as np

# mirror of the reference's default locations (its download scripts
# write to /tmp) plus conventional in-repo spots. Relative spots are
# anchored at the REPO root (parent of this package), not the process
# cwd — a launcher invoking a training script from elsewhere must find
# the same datasets the interactive run found. cwd stays as a LAST
# fallback for ad-hoc layouts.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SEARCH_ROOTS = ["/tmp", "/root/data",
                 os.path.join(_REPO_ROOT, "data"), _REPO_ROOT,
                 "data", "."]

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2023, 0.1994, 0.2010], np.float32)


class DatasetNotFoundError(FileNotFoundError):
    """Raised with download instructions when the files are absent."""


def _resolve(dir_path, candidates, what, hint):
    roots = [dir_path] if dir_path else _SEARCH_ROOTS
    for root in roots:
        for cand in candidates:
            p = os.path.join(root, cand)
            if os.path.exists(p):
                return p
    raise DatasetNotFoundError(
        f"{what} not found under {roots}. Place the standard files there "
        f"(e.g. {hint}); this environment performs no downloads.")


# ---------------------------------------------------------------------------
# CIFAR
# ---------------------------------------------------------------------------

def _load_cifar_pickle(path, label_key="labels"):
    with open(path, "rb") as fd:
        try:
            blob = pickle.load(fd, encoding="latin1")
        except TypeError:  # pragma: no cover - py2 pickles
            blob = pickle.load(fd)
    images = blob["data"].astype(np.uint8).reshape(-1, 3, 32, 32)
    labels = np.asarray(blob[label_key], dtype=np.int32)
    return images, labels


def _load_cifar_bin(path, n_coarse=0):
    """The binary distribution: records of [label][3072 pixel bytes]
    (cifar-10) or [coarse][fine][3072] (cifar-100)."""
    raw = np.fromfile(path, dtype=np.uint8)
    rec = 3073 + n_coarse
    raw = raw.reshape(-1, rec)
    labels = raw[:, n_coarse].astype(np.int32)
    images = raw[:, 1 + n_coarse:].reshape(-1, 3, 32, 32)
    return images, labels


def load_cifar10(dir_path=None, num_batches=5):
    """Returns (train_x, train_y, val_x, val_y); images uint8 NCHW.

    Accepts either distribution format: the python pickle batches
    (``cifar-10-batches-py/data_batch_N``) or the binary records
    (``cifar-10-batches-bin/data_batch_N.bin``)."""
    try:
        first = _resolve(dir_path,
                         ["cifar-10-batches-py/data_batch_1",
                          "data_batch_1"],
                         "CIFAR-10 (python format)", "data_batch_1")
        loader, suffix = _load_cifar_pickle, ""
    except DatasetNotFoundError:
        first = _resolve(dir_path,
                         ["cifar-10-batches-bin/data_batch_1.bin",
                          "data_batch_1.bin"],
                         "CIFAR-10", "cifar-10-batches-py/data_batch_1")
        loader, suffix = _load_cifar_bin, ".bin"
    base = os.path.dirname(first)
    xs, ys = [], []
    for i in range(1, num_batches + 1):
        x, y = loader(os.path.join(base, f"data_batch_{i}{suffix}"))
        xs.append(x)
        ys.append(y)
    vx, vy = loader(os.path.join(base, f"test_batch{suffix}"))
    return np.concatenate(xs), np.concatenate(ys), vx, vy


def load_cifar100(dir_path=None, label_mode="fine"):
    """Returns (train_x, train_y, val_x, val_y) from the python-format
    ``cifar-100-python/{train,test}`` pickles."""
    key = "fine_labels" if label_mode == "fine" else "coarse_labels"
    train = _resolve(dir_path, ["cifar-100-python/train", "train"],
                     "CIFAR-100 (python format)", "cifar-100-python/train")
    tx, ty = _load_cifar_pickle(train, key)
    vx, vy = _load_cifar_pickle(
        os.path.join(os.path.dirname(train), "test"), key)
    return tx, ty, vx, vy


def normalize_cifar(*arrays, mean=CIFAR10_MEAN, std=CIFAR10_STD):
    """uint8/float NCHW -> per-channel standardized float32 (all three
    channels — the reference's loop stops at channel 1, a long-standing
    off-by-one in examples/cnn/data/cifar10.py:70-76)."""
    out = []
    for a in arrays:
        a = np.asarray(a, np.float32) / 255.0
        a = (a - mean[None, :, None, None]) / std[None, :, None, None]
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


# ---------------------------------------------------------------------------
# MNIST (idx format)
# ---------------------------------------------------------------------------

def _open_maybe_gz(path):
    return gzip.open(path, "rb") if path.endswith(".gz") else \
        open(path, "rb")


def _read_idx(path, magic, header_ints):
    with _open_maybe_gz(path) as f:
        data = f.read()
    fields = struct.unpack(f">{header_ints}i", data[:4 * header_ints])
    if fields[0] != magic:
        raise ValueError(f"{path}: bad idx magic {fields[0]:#x}, "
                         f"expected {magic:#x}")
    arr = np.frombuffer(data, np.uint8, offset=4 * header_ints)
    return arr, fields[1:]


def load_mnist(dir_path=None):
    """Returns (train_x, train_y, val_x, val_y); images uint8
    (N, 1, 28, 28). Reads the standard idx files, gzipped or plain."""
    def find(stem):
        return _resolve(dir_path, [stem + ".gz", stem,
                                   os.path.join("mnist", stem + ".gz"),
                                   os.path.join("mnist", stem)],
                        f"MNIST ({stem})", stem + ".gz")

    out = []
    for stem_x, stem_y in [("train-images-idx3-ubyte",
                            "train-labels-idx1-ubyte"),
                           ("t10k-images-idx3-ubyte",
                            "t10k-labels-idx1-ubyte")]:
        xs, (n, rows, cols) = _read_idx(find(stem_x), 2051, 4)
        ys, (ny,) = _read_idx(find(stem_y), 2049, 2)
        if n != ny:
            raise ValueError(f"MNIST image/label count mismatch {n}/{ny}")
        out += [xs.reshape(n, 1, rows, cols), ys.astype(np.int32)]
    return tuple(out)


# ---------------------------------------------------------------------------
# batched host-side transforms
# ---------------------------------------------------------------------------

def augment_crop_flip(x, pad=4, rng=None):
    """Random shift-crop + horizontal flip over the WHOLE batch at once
    (reference: per-sample python loop, train_cnn.py:35-45). x: float32
    NCHW; returns a new array."""
    rng = rng or np.random
    n, c, h, w = x.shape
    xpad = np.pad(x, [(0, 0), (0, 0), (pad, pad), (pad, pad)], "symmetric")
    dy = rng.randint(0, 2 * pad + 1, n)
    dx = rng.randint(0, 2 * pad + 1, n)
    # gather all crops with one fancy-index: rows/cols per sample
    rows = dy[:, None] + np.arange(h)[None, :]           # (n, h)
    cols = dx[:, None] + np.arange(w)[None, :]           # (n, w)
    out = xpad[np.arange(n)[:, None, None, None],
               np.arange(c)[None, :, None, None],
               rows[:, None, :, None],
               cols[:, None, None, :]]
    flip = rng.randint(0, 2, n).astype(bool)
    out[flip] = out[flip, :, :, ::-1]
    return out


def resize_batch(x, image_size, as_numpy=False):
    """Bilinear resize of an NCHW batch in one vectorized op via
    jax.image.resize (reference: nested per-sample/per-channel PIL loop,
    train_cnn.py:84-94).

    Returns the on-device jax array by default — callers feeding a model
    should hand it straight to ``Tensor(data=...)`` so the resized batch
    never makes a device→host→device roundtrip. ``as_numpy=True`` pulls
    it to host for numpy consumers."""
    import jax.image

    if x.shape[2] == image_size and x.shape[3] == image_size:
        return np.asarray(x, np.float32)
    out = jax.image.resize(
        np.asarray(x, np.float32),
        (x.shape[0], x.shape[1], image_size, image_size),
        method="bilinear")
    return np.asarray(out) if as_numpy else out


def partition(global_rank, world_size, *arrays):
    """Contiguous equal shards of each array for data parallelism
    (reference train_cnn.py:58-72)."""
    out = []
    for a in arrays:
        per = a.shape[0] // world_size
        out.append(a[global_rank * per:(global_rank + 1) * per])
    return tuple(out)


def load(name, dir_path=None):
    """Dispatch by dataset name: 'cifar10' | 'cifar100' | 'mnist'."""
    table = {"cifar10": load_cifar10, "cifar100": load_cifar100,
             "mnist": load_mnist}
    if name not in table:
        raise ValueError(f"unknown dataset '{name}' "
                         f"(expected one of {sorted(table)})")
    return table[name](dir_path)
