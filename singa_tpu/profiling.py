"""Measured per-fusion step profiling from ``jax.profiler`` traces.

The reference prints MEASURED per-node times of the graph it actually
runs (src/core/scheduler/scheduler.cc:240-298). In the XLA world the
executed graph is a set of fusions, so the honest equivalent is: capture
a profiler trace of one compiled step and aggregate the per-fusion
durations. This complements the *static* cost analysis (flops/bytes)
captured by ``Model.cost_analysis``.
"""

import glob
import gzip
import json
import os

# host-side runtime/python frames that appear in CPU traces alongside the
# XLA op events; device lanes (TPU) don't need this
_RUNTIME_MARKERS = ("(", "::", " ")


def _is_xla_op_event(name):
    if name.startswith("$"):             # python source frames
        return False
    return not any(m in name for m in _RUNTIME_MARKERS)


def parse_trace_events(logdir):
    """Flat list of complete ('X') events from a ``jax.profiler.trace``
    output directory, one dict per event::

        {"name": <enriched symbol>, "ts": <µs or None>, "dur": <µs>,
         "lane": "device" | "host", "pid": ..., "xla_op": bool}

    ``lane`` is resolved per trace file (``/device:...`` process rows
    are device lanes); ``xla_op`` records whether the RAW event name
    looked like an XLA op/fusion symbol (the host-fallback filter —
    computed before :func:`_enrich` folds metadata into the name).
    Python source frames (``$...``) and zero-duration events are
    skipped. This is the ONE gzip+json pass both consumers share: the
    per-fusion aggregation (:func:`parse_trace_dir`) and the
    step-timeline bucketizer (``observability.timeline.analyze``)."""
    files = glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                      recursive=True)
    out = []
    for path in files:
        try:
            with gzip.open(path, "rt") as fh:
                trace = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        events = trace.get("traceEvents", [])
        lanes = {}
        for e in events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                lanes[e["pid"]] = e.get("args", {}).get("name", "")
        device_pids = {pid for pid, name in lanes.items()
                       if name.startswith("/device:")}
        for e in events:
            if e.get("ph") != "X" or not e.get("dur"):
                continue
            name = e.get("name", "")
            if name.startswith("$"):        # python source frames
                continue
            pid = e.get("pid")
            ts = e.get("ts")
            out.append({
                "name": _enrich(name, e.get("args")),
                "ts": float(ts) if ts is not None else None,
                "dur": float(e["dur"]),
                "lane": "device" if pid in device_pids else "host",
                "pid": pid,
                "xla_op": _is_xla_op_event(name)})
    return out


def parse_trace_dir(logdir):
    """Aggregate complete ('X') events from a ``jax.profiler.trace``
    output directory into ``{op_name: (count, total_seconds)}``.

    Prefers device lanes (``/device:...`` processes — real accelerator
    timelines); on backends without device lanes (CPU) falls back to the
    host lane filtered down to XLA op/fusion names.
    """
    return aggregate_events(parse_trace_events(logdir))


def aggregate_events(events):
    """Fold a :func:`parse_trace_events` list into the per-fusion
    ``{name: (count, total_seconds)}`` table (device lanes preferred,
    XLA-op host fallback otherwise — same rule one level up)."""
    has_device = any(e["lane"] == "device" for e in events)
    out = {}
    for e in events:
        if has_device:
            if e["lane"] != "device":
                continue
        elif not e["xla_op"]:
            continue
        cnt, tot = out.get(e["name"], (0, 0.0))
        out[e["name"]] = (cnt + 1, tot + e["dur"] * 1e-6)
    return out


def _enrich(name, args):
    """Fold trace metadata into an uninformative fusion symbol: device
    lanes name events "fusion.NN", but their args often carry the HLO
    long name / source op — without it a banked profile row can't be
    attributed to a model component. Purely additive: events without
    metadata keep their bare name (CPU CI traces are unchanged)."""
    if not isinstance(args, dict):
        return name
    meta = args.get("long_name") or args.get("tf_op") \
        or args.get("hlo_op") or args.get("hlo_category")
    meta = str(meta) if meta else ""
    if meta and meta != name:
        return f"{name}|{meta[:160]}"
    return name


def measure_step_fusions(run_step, logdir=None, events_out=None):
    """Run ``run_step()`` (which must block on its outputs) under a
    profiler trace and return the parsed per-op aggregate. Returns
    ``(result, {name: (count, total_seconds)})``.

    ``events_out``: a list that, when supplied, receives the RAW
    timestamped events (:func:`parse_trace_events`) of the same single
    parse pass — what ``observability.timeline.analyze`` buckets into
    compute/collective/memcpy/host/idle. An out-param so the 2-tuple
    shape every existing caller consumes stays stable.

    PROFILER failures degrade to an empty table; a failure of the step
    itself propagates untouched (re-running an expensive failing step to
    mask a profiling problem would double the damage and bury the real
    traceback). The temporary trace dump is deleted unless the caller
    supplied ``logdir``."""
    import shutil
    import tempfile

    import jax

    d = logdir or tempfile.mkdtemp(prefix="sg_prof_")
    try:
        ctx = None
        try:
            ctx = jax.profiler.trace(d)
            ctx.__enter__()
        except Exception:
            ctx = None
        try:
            result = run_step()
        finally:
            if ctx is not None:
                try:
                    ctx.__exit__(None, None, None)
                except Exception:
                    ctx = None
        table = {}
        if ctx is not None:
            try:
                events = parse_trace_events(d)
                table = aggregate_events(events)
                if events_out is not None:
                    events_out.extend(events)
            except Exception:
                table = {}
        return result, table
    finally:
        # the trace dump can be tens of MB per signature; never leave it
        # behind (including when the step itself raised)
        if logdir is None:
            shutil.rmtree(d, ignore_errors=True)


def summarize_table(table, top=5):
    """Top-``top`` fusions of one measured table by total seconds,
    JSON-able (``[[name, count, seconds], ...]``) — what the sampling
    profiler's ``profile.sample`` flight-recorder event carries so a
    blackbox names the hot fusions without the full table."""
    rows = sorted(table.items(), key=lambda kv: -kv[1][1])[:int(top)]
    return [[name[:120], int(cnt), round(tot, 6)]
            for name, (cnt, tot) in rows]


def record_fusion_metrics(table, registry=None):
    """Publish a measured per-fusion table into the metrics registry
    (gauges labeled by fusion symbol — SET, not accumulated: each
    profile run replaces the previous decomposition). Used by
    ``Model.profile_step``; returns the registry."""
    from .observability import metrics as _metrics
    reg = registry if registry is not None else _metrics.default_registry()
    secs = reg.gauge("profile_fusion_seconds",
                     "measured device seconds per XLA fusion in the "
                     "newest profiled step", labels=("fusion",))
    cnts = reg.gauge("profile_fusion_count",
                     "event count per XLA fusion in the newest "
                     "profiled step", labels=("fusion",))
    for name, (cnt, tot) in table.items():
        secs.set(tot, fusion=name)
        cnts.set(cnt, fusion=name)
    return reg
