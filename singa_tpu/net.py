"""Sequential convenience trainer.

Capability parity with the reference's legacy C++ training API
(``FeedForwardNet`` include/singa/model/feed_forward_net.h:63-116:
``Add``/``Compile``/``Train``/``TrainOnBatch``/``Evaluate``/``Predict``
with a shuffled epoch loop) — rebuilt on the modern Model machinery so the
per-batch step jits into one XLA computation instead of a layer-by-layer
walk.
"""

from __future__ import annotations

import numpy as np

from . import layer as layer_mod
from .data import DevicePrefetcher, NumpyBatchIter
from .metric import Accuracy
from .model import Model
from .tensor import Tensor
from .utils import update_progress


class FeedForwardNet(Model):
    """Stack of layers trained with a (loss, optimizer, metric) triple."""

    def __init__(self, loss=None, metric=None):
        super().__init__()
        self._layers = []
        self.loss_fn = loss or layer_mod.SoftMaxCrossEntropy()
        self.metric = metric or Accuracy()
        self._verbose = True

    def add(self, lyr):
        """Append a layer (reference FeedForwardNet::Add)."""
        self._layers.append(lyr)
        # register for param naming
        setattr(self, f"l{len(self._layers) - 1}", lyr)
        return lyr

    # -- Model hooks -------------------------------------------------------
    def forward(self, x):
        for lyr in self._layers:
            x = lyr(x)
        return x

    def train_one_batch(self, x, y):
        out = self.forward(x)
        loss = self.loss_fn(out, y)
        self.optimizer(loss)
        return out, loss

    # -- reference-style training API --------------------------------------
    def compile_net(self, optimizer, inputs, loss=None, metric=None,
                    use_graph=True):
        """(reference FeedForwardNet::Compile feed_forward_net.h:63-73)"""
        if loss is not None:
            self.loss_fn = loss
        if metric is not None:
            self.metric = metric
        self.set_optimizer(optimizer)
        self.compile([inputs] if isinstance(inputs, Tensor) else inputs,
                     is_train=True, use_graph=use_graph)

    def fit(self, x, y, batch_size=32, epochs=1, shuffle=True,
            dev=None, verbose=True):
        """Epoch loop with shuffling (reference FeedForwardNet::Train
        feed_forward_net.h:82-90; named ``fit`` because ``Model.train``
        toggles the mode). Returns (loss, metric) history per epoch."""
        if not self._compiled:
            raise RuntimeError("call compile_net(optimizer, sample) first")
        if len(x) < batch_size:
            raise ValueError(
                f"dataset of {len(x)} samples is smaller than batch_size "
                f"{batch_size}; no full batch to train on (tails are "
                "dropped to keep compiled-step shapes static)")
        dev = dev or self.dev
        history = []
        for epoch in range(epochs):
            it = NumpyBatchIter(np.asarray(x), np.asarray(y), batch_size,
                                shuffle=shuffle, seed=epoch)
            losses, metrics = [], []
            nb = it.num_batches
            # device staging one batch ahead: H2D transfer of the
            # next batch overlaps the current compiled step. Host
            # label arrays ride alongside so the metric (host-side
            # numpy) doesn't read labels back from the device.
            from collections import deque
            host_y = deque()

            def src():
                for bx, by in it:
                    host_y.append(by)
                    # preserve train_on_batch's historical float32
                    # contract: integer / float64 datasets would
                    # otherwise reach the compiled step with a new
                    # dtype (recompile or type error)
                    yield (np.asarray(bx, np.float32),
                           np.asarray(by, np.float32))

            for i, (tbx, tby) in enumerate(
                    DevicePrefetcher(src(), dev, depth=2)):
                by = host_y.popleft()
                out, loss = self.train_on_batch(tbx, tby, dev)
                losses.append(float(loss.data))
                metrics.append(self.metric.evaluate(out, by))
                if verbose:
                    update_progress(
                        (i + 1) / nb,
                        f"epoch {epoch} loss {np.mean(losses):.4f} "
                        f"metric {np.mean(metrics):.4f}")
            history.append((float(np.mean(losses)),
                            float(np.mean(metrics))))
        return history

    def train_on_batch(self, x, y, dev=None):
        """(reference FeedForwardNet::TrainOnBatch :92)"""
        dev = dev or self.dev
        tx = x if isinstance(x, Tensor) else Tensor(
            data=np.asarray(x, np.float32), device=dev, requires_grad=False)
        ty = y if isinstance(y, Tensor) else Tensor(
            data=np.asarray(y, np.float32), device=dev, requires_grad=False)
        return self(tx, ty)

    def evaluate(self, x, y, batch_size=32, dev=None):
        """Mean (loss, metric) without updates
        (reference FeedForwardNet::Evaluate :103)."""
        dev = dev or self.dev
        was_training = self._train
        self.eval()
        losses, metrics, weights = [], [], []
        try:
            it = NumpyBatchIter(np.asarray(x), np.asarray(y), batch_size,
                                shuffle=False, drop_last=False)
            for bx, by in it:
                tx = Tensor(data=np.asarray(bx, np.float32), device=dev,
                            requires_grad=False)
                ty = Tensor(data=np.asarray(by, np.float32), device=dev,
                            requires_grad=False)
                out = self(tx)
                losses.append(float(self.loss_fn(out, ty).data))
                metrics.append(self.metric.evaluate(out, by))
                weights.append(len(bx))
        finally:
            if was_training:
                self.train(True)
        # per-sample average: the tail batch must not be over-weighted
        return (float(np.average(losses, weights=weights)),
                float(np.average(metrics, weights=weights)))

    def predict(self, x, batch_size=32, dev=None):
        """Forward in eval mode (reference FeedForwardNet::Predict :109)."""
        dev = dev or self.dev
        was_training = self._train
        self.eval()
        outs = []
        try:
            n = len(x)
            for b in range(0, n, batch_size):
                tx = Tensor(data=np.asarray(x[b:b + batch_size],
                                            np.float32),
                            device=dev, requires_grad=False)
                outs.append(np.asarray(self(tx).data))
        finally:
            if was_training:
                self.train(True)
        return np.concatenate(outs, axis=0)

    # C++-style aliases (reference FeedForwardNet::Train/Evaluate/Predict)
    Train = fit
    TrainOnBatch = train_on_batch
    Evaluate = evaluate
    Predict = predict
    Add = add
