"""Vendored wire-compatible Caffe proto subset (see caffe.proto)."""
from . import caffe_pb2  # noqa: F401
