"""Async sharded checkpointing on Orbax.

The reference's persistence routes (Snapshot .bin/.desc, the
save_states zip — reference model.py:244-330, src/io/snapshot.cc:33-80)
both serialize through ONE host copy of every array. For models whose
state is tp/ep/pp-sharded across a mesh (or across hosts), this module
adds the TPU-idiomatic third route: state is read from the LIVE tensors
(no gather, no full-model host copy — each process contributes only its
addressable shards) and the write happens ASYNCHRONOUSLY, so training
steps continue while bytes land on disk.

    ck = AsyncModelCheckpointer()
    ck.save(path, model)          # returns immediately; shards stream out
    ...training continues...
    ck.wait()                     # barrier before e.g. rotating dirs
    ck.restore(path, model)       # shards land back WITH their shardings

Restore is driven by the CHECKPOINT's metadata (not the live state), so
a freshly constructed process — whose lazily-created optimizer aux does
not exist yet — restores momentum/moments too and replays the exact
trajectory. Arrays whose live counterpart exists restore onto that
array's current sharding.
"""

from __future__ import annotations

import os

import numpy as np
import jax


def _state_tensor_dict(model):
    """name -> LIVE Tensor for every model state + optimizer aux (no
    gather, no host copy — unlike get_states()/save_states)."""
    out = {}
    for k, t in model.get_states().items():
        out[f"model/{k}"] = t
    opt = getattr(model, "optimizer", None)
    if opt is not None and hasattr(opt, "state_tensor_dict"):
        for k, t in opt.state_tensor_dict().items():
            out[f"optimizer/{k}"] = t
    return out


class AsyncModelCheckpointer:
    """Orbax ``AsyncCheckpointer`` over a Model's state pytree."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path, model, force=True):
        """Start an async save of params + optimizer aux; returns
        immediately (the previous pending save is awaited first, as
        orbax allows a single outstanding write)."""
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        self._ckptr.save(os.path.abspath(str(path)),
                         args=self._ocp.args.StandardSave(arrays),
                         force=force)

    def wait(self):
        """Block until the outstanding async save has fully committed."""
        self._ckptr.wait_until_finished()

    def restore(self, path, model):
        """Load shards back into the model's live tensors.

        The restore template comes from the checkpoint's OWN metadata:
        every saved entry is restored (lazily-created optimizer aux that
        a fresh process has not materialised yet included), and entries
        with a live counterpart restore onto that array's current
        sharding — so a mesh-sharded model resumes without a gather or
        re-shard step."""
        path = os.path.abspath(str(path))
        live = _state_tensor_dict(model)
        meta = self._ckptr.metadata(path).item_metadata.tree
        template = {}
        for k, m in meta.items():
            shape = tuple(m.shape)
            sharding = None
            lt = live.get(k)
            if lt is not None and tuple(np.shape(lt.data)) == shape:
                sharding = getattr(lt.data, "sharding", None)
            template[k] = jax.ShapeDtypeStruct(shape, m.dtype,
                                               sharding=sharding)
        restored = self._ckptr.restore(
            path, args=self._ocp.args.StandardRestore(template))
        opt = getattr(model, "optimizer", None)
        for k, arr in restored.items():
            lt = live.get(k)
            if lt is not None:
                lt.data = arr
            elif k.startswith("optimizer/") and opt is not None \
                    and hasattr(opt, "restore_state_tensor"):
                # aux the fresh process has not lazily created yet;
                # momentum/moments shard like their param, so hand the
                # param's spec along (aux keys are '<param>:<kind>')
                nm = k[len("optimizer/"):]
                base = nm.split("/", 1)[-1].rsplit(":", 1)[0]
                pt = model.get_states().get(base)
                opt.restore_state_tensor(
                    nm, arr, getattr(pt, "spec", None))
            else:
                import warnings
                warnings.warn(f"checkpoint entry {k!r} has no live "
                              "counterpart in this model; skipped",
                              stacklevel=2)
        # compiled steps close over state identity; force a rebind
        model._invalidate_compiled()

    def close(self):
        self._ckptr.close()
