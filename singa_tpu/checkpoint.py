"""Async sharded checkpointing on Orbax.

The reference's persistence routes (Snapshot .bin/.desc, the
save_states zip — reference model.py:244-330, src/io/snapshot.cc:33-80)
both serialize through ONE host copy of every array. For models whose
state is tp/ep/pp-sharded across a mesh (or across hosts), this module
adds the TPU-idiomatic third route: state is read from the LIVE tensors
(no gather, no full-model host copy — each process contributes only its
addressable shards) and the write happens ASYNCHRONOUSLY, so training
steps continue while bytes land on disk.

    ck = AsyncModelCheckpointer()
    ck.save(path, model)          # returns immediately; shards stream out
    ...training continues...
    ck.wait()                     # barrier before e.g. rotating dirs
    ck.restore(path, model)       # shards land back WITH their shardings

Restore is driven by the CHECKPOINT's metadata (not the live state), so
a freshly constructed process — whose lazily-created optimizer aux does
not exist yet — restores momentum/moments too and replays the exact
trajectory. Every entry restores onto the CURRENT topology: live
counterparts keep their sharding, fresh optimizer aux adopts its owning
param's live sharding (never the layout persisted by a possibly
different mesh).
"""

from __future__ import annotations

import os
import warnings

import numpy as np
import jax


def _state_tensor_dict(model):
    """name -> LIVE Tensor for every model state + optimizer aux (no
    gather, no host copy — unlike get_states()/save_states)."""
    out = {}
    for k, t in model.get_states().items():
        out[f"model/{k}"] = t
    opt = getattr(model, "optimizer", None)
    if opt is not None and hasattr(opt, "state_tensor_dict"):
        for k, t in opt.state_tensor_dict().items():
            out[f"optimizer/{k}"] = t
    return out


def _aux_param_base(name):
    """'<param>:<kind>' (optionally 'residual/<param>') -> param name."""
    return name.split("/", 1)[-1].rsplit(":", 1)[0]


def _build_restore_template(live, meta_tree):
    """ShapeDtypeStruct tree for StandardRestore, keyed by the
    CHECKPOINT's metadata. Sharding targets come from the CURRENT
    process: a live counterpart's sharding when shapes agree, else —
    for fresh optimizer aux — the owning param's live sharding (the
    layout persisted in the checkpoint may belong to a different
    topology, which orbax itself flags as unsafe to reuse)."""
    template = {}
    for k, m in meta_tree.items():
        shape = tuple(m.shape)
        sharding = None
        lt = live.get(k)
        if lt is not None and tuple(np.shape(lt.data)) == shape:
            sharding = getattr(lt.data, "sharding", None)
        elif lt is None and k.startswith("optimizer/"):
            base = live.get(
                "model/" + _aux_param_base(k[len("optimizer/"):]))
            if base is not None and \
                    tuple(np.shape(base.data)) == shape:
                sharding = getattr(base.data, "sharding", None)
        template[k] = jax.ShapeDtypeStruct(shape, m.dtype,
                                           sharding=sharding)
    return template


def _apply_restored(model, live, restored):
    """Land restored arrays in the live tensors; create lazily-built
    optimizer aux that a fresh process has not materialised yet
    (announcing the owning param's spec so it keeps sharding like its
    param); skip — loudly — anything without a live home or with a
    mismatched shape (e.g. resuming into a re-architected model)."""
    opt = getattr(model, "optimizer", None)
    for k, arr in restored.items():
        lt = live.get(k)
        if lt is not None:
            if tuple(np.shape(lt.data)) != tuple(np.shape(arr)):
                warnings.warn(
                    f"checkpoint entry {k!r} has shape "
                    f"{tuple(np.shape(arr))} but the live tensor is "
                    f"{tuple(np.shape(lt.data))}; skipped (did the "
                    "architecture change since the save?)", stacklevel=3)
                continue
            lt.data = arr
        elif k.startswith("optimizer/") and opt is not None \
                and hasattr(opt, "restore_state_tensor"):
            nm = k[len("optimizer/"):]
            pt = live.get("model/" + _aux_param_base(nm))
            opt.restore_state_tensor(nm, arr, getattr(pt, "spec", None))
        else:
            warnings.warn(f"checkpoint entry {k!r} has no live "
                          "counterpart in this model; skipped",
                          stacklevel=3)
    # compiled steps close over state identity; force a rebind
    model._invalidate_compiled()


class AsyncModelCheckpointer:
    """Orbax ``AsyncCheckpointer`` over a Model's state pytree."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path, model, force=True):
        """Start an async save of params + optimizer aux; returns
        immediately (the previous pending save is awaited first, as
        orbax allows a single outstanding write)."""
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        self._ckptr.save(os.path.abspath(str(path)),
                         args=self._ocp.args.StandardSave(arrays),
                         force=force)

    def wait(self):
        """Block until the outstanding async save has fully committed."""
        self._ckptr.wait_until_finished()

    def restore(self, path, model):
        """Load shards back into the model's live tensors (see the
        module docstring for the template/topology rules)."""
        path = os.path.abspath(str(path))
        live = _state_tensor_dict(model)
        # orbax API drift: metadata() returns a plain dict tree on
        # newer versions, a CheckpointMetadata wrapper on older ones
        raw = self._ckptr.metadata(path)
        tree = getattr(getattr(raw, "item_metadata", None), "tree", None)
        meta = dict(tree if tree is not None else raw)
        restored = self._ckptr.restore(
            path, args=self._ocp.args.StandardRestore(
                _build_restore_template(live, meta)))
        _apply_restored(model, live, restored)

    def close(self):
        self._ckptr.close()


class CheckpointManager:
    """Rotated, step-numbered checkpoints over the async sharded route
    (orbax ``CheckpointManager``): save every ``save_interval_steps``,
    keep the newest ``max_to_keep``, resume from the latest — the
    checkpoint-restart loop the reference lacks entirely (its NCCL/MPI
    failures just exit, include/singa/io/communicator.h:40-67).

        mgr = CheckpointManager(dir, max_to_keep=3, save_interval_steps=50)
        start = mgr.restore_latest(model)        # 0 on a fresh run
        for step in range(start, total):
            model(tx, ty)
            mgr.save(step, model)                # no-op off-interval
        mgr.wait(); mgr.close()
    """

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._dir = os.path.abspath(str(directory))
        self._max_to_keep = max_to_keep
        self._save_interval_steps = save_interval_steps
        self._mgr = self._make_mgr()
        self._sweep_uncommitted()

    def _make_mgr(self):
        ocp = self._ocp
        return ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep,
                save_interval_steps=self._save_interval_steps,
                enable_async_checkpointing=True),
            # a FRESH manager (resume path) must know the handler type
            # before any save, or item metadata cannot be read
            item_handlers=ocp.StandardCheckpointHandler())

    def _sweep_uncommitted(self):
        """Remove step directories a dead writer left without a commit
        marker. A process killed mid-async-save (the normal way a
        preempted job dies) leaves the step's directory on disk but
        absent from ``all_steps()``; the restarted job resumes from an
        earlier step, re-trains, and its ``save`` of that step number
        would then refuse — 'destination already exists' — stranding
        the run. Single-writer-per-directory is assumed (as it is for
        rotation)."""
        import shutil
        committed = {str(s) for s in self._mgr.all_steps()}
        try:
            entries = os.listdir(self._dir)
        except OSError:
            return
        for name in entries:
            # only orbax's own artifacts: an exact step-number dir with
            # no commit marker, or an orbax tmp dir. Anything else in
            # here (a user's "3.backup", notes, …) is not ours to delete
            wreck = (name.isdigit() and name not in committed) or \
                ".orbax-checkpoint-tmp" in name
            if wreck:
                path = os.path.join(self._dir, name)
                if os.path.isdir(path):
                    warnings.warn(
                        f"removing uncommitted checkpoint wreckage "
                        f"{path} (a previous writer died mid-save)",
                        stacklevel=3)
                    shutil.rmtree(path, ignore_errors=True)

    def save(self, step, model, force=False):
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        return self._mgr.save(int(step),
                              args=self._ocp.args.StandardSave(arrays),
                              force=force)

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def _restore_step(self, step, model):
        live = _state_tensor_dict(model)
        meta = self._mgr.item_metadata(step)
        tree = dict(getattr(meta, "tree", None) or meta)
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(
                _build_restore_template(live, tree)))
        _apply_restored(model, live, restored)

    def restore_latest(self, model):
        """Restore the newest RESTORABLE checkpoint into ``model`` and
        return the NEXT step to run (0 when no checkpoint exists).

        A preempted or crashed writer can leave the newest step
        truncated or corrupt on disk even when its commit marker made
        it down; raising there would strand a job that has perfectly
        good earlier checkpoints. So restorability is verified by
        attempting the restore, scanning BACKWARD: a step that fails to
        load is warned about — loudly — and the scan falls back to the
        previous one. (A failed attempt may have partially landed
        arrays in the live tensors; the succeeding attempt overwrites
        every entry, so the model never trains on a half-restored mix.)
        """
        steps = sorted(self._mgr.all_steps(), reverse=True)
        for i, step in enumerate(steps):
            try:
                self._restore_step(step, model)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                warnings.warn(
                    f"checkpoint step {step} is not restorable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"previous step", stacklevel=2)
                continue
            if i:
                warnings.warn(
                    f"resumed from step {step} after skipping {i} "
                    f"corrupt/incomplete newer checkpoint(s) — up to "
                    f"{steps[0] - step} step(s) of work were lost",
                    stacklevel=2)
                # delete the skipped wreckage and rebuild the manager:
                # while a corrupt step remains the directory's newest,
                # orbax's should_save refuses every interval save of the
                # re-run window (step <= latest), so a second crash
                # there would lose the same stretch of work again
                import shutil
                for bad_step in steps[:i]:
                    shutil.rmtree(os.path.join(self._dir, str(bad_step)),
                                  ignore_errors=True)
                self._mgr.close()
                self._mgr = self._make_mgr()
            return step + 1
        if steps:
            warnings.warn(
                f"none of the {len(steps)} checkpoints under this "
                "directory are restorable; starting from scratch",
                stacklevel=2)
            # same stranding as the partial-fallback case: while the
            # corrupt steps remain committed, orbax refuses every save
            # of the from-scratch re-run (step <= latest) — clear them
            import shutil
            for bad_step in steps:
                shutil.rmtree(os.path.join(self._dir, str(bad_step)),
                              ignore_errors=True)
            self._mgr.close()
            self._mgr = self._make_mgr()
        return 0

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
