"""Async sharded checkpointing on Orbax.

The reference's persistence routes (Snapshot .bin/.desc, the
save_states zip — reference model.py:244-330, src/io/snapshot.cc:33-80)
both serialize through ONE host copy of every array. For models whose
state is tp/ep/pp-sharded across a mesh (or across hosts), this module
adds the TPU-idiomatic third route: state is read from the LIVE tensors
(no gather, no full-model host copy — each process contributes only its
addressable shards) and the write happens ASYNCHRONOUSLY, so training
steps continue while bytes land on disk.

    ck = AsyncModelCheckpointer()
    ck.save(path, model)          # returns immediately; shards stream out
    ...training continues...
    ck.wait()                     # barrier before e.g. rotating dirs
    ck.restore(path, model)       # shards land back WITH their shardings

Restore is driven by the CHECKPOINT's metadata (not the live state), so
a freshly constructed process — whose lazily-created optimizer aux does
not exist yet — restores momentum/moments too and replays the exact
trajectory. Every entry restores onto the CURRENT topology: live
counterparts keep their sharding, fresh optimizer aux adopts its owning
param's live sharding (never the layout persisted by a possibly
different mesh). ZeRO/FSDP needs no special handling here for the same
reason: the sharded-optimizer layout is recomputed from announced specs
when the GSPMD step compiles (``gspmd.fsdp_state_spec``), so a
ZeRO-sharded checkpoint restores bit-identical into the same mesh, a
different data degree, or an unsharded model — the live run owns the
layout, the checkpoint owns the bytes.
"""

from __future__ import annotations

import json
import os
import time
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from .integrity import (IntegrityError, data_state_digest, digest_tree,
                        manifest_digest, read_digest_sidecar,
                        verify_tree, write_digest_sidecar)
from .observability import metrics as _obs_metrics


def _obs_restore_done(t0, fallback_depth):
    """Shared restore accounting for both manager flavours: duration,
    and how many newer steps had to be skipped to find a restorable one
    (``fallback_depth`` > 0 means work was lost — the gauge a dashboard
    alarms on)."""
    reg = _obs_metrics.default_registry()
    reg.histogram("checkpoint_restore_seconds",
                  "restore_latest wall-clock, fallbacks included"
                  ).observe(time.perf_counter() - t0)
    reg.gauge("checkpoint_restore_fallback_depth",
              "newer unrestorable steps skipped by the latest restore"
              ).set(fallback_depth)
    if fallback_depth:
        reg.counter("checkpoint_restore_fallbacks_total",
                    "corrupt/incomplete steps skipped across restores"
                    ).inc(fallback_depth)


def _state_tensor_dict(model):
    """name -> LIVE Tensor for every model state + optimizer aux (no
    gather, no host copy — unlike get_states()/save_states)."""
    out = {}
    for k, t in model.get_states().items():
        out[f"model/{k}"] = t
    opt = getattr(model, "optimizer", None)
    if opt is not None and hasattr(opt, "state_tensor_dict"):
        for k, t in opt.state_tensor_dict().items():
            out[f"optimizer/{k}"] = t
    return out


def _aux_param_base(name):
    """'<param>:<kind>' (optionally 'residual/<param>') -> param name."""
    return name.split("/", 1)[-1].rsplit(":", 1)[0]


def _adapt_float(arr, target_dt):
    """Adapt a restored array to a live/template dtype, float-to-float
    only: a checkpoint written under a different precision mode (pure
    bf16 params vs fp32 masters) lands in the LIVE dtype so the
    compiled step's avals — and state donation — survive the migration.
    bf16→f32 is lossless; the reverse is the destination policy's own
    quantisation. Same-dtype (and any non-float) input passes through
    untouched, bit-identical."""
    arr_dt = getattr(arr, "dtype", None)
    if (target_dt is not None and arr_dt is not None
            and target_dt != arr_dt
            and jnp.issubdtype(target_dt, jnp.floating)
            and jnp.issubdtype(arr_dt, jnp.floating)):
        return jnp.asarray(arr, dtype=target_dt)
    return arr


def _build_restore_template(live, meta_tree):
    """ShapeDtypeStruct tree for StandardRestore, keyed by the
    CHECKPOINT's metadata. Sharding targets come from the CURRENT
    process: a live counterpart's sharding when shapes agree, else —
    for fresh optimizer aux — the owning param's live sharding (the
    layout persisted in the checkpoint may belong to a different
    topology, which orbax itself flags as unsafe to reuse)."""
    template = {}
    for k, m in meta_tree.items():
        shape = tuple(m.shape)
        sharding = None
        lt = live.get(k)
        if lt is not None and tuple(np.shape(lt.data)) == shape:
            sharding = getattr(lt.data, "sharding", None)
        elif lt is None and k.startswith("optimizer/"):
            base = live.get(
                "model/" + _aux_param_base(k[len("optimizer/"):]))
            if base is not None and \
                    tuple(np.shape(base.data)) == shape:
                sharding = getattr(base.data, "sharding", None)
        template[k] = jax.ShapeDtypeStruct(shape, m.dtype,
                                           sharding=sharding)
    return template


def _apply_restored(model, live, restored):
    """Land restored arrays in the live tensors; create lazily-built
    optimizer aux that a fresh process has not materialised yet
    (announcing the owning param's spec so it keeps sharding like its
    param); skip — loudly — anything without a live home or with a
    mismatched shape (e.g. resuming into a re-architected model).

    A QUANTIZED checkpoint (``tools/quantize_checkpoint.py`` /
    ``quant.quantize_state_arrays``) carries int8 payloads plus
    ``quant-scale/<key>`` fp32 sidecars: restoring one into a model
    with floating masters dequantizes payload × scale here and then
    rides :func:`_adapt_float`'s normal rules into the live master
    dtype — so a 4x-smaller checkpoint restores into fp32 masters with
    no extra ceremony. Restoring one into a model quantized IN PLACE
    (``quant.quantize_params`` — live int8 payloads, scales under live
    ``<prefix>quant-scale/<name>`` tensors) lands the payload verbatim
    and the sidecar scale into its live scale tensor: an int8 payload
    without its matching scale is wrong weights, so a sidecar scale
    with no home is a LOUD skip like any other orphan entry."""
    from .quant.core import SCALE_PREFIX as _QSCALE
    from .quant.core import dequantize_entry
    q_scales = {k[len(_QSCALE):]: a for k, a in restored.items()
                if k.startswith(_QSCALE)}
    opt = getattr(model, "optimizer", None)
    for k, arr in restored.items():
        if k.startswith(_QSCALE):
            base = k[len(_QSCALE):]
            lt0 = live.get(base)
            tdt = getattr(getattr(lt0, "data", None), "dtype", None)
            if tdt is not None and jnp.issubdtype(tdt, jnp.floating):
                continue    # consumed by the payload's dequant below
            # int8-live payload: the scale's home is the live
            # quant-scale tensor ('model/<n>' -> 'model/quant-scale/<n>')
            head, _sep, tail = base.rpartition("/")
            home = live.get(f"{head}/{_QSCALE}{tail}" if head
                            else _QSCALE + tail)
            if home is not None and \
                    tuple(np.shape(home.data)) == tuple(np.shape(arr)):
                home.data = arr
                continue
            warnings.warn(
                f"checkpoint entry {k!r} (quantization scale) has no "
                "live scale tensor and its payload did not dequantize "
                "into floating masters; skipped — the restored int8 "
                "payload may be mis-scaled", stacklevel=3)
            continue
        if (k in q_scales
                and np.dtype(getattr(arr, "dtype", None)) == np.int8):
            lt0 = live.get(k)
            tdt = getattr(getattr(lt0, "data", None), "dtype", None)
            if tdt is not None and jnp.issubdtype(tdt, jnp.floating):
                arr = dequantize_entry(arr, q_scales[k])
        lt = live.get(k)
        if lt is not None:
            if tuple(np.shape(lt.data)) != tuple(np.shape(arr)):
                warnings.warn(
                    f"checkpoint entry {k!r} has shape "
                    f"{tuple(np.shape(arr))} but the live tensor is "
                    f"{tuple(np.shape(lt.data))}; skipped (did the "
                    "architecture change since the save?)", stacklevel=3)
                continue
            tdt = getattr(lt.data, "dtype", None)
            if (tdt is not None and jnp.dtype(tdt) == jnp.int8
                    and jnp.issubdtype(
                        np.dtype(getattr(arr, "dtype", np.int8)),
                        np.floating)):
                # an fp32 checkpoint restored into an in-place-
                # quantized model (warm restart after quantize_params):
                # landing the float bytes verbatim would make the
                # dequant scope multiply full-precision weights by the
                # stale scale (~100x shrink). Re-quantize fresh and
                # land the new scale beside the payload.
                from .quant.core import (SCALE_PREFIX, channel_axis,
                                         quantize_int8)
                head, _sep, tail = k.rpartition("/")
                home = live.get(f"{head}/{SCALE_PREFIX}{tail}" if head
                                else SCALE_PREFIX + tail)
                if home is None:
                    warnings.warn(
                        f"checkpoint entry {k!r} is float but the live "
                        "tensor is an int8 payload with no live scale "
                        "tensor; skipped", stacklevel=3)
                    continue
                q, s = quantize_int8(np.asarray(arr),
                                     channel_axis(np.shape(arr)))
                lt.data = q
                home.data = s
                continue
            lt.data = _adapt_float(arr, tdt)
        elif k.startswith("optimizer/") and opt is not None \
                and hasattr(opt, "restore_state_tensor"):
            nm = k[len("optimizer/"):]
            pt = live.get("model/" + _aux_param_base(nm))
            # lazily-built aux has no live tensor to adapt to yet — the
            # owning param's dtype is its template (momentum must match
            # its master, or the first step promotes and retraces)
            arr = _adapt_float(
                arr, getattr(getattr(pt, "data", None), "dtype", None))
            opt.restore_state_tensor(nm, arr, getattr(pt, "spec", None))
        else:
            warnings.warn(f"checkpoint entry {k!r} has no live "
                          "counterpart in this model; skipped",
                          stacklevel=3)
    # compiled steps close over state identity; force a rebind
    model._invalidate_compiled()


class AsyncModelCheckpointer:
    """Orbax ``AsyncCheckpointer`` over a Model's state pytree."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path, model, force=True):
        """Start an async save of params + optimizer aux; returns
        immediately (the previous pending save is awaited first, as
        orbax allows a single outstanding write)."""
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        self._ckptr.save(os.path.abspath(str(path)),
                         args=self._ocp.args.StandardSave(arrays),
                         force=force)

    def wait(self):
        """Block until the outstanding async save has fully committed."""
        self._ckptr.wait_until_finished()

    def restore(self, path, model):
        """Load shards back into the model's live tensors (see the
        module docstring for the template/topology rules)."""
        path = os.path.abspath(str(path))
        live = _state_tensor_dict(model)
        # orbax API drift: metadata() returns a plain dict tree on
        # newer versions, a CheckpointMetadata wrapper on older ones
        raw = self._ckptr.metadata(path)
        tree = getattr(getattr(raw, "item_metadata", None), "tree", None)
        meta = dict(tree if tree is not None else raw)
        restored = self._ckptr.restore(
            path, args=self._ocp.args.StandardRestore(
                _build_restore_template(live, meta)))
        _apply_restored(model, live, restored)

    def close(self):
        self._ckptr.close()


class CheckpointManager:
    """Rotated, step-numbered checkpoints over the async sharded route
    (orbax ``CheckpointManager``): save every ``save_interval_steps``,
    keep the newest ``max_to_keep``, resume from the latest — the
    checkpoint-restart loop the reference lacks entirely (its NCCL/MPI
    failures just exit, include/singa/io/communicator.h:40-67).

        mgr = CheckpointManager(dir, max_to_keep=3, save_interval_steps=50)
        start = mgr.restore_latest(model)        # 0 on a fresh run
        for step in range(start, total):
            model(tx, ty)
            mgr.save(step, model)                # no-op off-interval
        mgr.wait(); mgr.close()
    """

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1,
                 sweep=True, digests=True):
        """``sweep=False`` skips the uncommitted-wreckage sweep at init —
        for READ-ONLY managers opened on a directory another rank owns
        (the elastic cross-rank restore path must never delete a live
        writer's in-flight step). ``digests=False`` disables the
        per-tensor content-digest sidecars (``<dir>/digests/<step>.json``)
        written with every save and re-verified before any restore
        hands state back — only for callers that measure the host-side
        CRC cost and prefer orbax's own parse errors as the sole
        corruption detector."""
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._dir = os.path.abspath(str(directory))
        self._max_to_keep = max_to_keep
        self._save_interval_steps = save_interval_steps
        self._digests_on = bool(digests)
        self._digest_dir = os.path.join(self._dir, "digests")
        self._data_dir = os.path.join(self._dir, "data_state")
        # digest tree of the newest save (the distributed manager acks
        # its manifest digest to the cluster); None when digests are off
        self.last_saved_digests = None
        # digest of the newest save's data-iterator state (rides the
        # two-phase ACK beside the tensor digest); None when the save
        # carried no data state
        self.last_saved_data_digest = None
        # data-iterator state of the newest successful restore_latest
        # (the trainer rewinds its iterator to it); None when the step
        # predates data-state capture
        self.restored_data_state = None
        self._restored_data_state = None
        self._mgr = self._make_mgr()
        if sweep:
            self._sweep_uncommitted()
        # steps THIS manager owns: present at init (post-sweep) or
        # saved by us. A step directory that appears outside this set
        # is a dead predecessor's late-finalized write — the save
        # retry below keys on membership here, never on orbax
        # internals (error text, all_steps caching)
        self._known_steps = {int(s) for s in self._mgr.all_steps()}

    def _make_mgr(self):
        ocp = self._ocp
        return ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep,
                save_interval_steps=self._save_interval_steps,
                enable_async_checkpointing=True),
            # a FRESH manager (resume path) must know the handler type
            # before any save, or item metadata cannot be read
            item_handlers=ocp.StandardCheckpointHandler())

    def _sweep_uncommitted(self):
        """Remove step directories a dead writer left without a commit
        marker. A process killed mid-async-save (the normal way a
        preempted job dies) leaves the step's directory on disk but
        absent from ``all_steps()``; the restarted job resumes from an
        earlier step, re-trains, and its ``save`` of that step number
        would then refuse — 'destination already exists' — stranding
        the run. Single-writer-per-directory is assumed (as it is for
        rotation)."""
        import shutil
        committed = {str(s) for s in self._mgr.all_steps()}
        try:
            entries = os.listdir(self._dir)
        except OSError:
            return
        for name in entries:
            # only orbax's own artifacts: an exact step-number dir with
            # no commit marker, or an orbax tmp dir. Anything else in
            # here (a user's "3.backup", notes, …) is not ours to delete
            wreck = (name.isdigit() and name not in committed) or \
                ".orbax-checkpoint-tmp" in name
            if wreck:
                path = os.path.join(self._dir, name)
                if os.path.isdir(path):
                    warnings.warn(
                        f"removing uncommitted checkpoint wreckage "
                        f"{path} (a previous writer died mid-save)",
                        stacklevel=3)
                    shutil.rmtree(path, ignore_errors=True)

    def _reopen(self):
        """Rebuild the orbax manager after step dirs were deleted out
        from under it (its should_save else refuses the re-run window),
        and prune digest sidecars down to the surviving steps."""
        self._mgr.close()
        self._mgr = self._make_mgr()
        self._prune_digests()

    # -- content digests ---------------------------------------------------
    def _digest_path(self, step):
        return os.path.join(self._digest_dir, f"{int(step)}.json")

    def _write_digests(self, step, tree):
        os.makedirs(self._digest_dir, exist_ok=True)
        write_digest_sidecar(self._digest_path(step), tree,
                             step=int(step))
        self._prune_digests()

    def read_digests(self, step):
        """The step's digest sidecar dict ({"algo","records","manifest"})
        or None when absent (a pre-integrity save) / unreadable."""
        return read_digest_sidecar(self._digest_path(step))

    def _prune_digests(self, keep=None):
        """Sidecars (tensor digests AND data states) follow the step
        rotation: one whose step orbax (or a wreckage sweep) already
        deleted is dead weight."""
        keep = {int(s) for s in (self._mgr.all_steps()
                                 if keep is None else keep)}
        for d in (self._digest_dir, self._data_dir):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                if n.endswith(".json") and n[:-5].isdigit() \
                        and int(n[:-5]) not in keep:
                    try:
                        os.remove(os.path.join(d, n))
                    except OSError:
                        pass

    # -- data-iterator state -----------------------------------------------
    def _data_state_path(self, step):
        return os.path.join(self._data_dir, f"{int(step)}.json")

    def _write_data_state(self, step, state):
        """Persist the data pipeline's ``state_dict()`` beside the step
        — synchronous (the state is a few counters) and atomic, with
        its own content digest: the sample-stream offset a resume
        rewinds to is vouched for exactly like the tensors."""
        os.makedirs(self._data_dir, exist_ok=True)
        digest = data_state_digest(state)
        doc = {"step": int(step), "state": state, "digest": digest}
        path = self._data_state_path(step)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return digest

    def read_data_state(self, step):
        """The step's verified data-iterator state, or None when the
        step carries none (a pre-data-state save, or a run without a
        checkpointable iterator). Raises
        :class:`~singa_tpu.integrity.IntegrityError` when the sidecar
        exists but its content does not match its digest — a corrupt
        data offset must drive the same fallback chain as corrupt
        tensor bytes, never silently restart the stream."""
        try:
            with open(self._data_state_path(step)) as f:
                doc = json.load(f)
        except OSError:
            return None
        except ValueError as e:
            raise IntegrityError(
                f"checkpoint step {step}: data-state sidecar is "
                f"unparseable ({e})")
        state = doc.get("state")
        want = doc.get("digest")
        if state is None or want is None or \
                data_state_digest(state) != want:
            raise IntegrityError(
                f"checkpoint step {step}: data-state sidecar failed "
                f"its digest check — the resume offset is corrupt")
        return state

    def _verify_restored(self, step, restored, expect_manifest=None):
        """Verify restored arrays against the step's digest sidecar
        BEFORE they land in any live tensor — and, when the caller
        holds a cluster-committed manifest digest, against THAT too: a
        shard whose sidecar agrees with its own bytes but not with the
        commit marker is a stale/foreign shard wearing the right step
        number, and must be rejected before it touches training state.
        Raises :class:`~singa_tpu.integrity.IntegrityError` on any
        mismatch; returns the sidecar dict (None when the step predates
        the integrity layer — accepted, loudly)."""
        if not self._digests_on:
            return None
        expected = self.read_digests(step)
        if expected is None:
            if expect_manifest:
                # the commit marker carries the cluster-agreed digest,
                # so the shard can be verified DIRECTLY against it even
                # without its sidecar (lost, or this rank's sidecar
                # write failed at save time): recompute the manifest
                # digest from the restored bytes. A healthy shard
                # passes — no crash loop for a rank whose bookkeeping
                # failed — while a stale/corrupt shard still fails to
                # the next source, never reaching live tensors.
                tree = digest_tree(restored)
                got = manifest_digest(tree)
                if got != expect_manifest:
                    raise IntegrityError(
                        f"checkpoint step {step}: no digest sidecar, "
                        f"and the restored content ({got}) does not "
                        f"match the cluster-committed "
                        f"{expect_manifest} — stale or corrupt shard")
                warnings.warn(
                    f"checkpoint step {step}: digest sidecar missing; "
                    "shard re-verified directly against the cluster-"
                    "committed manifest digest", stacklevel=3)
                return {"algo": "crc32", "records": tree,
                        "manifest": got}
            warnings.warn(
                f"checkpoint step {step} has no digest sidecar (saved "
                "before the integrity layer?); restoring UNVERIFIED",
                stacklevel=3)
            return None
        bad = verify_tree(restored, expected["records"])
        if bad:
            raise IntegrityError(
                f"checkpoint step {step}: content digest mismatch for "
                f"{len(bad)} entr{'y' if len(bad) == 1 else 'ies'} "
                f"(first: {bad[:3]}) — the shard is corrupt on disk")
        got = expected.get("manifest")
        if expect_manifest and got and expect_manifest != got:
            raise IntegrityError(
                f"checkpoint step {step}: shard manifest digest {got} "
                f"does not match the cluster-committed "
                f"{expect_manifest}")
        return expected

    def save(self, step, model, force=False, data_state=None):
        t0 = time.perf_counter()
        # one outstanding digest worker, like orbax's one outstanding
        # write — and joined BEFORE the next orbax save so the worker's
        # all_steps()-based sidecar pruning never overlaps a write
        self._join_digest_thread()
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        try:
            saved = self._mgr.save(
                int(step), args=self._ocp.args.StandardSave(arrays),
                force=force)
        except ValueError:
            # a crashed predecessor's zombie orbax machinery can still
            # mutate the directory after our init raced past it:
            # (a) its async writer FINALIZES its step dir (a rename)
            # after _sweep_uncommitted's rmtree — orbax then refuses
            # our re-save of the step a restore legitimately re-ran —
            # detected STRUCTURALLY (a step dir on disk this manager
            # never owned; not orbax's error text, which is unpinned);
            # (b) its ROTATION deletes an old step this manager had
            # already cached in its step list — orbax's next
            # should-remove scan then raises on the vanished dir.
            # Both recover the same way: reconcile with the on-disk
            # state (drop the foreign dir for (a), rebuild the manager
            # either way) and retry ONCE; an unrelated ValueError
            # recurs on the retry and propagates.
            path = os.path.join(self._dir, str(int(step)))
            if os.path.isdir(path) and \
                    int(step) not in self._known_steps:
                import shutil
                warnings.warn(
                    f"removing late-appearing uncommitted checkpoint "
                    f"wreckage {path} (a previous writer's async save "
                    "finalized after the init sweep)", stacklevel=2)
                shutil.rmtree(path, ignore_errors=True)
            else:
                warnings.warn(
                    f"checkpoint save of step {step} tripped on stale "
                    "step bookkeeping (a previous writer's rotation "
                    "deleted a step this manager had cached?); "
                    "rebuilding from on-disk state and retrying once",
                    stacklevel=2)
            self._reopen()
            self._known_steps &= {int(s)
                                  for s in self._mgr.all_steps()}
            saved = self._mgr.save(
                int(step), args=self._ocp.args.StandardSave(arrays),
                force=force)
        if saved:
            self._known_steps.add(int(step))
            reg = _obs_metrics.default_registry()
            reg.counter("checkpoint_saves_total",
                        "checkpoint saves actually started").inc()
            # host-side dispatch cost only — the write itself is async;
            # DistributedCheckpointManager.save adds the commit wait
            reg.histogram("checkpoint_save_seconds",
                          "host-side save dispatch (async write "
                          "excluded)").observe(time.perf_counter() - t0)
            # the data-iterator state rides every save (tiny JSON,
            # synchronous + atomic): on ANY restore of this step the
            # sample stream rewinds in lockstep with the tensors
            self.last_saved_data_digest = \
                self._write_data_state(step, data_state) \
                if data_state is not None else None
        if saved and self._digests_on:
            # digest the SAME immutable arrays handed to orbax (jax
            # arrays cannot change under the async write), so the
            # sidecar vouches for exactly the bytes being persisted —
            # but OFF the step path: the device→host transfer + CRC
            # runs on a worker thread overlapping training, exactly
            # like orbax's own async write, and wait() joins it. A
            # process that dies before the join leaves a step without
            # a sidecar, which restore treats as 'unverified' (warned)
            # — never as verified-and-wrong.
            import threading
            # cleared BEFORE the worker runs: a worker that fails must
            # leave None (ack'd as "no digest"), never the PREVIOUS
            # step's tree masquerading as this one's
            self.last_saved_digests = None

            def digest_work(arrays=arrays, step=int(step)):
                try:
                    tree = digest_tree(arrays)
                    self._write_digests(step, tree)
                    # published only once the sidecar is ON DISK: a
                    # digest ACKed into a commit marker must always
                    # have the sidecar restore will check against
                    self.last_saved_digests = tree
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    warnings.warn(
                        f"digest sidecar for step {step} failed "
                        f"({type(e).__name__}: {e}); the step will "
                        "restore unverified", stacklevel=2)

            self._digest_thread = threading.Thread(
                target=digest_work, daemon=True, name="ckpt-digest")
            self._digest_thread.start()
        return saved

    def _join_digest_thread(self):
        t = getattr(self, "_digest_thread", None)
        if t is not None:
            t.join()
            self._digest_thread = None

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def _restore_step(self, step, model, expect_manifest=None):
        """Restore + VERIFY one step into ``model``. Digest verification
        (including the cluster-committed manifest digest, when the
        caller passes one) runs on the restored arrays BEFORE any of
        them lands in a live tensor, so corrupt or stale bytes never
        reach training state — the raised IntegrityError drives the
        caller's fallback chain (peer shards, then older steps) exactly
        like an unreadable file does. Returns the digest sidecar (or
        None, pre-integrity)."""
        live = _state_tensor_dict(model)
        meta = self._mgr.item_metadata(step)
        tree = dict(getattr(meta, "tree", None) or meta)
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(
                _build_restore_template(live, tree)))
        sidecar = self._verify_restored(step, restored, expect_manifest)
        # the data state is read AND verified before any restored array
        # lands in a live tensor: a corrupt resume offset falls back
        # exactly like corrupt tensor bytes, keeping data and model
        # state consistent at whatever step the chain settles on
        self._restored_data_state = self.read_data_state(step)
        _apply_restored(model, live, restored)
        return sidecar

    def restore_latest(self, model):
        """Restore the newest RESTORABLE checkpoint into ``model`` and
        return the NEXT step to run (0 when no checkpoint exists).

        A preempted or crashed writer can leave the newest step
        truncated or corrupt on disk even when its commit marker made
        it down; raising there would strand a job that has perfectly
        good earlier checkpoints. So restorability is verified by
        attempting the restore, scanning BACKWARD: a step that fails to
        load is warned about — loudly — and the scan falls back to the
        previous one. (A failed attempt may have partially landed
        arrays in the live tensors; the succeeding attempt overwrites
        every entry, so the model never trains on a half-restored mix.)
        """
        t0 = time.perf_counter()
        self._join_digest_thread()
        self.restored_data_state = None
        steps = sorted(self._mgr.all_steps(), reverse=True)
        for i, step in enumerate(steps):
            try:
                self._restore_step(step, model)
                self.restored_data_state = self._restored_data_state
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                warnings.warn(
                    f"checkpoint step {step} is not restorable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"previous step", stacklevel=2)
                continue
            if i:
                warnings.warn(
                    f"resumed from step {step} after skipping {i} "
                    f"corrupt/incomplete newer checkpoint(s) — up to "
                    f"{steps[0] - step} step(s) of work were lost",
                    stacklevel=2)
                # delete the skipped wreckage and rebuild the manager:
                # while a corrupt step remains the directory's newest,
                # orbax's should_save refuses every interval save of the
                # re-run window (step <= latest), so a second crash
                # there would lose the same stretch of work again
                import shutil
                for bad_step in steps[:i]:
                    shutil.rmtree(os.path.join(self._dir, str(bad_step)),
                                  ignore_errors=True)
                self._reopen()
            _obs_restore_done(t0, i)
            return step + 1
        if steps:
            warnings.warn(
                f"none of the {len(steps)} checkpoints under this "
                "directory are restorable; starting from scratch",
                stacklevel=2)
            # same stranding as the partial-fallback case: while the
            # corrupt steps remain committed, orbax refuses every save
            # of the from-scratch re-run (step <= latest) — clear them
            import shutil
            for bad_step in steps:
                shutil.rmtree(os.path.join(self._dir, str(bad_step)),
                              ignore_errors=True)
            self._reopen()
        _obs_restore_done(t0, len(steps))
        return 0

    def scrub(self, delete=False):
        """Re-verify every at-rest checkpoint against its digest
        sidecar (no model needed: the restore template comes from the
        checkpoint's own metadata). Returns ``{step: status}`` with
        status one of ``"ok"``, ``"corrupt"``, ``"unreadable"``,
        ``"unverified"`` (no sidecar — a pre-integrity save), or
        ``"in-flight"`` (a live writer's not-yet-committed save —
        skipped, never demoted).

        With ``delete=True``, corrupt/unreadable steps are DEMOTED
        (step dir + sidecar removed) so the rotation window only ever
        counts — and therefore only ever deletes — *verified* steps:
        without the demotion, a corrupt newest step would let
        ``max_to_keep`` rotate away the last restorable one. Run it
        periodically (cron / a background thread between steps) or via
        ``tools/scrub_checkpoints.py``."""
        # a step whose async orbax write is still in flight appears in
        # all_steps() but cannot restore yet — wait() (digest join +
        # wait_until_finished) so a healthy in-flight step is never
        # reported, or demoted, as corrupt
        self.wait()
        scrub_t0 = time.perf_counter()
        report = {}
        for step in self.all_steps():
            if not os.path.isdir(os.path.join(self._dir, str(step))):
                # a LIVE WRITER's in-flight async save: listed in
                # all_steps() but its final-named dir only appears at
                # commit (until then only an orbax tmp dir exists). A
                # read-only scrubber (the CLI, the background daemon)
                # must neither flag it as corrupt nor — with delete —
                # demote it out from under the writer; our own wait()
                # above only covers the in-process pipeline.
                report[step] = "in-flight"
                continue
            try:
                # the data-state sidecar is part of the step: a corrupt
                # resume offset makes the checkpoint as unrestorable as
                # corrupt tensor bytes (restore would fall back past it)
                self.read_data_state(step)
            except IntegrityError as e:
                warnings.warn(
                    f"scrub: checkpoint step {step} data-state sidecar "
                    f"FAILED verification ({e})", stacklevel=2)
                report[step] = "corrupt"
                continue
            expected = self.read_digests(step) if self._digests_on \
                else None
            if expected is None:
                report[step] = "unverified"
                continue
            try:
                meta = self._mgr.item_metadata(step)
                tree = dict(getattr(meta, "tree", None) or meta)
                template = {k: jax.ShapeDtypeStruct(tuple(m.shape),
                                                    m.dtype)
                            for k, m in tree.items()}
                restored = self._mgr.restore(
                    step,
                    args=self._ocp.args.StandardRestore(template))
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                warnings.warn(
                    f"scrub: checkpoint step {step} is unreadable "
                    f"({type(e).__name__}: {e})", stacklevel=2)
                report[step] = "unreadable"
                continue
            bad = verify_tree(restored, expected["records"])
            if bad:
                warnings.warn(
                    f"scrub: checkpoint step {step} FAILED digest "
                    f"verification ({len(bad)} entries, first "
                    f"{bad[:3]})", stacklevel=2)
                report[step] = "corrupt"
            else:
                report[step] = "ok"
        # the aot/ sidecar (exported compiled executables,
        # singa_tpu.aot) is integrity-covered bytes like any other:
        # each artifact re-verifies against its manifest digest, and
        # delete=True QUARANTINES (not rmtree's) the bad ones — the
        # store's own demotion discipline
        aot_dir = os.path.join(self._dir, "aot")
        if os.path.isdir(aot_dir):
            from .aot.export import AotStore
            aot_report = AotStore(aot_dir).scrub(delete=delete)
            for prog, status in aot_report.items():
                report[f"aot/{prog}"] = status
        if delete:
            import shutil
            demoted = [s for s, st in report.items()
                       if st in ("corrupt", "unreadable")
                       and not isinstance(s, str)]   # aot: quarantined
            for s in demoted:
                shutil.rmtree(os.path.join(self._dir, str(s)),
                              ignore_errors=True)
            if demoted:
                warnings.warn(
                    f"scrub: demoted corrupt checkpoint step(s) "
                    f"{demoted} so rotation keeps only verified steps",
                    stacklevel=2)
                self._reopen()
        reg = _obs_metrics.default_registry()
        reg.histogram("checkpoint_scrub_seconds",
                      "one at-rest verification pass"
                      ).observe(time.perf_counter() - scrub_t0)
        reg.gauge("checkpoint_scrub_bad",
                  "corrupt/unreadable steps found by the newest scrub"
                  ).set(sum(1 for s in report.values()
                            if s in ("corrupt", "unreadable")))
        return report

    def start_scrubber(self, interval=3600.0):
        """Background at-rest verification: a daemon thread re-runs
        :meth:`scrub` every ``interval`` seconds through its OWN
        read-only manager (``sweep=False`` — the live writer's orbax
        bookkeeping is never touched), warns on anything corrupt, and
        publishes the newest result as ``self.scrub_report``.
        Report-only by design: demotion while a writer is live is an
        explicit decision (``scrub(delete=True)`` between runs, or the
        ``tools/scrub_checkpoints.py`` CLI). Returns the thread;
        ``stop_scrubber()`` (also called by ``close``) ends it."""
        import threading
        if getattr(self, "_scrub_stop", None) is not None:
            if self._scrubber.is_alive():
                raise RuntimeError("scrubber already running")
            # a prior stop_scrubber's timed join expired while a long
            # pass finished in the background; the thread is dead now —
            # disarm the stale guard and start fresh
            self._scrub_stop = None
        self._scrub_stop = threading.Event()
        self.scrub_report = {}

        def loop(stop=self._scrub_stop):
            # the Event is captured: stop_scrubber may null the
            # attribute after an expired join while a long scrub pass
            # is still mid-flight
            while not stop.wait(float(interval)):
                try:
                    ro = CheckpointManager(
                        self._dir, max_to_keep=self._max_to_keep,
                        sweep=False, digests=self._digests_on)
                    try:
                        self.scrub_report = ro.scrub()
                    finally:
                        ro.close()
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:   # keep scrubbing next round
                    warnings.warn(
                        f"background scrub pass failed "
                        f"({type(e).__name__}: {e})", stacklevel=2)

        t = threading.Thread(target=loop, daemon=True,
                             name="ckpt-scrubber")
        t.start()
        self._scrubber = t
        return t

    def stop_scrubber(self):
        stop = getattr(self, "_scrub_stop", None)
        if stop is not None:
            stop.set()
            self._scrubber.join(timeout=5.0)
            if not self._scrubber.is_alive():
                # a scrub pass longer than the join grace finishes in
                # the background and exits at its next wait(); until
                # then the already-running guard stays armed
                self._scrub_stop = None

    def wait(self):
        self._join_digest_thread()
        self._mgr.wait_until_finished()

    def close(self):
        self.stop_scrubber()
        self._join_digest_thread()
        self._mgr.close()


def latest_manifest(directory):
    """Peek the newest commit marker's manifest under a
    :class:`DistributedCheckpointManager` root WITHOUT constructing a
    manager — restarted launchers read this before building anything
    (the manifest's saved world size + batch extras decide the new
    run's batch shapes, which must exist before the model compiles).
    Returns None when no committed checkpoint exists."""
    cdir = os.path.join(os.path.abspath(str(directory)), "commits")
    try:
        names = os.listdir(cdir)
    except OSError:
        return None
    steps = sorted(int(n[:-5]) for n in names
                   if n.endswith(".json") and n[:-5].isdigit())
    for s in reversed(steps):
        try:
            with open(os.path.join(cdir, f"{s}.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return None


class DistributedCheckpointManager(CheckpointManager):
    """Two-phase-commit checkpoints for a multi-host run.

    A host that dies mid-save must never leave a checkpoint that only
    *looks* committed. Layout under ``directory``::

        rank0/<step>/...      each rank's shard, via its own rotated
        rank1/<step>/...      orbax manager (single writer per dir)
        commits/<step>.json   the CLUSTER commit marker + manifest

    Phase 1: every rank writes its shard and waits the bytes down, then
    ACKs the step to the coordinator (``cluster.ack_save``). Phase 2:
    only after ALL ranks acked does the coordinator atomically publish
    ``commits/<step>.json`` (the registered commit hook) and broadcast
    the decision. A rank killed between shard-write and ACK leaves a
    step with no marker: ``restore_latest`` treats such step dirs as
    uncommitted wreckage — swept, never restored — reusing the
    backward-scan machinery of the base class.

    The marker doubles as the **elastic manifest**: it records the world
    size (and the caller's batch-accounting extras), so a run restarted
    at a *different* world size M deterministically re-shards: each new
    rank reads shard ``rank % N`` of the old world N (full-shape arrays
    re-land onto the CURRENT mesh via the live-sharding restore
    template), and the batch accounting rescales from the manifest
    (``parallel.communicator.rescale_batch``).

    This per-rank-directory scheme matches the control-plane-coordinated
    deployment (each process holds its full replica / addressable
    shards). Under ``jax.distributed`` with globally-addressed arrays,
    orbax's save is itself collective and all ranks share one directory
    — the two-phase marker protocol above still applies unchanged.
    """

    def __init__(self, directory, cluster, max_to_keep=3,
                 save_interval_steps=1, commit_timeout=60.0,
                 manifest_extra=None, digests=True):
        self.cluster = cluster
        self._root = os.path.abspath(str(directory))
        self._commit_dir = os.path.join(self._root, "commits")
        os.makedirs(self._commit_dir, exist_ok=True)
        self._commit_timeout = float(commit_timeout)
        self.manifest_extra = dict(manifest_extra or {})
        self.restored_manifest = None
        # step -> this rank's manifest digest, pending its commit marker
        # (rank 0's publish hook reads it; bounded by the save window)
        self._pending_digest = {}
        if cluster.rank == 0:
            cluster.set_commit_hook(self._publish_commit)
        super().__init__(os.path.join(self._root, f"rank{cluster.rank}"),
                         max_to_keep=max_to_keep,
                         save_interval_steps=save_interval_steps,
                         digests=digests)

    # -- commit markers ----------------------------------------------------
    def _marker(self, step):
        return os.path.join(self._commit_dir, f"{int(step)}.json")

    def committed_steps(self):
        """Steps with a published cluster commit marker."""
        try:
            names = os.listdir(self._commit_dir)
        except OSError:
            return []
        return sorted(int(n[:-5]) for n in names
                      if n.endswith(".json") and n[:-5].isdigit())

    def read_manifest(self, step):
        with open(self._marker(step)) as f:
            return json.load(f)

    def _publish_commit(self, step):
        """Coordinator-only (runs as the cluster's commit hook, after
        every rank's ACK): atomic tmp-write + rename, so a marker either
        fully exists or not at all — no torn marker can ever pass for a
        commit."""
        manifest = {"step": int(step), "world": int(self.cluster.world)}
        digest = self._pending_digest.pop(int(step), None)
        if digest is not None:
            # the manifest-level content digest: every rank ACKed this
            # exact digest (the cluster refuses to commit disagreeing
            # ones), so any rank's restore can cross-check its shard —
            # even a peer's — against the cluster-agreed content
            manifest["digest"] = digest
        data = {str(r): d for r, d in
                self.cluster.ack_data_digests(int(step)).items()
                if d is not None}
        if data:
            # each rank's data-iterator state digest rode its ACK: the
            # marker vouches for the sample-stream offset exactly like
            # it vouches for the tensors, and any restore cross-checks
            # whichever rank's data sidecar it lands on
            manifest["data_digests"] = data
        manifest.update(self.manifest_extra)
        tmp = os.path.join(self._commit_dir, f".tmp-{int(step)}.json")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._marker(step))
        # markers follow the shard rotation window: a marker whose
        # shards max_to_keep already rotated away is dead weight. Only
        # markers AT OR BELOW the step just published are candidates —
        # a stale higher-numbered marker (left by a resume that fell
        # back) must not make this fresh marker count as the oldest and
        # get pruned the moment it lands; stale-newer markers are
        # cleared by invalidate_markers_from once the cluster agrees on
        # a resume point
        committed = self.committed_steps()
        kept = [s for s in committed if s <= int(step)]
        kept = set(kept[-self._max_to_keep:])
        for old in committed:
            if old <= int(step) and old not in kept:
                try:
                    os.remove(self._marker(old))
                except OSError:
                    pass

    def invalidate_markers_from(self, step):
        """Remove commit markers at/after ``step`` — coordinator-only,
        and ONLY once the cluster has AGREED to resume at ``step`` (the
        trainer's resume barrier): agreement proves no rank restored
        past it, so those markers vouch for a timeline about to be
        re-run, where a rank killed pre-ACK would otherwise find a
        stale marker vouching for its never-acked shard. This is the
        cluster-consistent counterpart of what a lone rank must never
        do (its local restore failures say nothing about its peers'
        shards). Returns the number of markers removed."""
        if self.cluster.rank != 0:
            return 0
        removed = 0
        for s in self.committed_steps():
            if s >= int(step):
                try:
                    os.remove(self._marker(s))
                    removed += 1
                except OSError:
                    pass
        if removed:
            warnings.warn(
                f"invalidated {removed} stale commit marker(s) at/after "
                f"the agreed resume step {step} (their timeline is "
                "about to be re-run)", stacklevel=2)
        return removed

    # -- two-phase save ----------------------------------------------------
    def save(self, step, model, force=False, commit_timeout=None,
             data_state=None):
        """Write this rank's shard, ACK, and wait for the cluster commit.
        Returns True only when the step COMMITTED (marker published).
        The underlying write is awaited before the ACK — an ACK is a
        durability promise, not an intention. ``commit_timeout``
        overrides the manager default for THIS save (the preemption
        path uses a short one: a forced off-schedule save can only
        quorum when every rank was preempted at the same boundary, and
        a doomed wait must not eat the kill grace)."""
        saved = super().save(step, model, force=force,
                             data_state=data_state)
        if not saved:
            return False
        self.wait()     # bytes down AND digests computed BEFORE the ack
        digest = manifest_digest(self.last_saved_digests) \
            if self.last_saved_digests else None
        if digest is not None:
            self._pending_digest[int(step)] = digest
            # bound the bookkeeping to the rotation window
            for old in sorted(self._pending_digest)[:-self._max_to_keep]:
                self._pending_digest.pop(old, None)
        # the ACK carries this rank's manifest digest — the coordinator
        # commits only when EVERY rank acked the same content, so a
        # silently-diverged replica can never be vouched for by a
        # marker — and its data-state digest, recorded in the marker so
        # the committed checkpoint vouches for the sample-stream offset
        self.cluster.ack_save(  # fault: kill_before_ack
            step, digest=digest,
            data_digest=self.last_saved_data_digest)
        timeout = self._commit_timeout if commit_timeout is None \
            else float(commit_timeout)
        ok = self.cluster.wait_commit(step, timeout=timeout)
        if not ok:
            _obs_metrics.default_registry().counter(
                "checkpoint_commit_failures_total",
                "two-phase saves that never gained a commit marker"
            ).inc()
            warnings.warn(
                f"checkpoint step {step}: commit did not complete within "
                f"{timeout:.0f}s (a rank died before its ACK"
                "?); the step stays uncommitted and restore will refuse "
                "it", stacklevel=2)
        return ok

    # -- elastic restore ---------------------------------------------------
    def _source_ranks(self, manifest):
        """Deterministic shard-source order for this rank: our own (or
        wrapped, when the world grew) shard first, then every other
        rank of the SAVED world. In this per-rank-directory deployment
        each rank's shard is a full replica, so a rank whose own shard
        is corrupt restores a peer's copy of the SAME step instead of
        silently diverging to an older one."""
        saved_world = max(1, int(manifest.get("world",
                                              self.cluster.world)))
        primary = self.cluster.rank % saved_world
        return [primary] + [r for r in range(saved_world)
                            if r != primary]

    def _restore_foreign(self, src_rank, step, model,
                         expect_manifest=None):
        """Restore from another rank's shard directory (read-only: no
        wreckage sweep — that dir may belong to a live writer). The
        peer's digest sidecar is verified exactly like our own."""
        src = CheckpointManager(
            os.path.join(self._root, f"rank{src_rank}"),
            max_to_keep=self._max_to_keep,
            save_interval_steps=self._save_interval_steps, sweep=False,
            digests=self._digests_on)
        try:
            out = src._restore_step(step, model, expect_manifest)
            # the data state is GLOBAL-stream state (rank-agnostic by
            # construction — see data.NumpyBatchIter), so the peer's
            # offset resumes this rank's derived shard exactly
            self._restored_data_state = src._restored_data_state
            return out
        finally:
            src.close()

    def _check_restored_data(self, step, src_rank, manifest):
        """Cross-check the just-restored data state against the digest
        rank ``src_rank`` ACKed into the commit marker. Raises
        :class:`~singa_tpu.integrity.IntegrityError` (driving the
        caller's next-source fallback) when the marker vouches for a
        data state this shard cannot produce."""
        want = (manifest.get("data_digests") or {}).get(str(src_rank))
        if not want:
            return        # pre-data-state marker, or a stateless run
        state = self._restored_data_state
        if state is None:
            raise IntegrityError(
                f"checkpoint step {step}: rank {src_rank} ACKed a "
                f"data state into the commit marker but its sidecar "
                "is missing — the resume offset cannot be trusted")
        got = data_state_digest(state)
        if got != want:
            raise IntegrityError(
                f"checkpoint step {step}: data-state digest {got} "
                f"does not match the cluster-committed {want} for "
                f"rank {src_rank} — stale or corrupt resume offset")

    def restore_latest(self, model):
        """Restore the newest CLUSTER-COMMITTED checkpoint and return
        the next step to run (0 when none exists). Local step dirs
        without a commit marker are wreckage from a writer that died in
        the commit hole — swept, exactly like the base class sweeps
        orbax-uncommitted dirs. On success ``self.restored_manifest``
        carries the marker's manifest (saved world size + batch extras)
        for the elastic-resume accounting."""
        import shutil
        t0 = time.perf_counter()
        self._join_digest_thread()
        self.restored_manifest = None
        self.restored_data_state = None
        committed = self.committed_steps()
        committed_set = set(committed)
        local = set(self._mgr.all_steps())
        wreck = sorted(s for s in local if s not in committed_set)
        if wreck:
            warnings.warn(
                f"sweeping {len(wreck)} locally-saved but cluster-"
                f"uncommitted checkpoint step(s) {wreck} (a rank died "
                "between shard-write and ACK)", stacklevel=2)
            for s in wreck:
                shutil.rmtree(os.path.join(self._dir, str(s)),
                              ignore_errors=True)
            self._reopen()
            local -= set(wreck)
        for i, step in enumerate(reversed(committed)):
            restored = False
            try:
                manifest = self.read_manifest(step)
            except (OSError, ValueError):
                continue                       # torn marker: not ours
            # the commit marker carries the CLUSTER-AGREED manifest
            # digest: _verify_restored checks each candidate shard
            # against it BEFORE any array lands in a live tensor, so a
            # stale/foreign shard wearing the right step number is
            # rejected without ever touching training state
            want = manifest.get("digest")
            for src in self._source_ranks(manifest):
                try:
                    if src == self.cluster.rank and step in local:
                        self._restore_step(step, model, want)
                    else:
                        self._restore_foreign(src, step, model, want)
                    self._check_restored_data(step, src, manifest)
                    restored = True
                    break
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    warnings.warn(
                        f"committed checkpoint step {step}: rank "
                        f"{src}'s shard is not restorable on rank "
                        f"{self.cluster.rank} ({type(e).__name__}: {e})"
                        "; trying the next source", stacklevel=2)
            if not restored:
                warnings.warn(
                    f"committed checkpoint step {step} is not "
                    f"restorable from any rank's shard; falling back "
                    "to the previous step", stacklevel=2)
                continue
            if i:
                # clear OUR newer (locally corrupt) shards so orbax's
                # should_save does not refuse the re-run window; the
                # markers stay — other ranks' shards may be intact
                newer = [s for s in local if s > step]
                for s in newer:
                    shutil.rmtree(os.path.join(self._dir, str(s)),
                                  ignore_errors=True)
                if newer:
                    self._reopen()
            self.restored_manifest = manifest
            self.restored_data_state = self._restored_data_state
            _obs_restore_done(t0, i)
            if int(manifest.get("world", self.cluster.world)) != \
                    self.cluster.world:
                warnings.warn(
                    f"elastic resume: checkpoint step {step} was saved "
                    f"at world size {manifest.get('world')}, restoring "
                    f"into world size {self.cluster.world} (state "
                    "re-sharded onto the current mesh)", stacklevel=2)
            return step + 1
        if committed:
            warnings.warn(
                f"none of the {len(committed)} committed checkpoints "
                "are restorable on this rank; starting from scratch",
                stacklevel=2)
            for s in local:
                shutil.rmtree(os.path.join(self._dir, str(s)),
                              ignore_errors=True)
            # the shared commit markers are deliberately LEFT in place:
            # this branch only proves the steps unreadable on THIS rank
            # (possibly a transient IO error), and deleting markers
            # would destroy checkpoints peers can still restore. Ranks
            # that disagree about the resume step fail loudly at the
            # trainer's resume barrier; markers whose shards rotate
            # away are pruned by _publish_commit.
            self._reopen()
        _obs_restore_done(t0, len(committed))
        return 0
