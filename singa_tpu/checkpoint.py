"""Async sharded checkpointing on Orbax.

The reference's persistence routes (Snapshot .bin/.desc, the
save_states zip — reference model.py:244-330, src/io/snapshot.cc:33-80)
both serialize through ONE host copy of every array. For models whose
state is tp/ep/pp-sharded across a mesh (or across hosts), this module
adds the TPU-idiomatic third route: state is read from the LIVE tensors
(no gather, no full-model host copy — each process contributes only its
addressable shards) and the write happens ASYNCHRONOUSLY, so training
steps continue while bytes land on disk.

    ck = AsyncModelCheckpointer()
    ck.save(path, model)          # returns immediately; shards stream out
    ...training continues...
    ck.wait()                     # barrier before e.g. rotating dirs
    ck.restore(path, model)       # shards land back WITH their shardings

Restore is driven by the CHECKPOINT's metadata (not the live state), so
a freshly constructed process — whose lazily-created optimizer aux does
not exist yet — restores momentum/moments too and replays the exact
trajectory. Every entry restores onto the CURRENT topology: live
counterparts keep their sharding, fresh optimizer aux adopts its owning
param's live sharding (never the layout persisted by a possibly
different mesh).
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np
import jax


def _state_tensor_dict(model):
    """name -> LIVE Tensor for every model state + optimizer aux (no
    gather, no host copy — unlike get_states()/save_states)."""
    out = {}
    for k, t in model.get_states().items():
        out[f"model/{k}"] = t
    opt = getattr(model, "optimizer", None)
    if opt is not None and hasattr(opt, "state_tensor_dict"):
        for k, t in opt.state_tensor_dict().items():
            out[f"optimizer/{k}"] = t
    return out


def _aux_param_base(name):
    """'<param>:<kind>' (optionally 'residual/<param>') -> param name."""
    return name.split("/", 1)[-1].rsplit(":", 1)[0]


def _build_restore_template(live, meta_tree):
    """ShapeDtypeStruct tree for StandardRestore, keyed by the
    CHECKPOINT's metadata. Sharding targets come from the CURRENT
    process: a live counterpart's sharding when shapes agree, else —
    for fresh optimizer aux — the owning param's live sharding (the
    layout persisted in the checkpoint may belong to a different
    topology, which orbax itself flags as unsafe to reuse)."""
    template = {}
    for k, m in meta_tree.items():
        shape = tuple(m.shape)
        sharding = None
        lt = live.get(k)
        if lt is not None and tuple(np.shape(lt.data)) == shape:
            sharding = getattr(lt.data, "sharding", None)
        elif lt is None and k.startswith("optimizer/"):
            base = live.get(
                "model/" + _aux_param_base(k[len("optimizer/"):]))
            if base is not None and \
                    tuple(np.shape(base.data)) == shape:
                sharding = getattr(base.data, "sharding", None)
        template[k] = jax.ShapeDtypeStruct(shape, m.dtype,
                                           sharding=sharding)
    return template


def _apply_restored(model, live, restored):
    """Land restored arrays in the live tensors; create lazily-built
    optimizer aux that a fresh process has not materialised yet
    (announcing the owning param's spec so it keeps sharding like its
    param); skip — loudly — anything without a live home or with a
    mismatched shape (e.g. resuming into a re-architected model)."""
    opt = getattr(model, "optimizer", None)
    for k, arr in restored.items():
        lt = live.get(k)
        if lt is not None:
            if tuple(np.shape(lt.data)) != tuple(np.shape(arr)):
                warnings.warn(
                    f"checkpoint entry {k!r} has shape "
                    f"{tuple(np.shape(arr))} but the live tensor is "
                    f"{tuple(np.shape(lt.data))}; skipped (did the "
                    "architecture change since the save?)", stacklevel=3)
                continue
            lt.data = arr
        elif k.startswith("optimizer/") and opt is not None \
                and hasattr(opt, "restore_state_tensor"):
            nm = k[len("optimizer/"):]
            pt = live.get("model/" + _aux_param_base(nm))
            opt.restore_state_tensor(nm, arr, getattr(pt, "spec", None))
        else:
            warnings.warn(f"checkpoint entry {k!r} has no live "
                          "counterpart in this model; skipped",
                          stacklevel=3)
    # compiled steps close over state identity; force a rebind
    model._invalidate_compiled()


class AsyncModelCheckpointer:
    """Orbax ``AsyncCheckpointer`` over a Model's state pytree."""

    def __init__(self):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._ckptr = ocp.AsyncCheckpointer(
            ocp.StandardCheckpointHandler())

    def save(self, path, model, force=True):
        """Start an async save of params + optimizer aux; returns
        immediately (the previous pending save is awaited first, as
        orbax allows a single outstanding write)."""
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        self._ckptr.save(os.path.abspath(str(path)),
                         args=self._ocp.args.StandardSave(arrays),
                         force=force)

    def wait(self):
        """Block until the outstanding async save has fully committed."""
        self._ckptr.wait_until_finished()

    def restore(self, path, model):
        """Load shards back into the model's live tensors (see the
        module docstring for the template/topology rules)."""
        path = os.path.abspath(str(path))
        live = _state_tensor_dict(model)
        # orbax API drift: metadata() returns a plain dict tree on
        # newer versions, a CheckpointMetadata wrapper on older ones
        raw = self._ckptr.metadata(path)
        tree = getattr(getattr(raw, "item_metadata", None), "tree", None)
        meta = dict(tree if tree is not None else raw)
        restored = self._ckptr.restore(
            path, args=self._ocp.args.StandardRestore(
                _build_restore_template(live, meta)))
        _apply_restored(model, live, restored)

    def close(self):
        self._ckptr.close()


class CheckpointManager:
    """Rotated, step-numbered checkpoints over the async sharded route
    (orbax ``CheckpointManager``): save every ``save_interval_steps``,
    keep the newest ``max_to_keep``, resume from the latest — the
    checkpoint-restart loop the reference lacks entirely (its NCCL/MPI
    failures just exit, include/singa/io/communicator.h:40-67).

        mgr = CheckpointManager(dir, max_to_keep=3, save_interval_steps=50)
        start = mgr.restore_latest(model)        # 0 on a fresh run
        for step in range(start, total):
            model(tx, ty)
            mgr.save(step, model)                # no-op off-interval
        mgr.wait(); mgr.close()
    """

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1,
                 sweep=True):
        """``sweep=False`` skips the uncommitted-wreckage sweep at init —
        for READ-ONLY managers opened on a directory another rank owns
        (the elastic cross-rank restore path must never delete a live
        writer's in-flight step)."""
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self._dir = os.path.abspath(str(directory))
        self._max_to_keep = max_to_keep
        self._save_interval_steps = save_interval_steps
        self._mgr = self._make_mgr()
        if sweep:
            self._sweep_uncommitted()

    def _make_mgr(self):
        ocp = self._ocp
        return ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep,
                save_interval_steps=self._save_interval_steps,
                enable_async_checkpointing=True),
            # a FRESH manager (resume path) must know the handler type
            # before any save, or item metadata cannot be read
            item_handlers=ocp.StandardCheckpointHandler())

    def _sweep_uncommitted(self):
        """Remove step directories a dead writer left without a commit
        marker. A process killed mid-async-save (the normal way a
        preempted job dies) leaves the step's directory on disk but
        absent from ``all_steps()``; the restarted job resumes from an
        earlier step, re-trains, and its ``save`` of that step number
        would then refuse — 'destination already exists' — stranding
        the run. Single-writer-per-directory is assumed (as it is for
        rotation)."""
        import shutil
        committed = {str(s) for s in self._mgr.all_steps()}
        try:
            entries = os.listdir(self._dir)
        except OSError:
            return
        for name in entries:
            # only orbax's own artifacts: an exact step-number dir with
            # no commit marker, or an orbax tmp dir. Anything else in
            # here (a user's "3.backup", notes, …) is not ours to delete
            wreck = (name.isdigit() and name not in committed) or \
                ".orbax-checkpoint-tmp" in name
            if wreck:
                path = os.path.join(self._dir, name)
                if os.path.isdir(path):
                    warnings.warn(
                        f"removing uncommitted checkpoint wreckage "
                        f"{path} (a previous writer died mid-save)",
                        stacklevel=3)
                    shutil.rmtree(path, ignore_errors=True)

    def save(self, step, model, force=False):
        arrays = {k: t.data for k, t in _state_tensor_dict(model).items()}
        return self._mgr.save(int(step),
                              args=self._ocp.args.StandardSave(arrays),
                              force=force)

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def _restore_step(self, step, model):
        live = _state_tensor_dict(model)
        meta = self._mgr.item_metadata(step)
        tree = dict(getattr(meta, "tree", None) or meta)
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(
                _build_restore_template(live, tree)))
        _apply_restored(model, live, restored)

    def restore_latest(self, model):
        """Restore the newest RESTORABLE checkpoint into ``model`` and
        return the NEXT step to run (0 when no checkpoint exists).

        A preempted or crashed writer can leave the newest step
        truncated or corrupt on disk even when its commit marker made
        it down; raising there would strand a job that has perfectly
        good earlier checkpoints. So restorability is verified by
        attempting the restore, scanning BACKWARD: a step that fails to
        load is warned about — loudly — and the scan falls back to the
        previous one. (A failed attempt may have partially landed
        arrays in the live tensors; the succeeding attempt overwrites
        every entry, so the model never trains on a half-restored mix.)
        """
        steps = sorted(self._mgr.all_steps(), reverse=True)
        for i, step in enumerate(steps):
            try:
                self._restore_step(step, model)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                warnings.warn(
                    f"checkpoint step {step} is not restorable "
                    f"({type(e).__name__}: {e}); falling back to the "
                    f"previous step", stacklevel=2)
                continue
            if i:
                warnings.warn(
                    f"resumed from step {step} after skipping {i} "
                    f"corrupt/incomplete newer checkpoint(s) — up to "
                    f"{steps[0] - step} step(s) of work were lost",
                    stacklevel=2)
                # delete the skipped wreckage and rebuild the manager:
                # while a corrupt step remains the directory's newest,
                # orbax's should_save refuses every interval save of the
                # re-run window (step <= latest), so a second crash
                # there would lose the same stretch of work again
                import shutil
                for bad_step in steps[:i]:
                    shutil.rmtree(os.path.join(self._dir, str(bad_step)),
                                  ignore_errors=True)
                self._mgr.close()
                self._mgr = self._make_mgr()
            return step + 1
        if steps:
            warnings.warn(
                f"none of the {len(steps)} checkpoints under this "
                "directory are restorable; starting from scratch",
                stacklevel=2)
            # same stranding as the partial-fallback case: while the
            # corrupt steps remain committed, orbax refuses every save
            # of the from-scratch re-run (step <= latest) — clear them
            import shutil
            for bad_step in steps:
                shutil.rmtree(os.path.join(self._dir, str(bad_step)),
                              ignore_errors=True)
            self._mgr.close()
            self._mgr = self._make_mgr()
        return 0

    def wait(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def latest_manifest(directory):
    """Peek the newest commit marker's manifest under a
    :class:`DistributedCheckpointManager` root WITHOUT constructing a
    manager — restarted launchers read this before building anything
    (the manifest's saved world size + batch extras decide the new
    run's batch shapes, which must exist before the model compiles).
    Returns None when no committed checkpoint exists."""
    cdir = os.path.join(os.path.abspath(str(directory)), "commits")
    try:
        names = os.listdir(cdir)
    except OSError:
        return None
    steps = sorted(int(n[:-5]) for n in names
                   if n.endswith(".json") and n[:-5].isdigit())
    for s in reversed(steps):
        try:
            with open(os.path.join(cdir, f"{s}.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            continue
    return None


class DistributedCheckpointManager(CheckpointManager):
    """Two-phase-commit checkpoints for a multi-host run.

    A host that dies mid-save must never leave a checkpoint that only
    *looks* committed. Layout under ``directory``::

        rank0/<step>/...      each rank's shard, via its own rotated
        rank1/<step>/...      orbax manager (single writer per dir)
        commits/<step>.json   the CLUSTER commit marker + manifest

    Phase 1: every rank writes its shard and waits the bytes down, then
    ACKs the step to the coordinator (``cluster.ack_save``). Phase 2:
    only after ALL ranks acked does the coordinator atomically publish
    ``commits/<step>.json`` (the registered commit hook) and broadcast
    the decision. A rank killed between shard-write and ACK leaves a
    step with no marker: ``restore_latest`` treats such step dirs as
    uncommitted wreckage — swept, never restored — reusing the
    backward-scan machinery of the base class.

    The marker doubles as the **elastic manifest**: it records the world
    size (and the caller's batch-accounting extras), so a run restarted
    at a *different* world size M deterministically re-shards: each new
    rank reads shard ``rank % N`` of the old world N (full-shape arrays
    re-land onto the CURRENT mesh via the live-sharding restore
    template), and the batch accounting rescales from the manifest
    (``parallel.communicator.rescale_batch``).

    This per-rank-directory scheme matches the control-plane-coordinated
    deployment (each process holds its full replica / addressable
    shards). Under ``jax.distributed`` with globally-addressed arrays,
    orbax's save is itself collective and all ranks share one directory
    — the two-phase marker protocol above still applies unchanged.
    """

    def __init__(self, directory, cluster, max_to_keep=3,
                 save_interval_steps=1, commit_timeout=60.0,
                 manifest_extra=None):
        self.cluster = cluster
        self._root = os.path.abspath(str(directory))
        self._commit_dir = os.path.join(self._root, "commits")
        os.makedirs(self._commit_dir, exist_ok=True)
        self._commit_timeout = float(commit_timeout)
        self.manifest_extra = dict(manifest_extra or {})
        self.restored_manifest = None
        if cluster.rank == 0:
            cluster.set_commit_hook(self._publish_commit)
        super().__init__(os.path.join(self._root, f"rank{cluster.rank}"),
                         max_to_keep=max_to_keep,
                         save_interval_steps=save_interval_steps)

    # -- commit markers ----------------------------------------------------
    def _marker(self, step):
        return os.path.join(self._commit_dir, f"{int(step)}.json")

    def committed_steps(self):
        """Steps with a published cluster commit marker."""
        try:
            names = os.listdir(self._commit_dir)
        except OSError:
            return []
        return sorted(int(n[:-5]) for n in names
                      if n.endswith(".json") and n[:-5].isdigit())

    def read_manifest(self, step):
        with open(self._marker(step)) as f:
            return json.load(f)

    def _publish_commit(self, step):
        """Coordinator-only (runs as the cluster's commit hook, after
        every rank's ACK): atomic tmp-write + rename, so a marker either
        fully exists or not at all — no torn marker can ever pass for a
        commit."""
        manifest = {"step": int(step), "world": int(self.cluster.world)}
        manifest.update(self.manifest_extra)
        tmp = os.path.join(self._commit_dir, f".tmp-{int(step)}.json")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._marker(step))
        # markers follow the shard rotation window: a marker whose
        # shards max_to_keep already rotated away is dead weight. Only
        # markers AT OR BELOW the step just published are candidates —
        # a stale higher-numbered marker (left by a resume that fell
        # back) must not make this fresh marker count as the oldest and
        # get pruned the moment it lands; stale-newer markers are
        # cleared by invalidate_markers_from once the cluster agrees on
        # a resume point
        committed = self.committed_steps()
        kept = [s for s in committed if s <= int(step)]
        kept = set(kept[-self._max_to_keep:])
        for old in committed:
            if old <= int(step) and old not in kept:
                try:
                    os.remove(self._marker(old))
                except OSError:
                    pass

    def invalidate_markers_from(self, step):
        """Remove commit markers at/after ``step`` — coordinator-only,
        and ONLY once the cluster has AGREED to resume at ``step`` (the
        trainer's resume barrier): agreement proves no rank restored
        past it, so those markers vouch for a timeline about to be
        re-run, where a rank killed pre-ACK would otherwise find a
        stale marker vouching for its never-acked shard. This is the
        cluster-consistent counterpart of what a lone rank must never
        do (its local restore failures say nothing about its peers'
        shards). Returns the number of markers removed."""
        if self.cluster.rank != 0:
            return 0
        removed = 0
        for s in self.committed_steps():
            if s >= int(step):
                try:
                    os.remove(self._marker(s))
                    removed += 1
                except OSError:
                    pass
        if removed:
            warnings.warn(
                f"invalidated {removed} stale commit marker(s) at/after "
                f"the agreed resume step {step} (their timeline is "
                "about to be re-run)", stacklevel=2)
        return removed

    # -- two-phase save ----------------------------------------------------
    def save(self, step, model, force=False, commit_timeout=None):
        """Write this rank's shard, ACK, and wait for the cluster commit.
        Returns True only when the step COMMITTED (marker published).
        The underlying write is awaited before the ACK — an ACK is a
        durability promise, not an intention. ``commit_timeout``
        overrides the manager default for THIS save (the preemption
        path uses a short one: a forced off-schedule save can only
        quorum when every rank was preempted at the same boundary, and
        a doomed wait must not eat the kill grace)."""
        saved = super().save(step, model, force=force)
        if not saved:
            return False
        self.wait()                       # bytes down BEFORE the ack
        self.cluster.ack_save(step)       # fault hook: kill_before_ack
        timeout = self._commit_timeout if commit_timeout is None \
            else float(commit_timeout)
        ok = self.cluster.wait_commit(step, timeout=timeout)
        if not ok:
            warnings.warn(
                f"checkpoint step {step}: commit did not complete within "
                f"{timeout:.0f}s (a rank died before its ACK"
                "?); the step stays uncommitted and restore will refuse "
                "it", stacklevel=2)
        return ok

    # -- elastic restore ---------------------------------------------------
    def _source_ranks(self, manifest):
        """Deterministic shard-source order for this rank: our own (or
        wrapped, when the world grew) shard first, then every other
        rank of the SAVED world. In this per-rank-directory deployment
        each rank's shard is a full replica, so a rank whose own shard
        is corrupt restores a peer's copy of the SAME step instead of
        silently diverging to an older one."""
        saved_world = max(1, int(manifest.get("world",
                                              self.cluster.world)))
        primary = self.cluster.rank % saved_world
        return [primary] + [r for r in range(saved_world)
                            if r != primary]

    def _restore_foreign(self, src_rank, step, model):
        """Restore from another rank's shard directory (read-only: no
        wreckage sweep — that dir may belong to a live writer)."""
        src = CheckpointManager(
            os.path.join(self._root, f"rank{src_rank}"),
            max_to_keep=self._max_to_keep,
            save_interval_steps=self._save_interval_steps, sweep=False)
        try:
            src._restore_step(step, model)
        finally:
            src.close()

    def restore_latest(self, model):
        """Restore the newest CLUSTER-COMMITTED checkpoint and return
        the next step to run (0 when none exists). Local step dirs
        without a commit marker are wreckage from a writer that died in
        the commit hole — swept, exactly like the base class sweeps
        orbax-uncommitted dirs. On success ``self.restored_manifest``
        carries the marker's manifest (saved world size + batch extras)
        for the elastic-resume accounting."""
        import shutil
        self.restored_manifest = None
        committed = self.committed_steps()
        committed_set = set(committed)
        local = set(self._mgr.all_steps())
        wreck = sorted(s for s in local if s not in committed_set)
        if wreck:
            warnings.warn(
                f"sweeping {len(wreck)} locally-saved but cluster-"
                f"uncommitted checkpoint step(s) {wreck} (a rank died "
                "between shard-write and ACK)", stacklevel=2)
            for s in wreck:
                shutil.rmtree(os.path.join(self._dir, str(s)),
                              ignore_errors=True)
            self._mgr.close()
            self._mgr = self._make_mgr()
            local -= set(wreck)
        for i, step in enumerate(reversed(committed)):
            restored = False
            try:
                manifest = self.read_manifest(step)
            except (OSError, ValueError):
                continue                       # torn marker: not ours
            for src in self._source_ranks(manifest):
                try:
                    if src == self.cluster.rank and step in local:
                        self._restore_step(step, model)
                    else:
                        self._restore_foreign(src, step, model)
                    restored = True
                    break
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:
                    warnings.warn(
                        f"committed checkpoint step {step}: rank "
                        f"{src}'s shard is not restorable on rank "
                        f"{self.cluster.rank} ({type(e).__name__}: {e})"
                        "; trying the next source", stacklevel=2)
            if not restored:
                warnings.warn(
                    f"committed checkpoint step {step} is not "
                    f"restorable from any rank's shard; falling back "
                    "to the previous step", stacklevel=2)
                continue
            if i:
                # clear OUR newer (locally corrupt) shards so orbax's
                # should_save does not refuse the re-run window; the
                # markers stay — other ranks' shards may be intact
                newer = [s for s in local if s > step]
                for s in newer:
                    shutil.rmtree(os.path.join(self._dir, str(s)),
                                  ignore_errors=True)
                if newer:
                    self._mgr.close()
                    self._mgr = self._make_mgr()
            self.restored_manifest = manifest
            if int(manifest.get("world", self.cluster.world)) != \
                    self.cluster.world:
                warnings.warn(
                    f"elastic resume: checkpoint step {step} was saved "
                    f"at world size {manifest.get('world')}, restoring "
                    f"into world size {self.cluster.world} (state "
                    "re-sharded onto the current mesh)", stacklevel=2)
            return step + 1
        if committed:
            warnings.warn(
                f"none of the {len(committed)} committed checkpoints "
                "are restorable on this rank; starting from scratch",
                stacklevel=2)
            for s in local:
                shutil.rmtree(os.path.join(self._dir, str(s)),
                              ignore_errors=True)
            # the shared commit markers are deliberately LEFT in place:
            # this branch only proves the steps unreadable on THIS rank
            # (possibly a transient IO error), and deleting markers
            # would destroy checkpoints peers can still restore. Ranks
            # that disagree about the resume step fail loudly at the
            # trainer's resume barrier; markers whose shards rotate
            # away are pruned by _publish_commit.
            self._mgr.close()
            self._mgr = self._make_mgr()
        return 0
