"""Cluster health: heartbeats, failing-fast barriers, two-phase commit.

The reference's multi-process story is fail-fast NCCL error prints
(include/singa/io/communicator.h:40-67): a dead or straggling host
silently hangs every collective, and a host that dies mid-save leaves a
checkpoint that only *looks* committed. This module is the control-plane
layer a pod-scale job needs on top of :mod:`singa_tpu.network`
(``NetworkThread``/``EndPoint`` — tensor traffic stays on XLA
collectives over ICI/DCN, never these sockets):

- **Membership**: every worker heartbeats the coordinator (rank 0); the
  coordinator tracks last-seen ages, flags *stragglers* (heartbeat gap
  over ``straggler_after``) and declares a rank *dead* after
  ``dead_after`` of silence. The digest rides back on every heartbeat
  ack, so workers learn of lost peers (and of a dead coordinator, by
  the ack going silent) without extra traffic. :meth:`ClusterBase.check`
  raises :class:`MembershipError` — a *recoverable* loss: the
  supervisor contract is exit :data:`~singa_tpu.resilience.runtime.
  EXIT_PREEMPTED` (75) and a restart at the smaller world size.
- **Barriers**: :meth:`ClusterBase.barrier` never hangs — at the
  timeout (or as soon as a participant is declared dead) it raises
  :class:`BarrierTimeout` *naming the missing ranks*.
- **Two-phase commit** (for distributed checkpoints,
  ``singa_tpu/checkpoint.py``): every rank writes its shard then
  :meth:`ClusterBase.ack_save`; the coordinator publishes the commit
  marker (the registered ``commit_hook``) only once ALL ranks acked,
  then broadcasts the decision; :meth:`ClusterBase.wait_commit` returns
  whether the step committed. A rank that dies between shard-write and
  ACK therefore leaves a step with NO marker — wreckage that
  ``restore_latest`` refuses.

Usage (one process per rank)::

    cluster = make_cluster(rank, world, "host0:19123")
    cluster.barrier("start", timeout=30)     # rendezvous, names absentees
    ...
    cluster.ack_save(step); cluster.wait_commit(step, timeout=30)
    cluster.check()                          # raises on membership loss
    cluster.close()

``world == 1`` returns a :class:`SoloCluster` that needs no sockets, so
elastic restarts down to a single host run the identical code path.
"""

from __future__ import annotations

import json
import os
import threading
import time
import warnings
from dataclasses import dataclass

from .. import network as net
from ..integrity import (MAX_MESSAGE_BYTES, IntegrityError, open_frame,
                         seal_frame)
from ..observability import metrics as _metrics
from .faults import NULL_PLAN, DropPeerSignal as _DropPeerSignal

# control-plane protocol version, negotiated in the hello handshake: a
# peer speaking a different framing/message dialect is REJECTED at join
# (named, loudly) instead of being mis-parsed for the whole run. Bump on
# any incompatible change to the message set or frame format.
PROTO_VERSION = 1


class ClusterError(RuntimeError):
    """Base class for cluster-health failures."""


class MembershipError(ClusterError):
    """A peer (or the coordinator) was lost — RECOVERABLE by a restart
    at the smaller world size: the supervisor contract is exit code 75
    (``resilience.EXIT_PREEMPTED``)."""

    def __init__(self, dead, world):
        self.dead = sorted(int(r) for r in dead)
        self.world = int(world)
        super().__init__(
            f"cluster membership lost: rank(s) {self.dead} of world "
            f"{self.world} are dead; restart at world "
            f"{self.world - len(self.dead)} to continue")


class BarrierTimeout(ClusterError):
    """A barrier did not complete — names who is missing instead of
    hanging the collective."""

    def __init__(self, name, missing, timeout):
        self.name = name
        self.missing = sorted(int(r) for r in missing)
        super().__init__(
            f"barrier {name!r} timed out after {timeout:.1f}s waiting "
            f"for rank(s) {self.missing}")


@dataclass
class ClusterConfig:
    """Timing knobs. Defaults suit tests/local chaos runs; production
    pods want heartbeat_interval ~1s and dead_after ~30s."""

    heartbeat_interval: float = 0.25   # worker -> coordinator beat period
    straggler_after: float = 0.75      # silence before a rank is "slow"
    dead_after: float = 2.5            # silence before a rank is dead
    connect_timeout: float = 15.0      # worker's coordinator-dial budget
    recv_slice: float = 0.25           # receiver-loop poll granularity
    stale_beats: float = 3.0           # heartbeats of silence before a
    #   rank's last-carried metric summary is STALE (dead data): the
    #   aggregate fleet view excludes it and surfaces the age instead
    #   of reporting frozen gauges as current

    @property
    def stale_after(self) -> float:
        """Seconds of silence before a rank's summary is stale
        (``stale_beats`` × ``heartbeat_interval``)."""
        return self.stale_beats * self.heartbeat_interval


def _addr(coordinator: str):
    host, port = coordinator.rsplit(":", 1)
    return host, int(port)


def _msg(kind: str, **payload) -> net.Message:
    """A SEALED control-plane message: the JSON payload rides behind the
    integrity frame header (magic + protocol version + CRCs), so a
    corrupted frame is detected before any parsing."""
    meta = kind.encode()
    raw = json.dumps(payload).encode()
    return net.Message(meta, seal_frame(meta, raw))


def _payload(msg: net.Message) -> dict:
    """Verify + parse a sealed message; raises
    :class:`~singa_tpu.integrity.IntegrityError` on a corrupt frame
    (receive loops drop-and-count those — see ``_open``)."""
    return json.loads(open_frame(msg.meta, msg.payload).decode() or "{}")


# decided commit steps kept in memory per rank — coordinator and worker
# MUST share this window: a worker pruning earlier than the coordinator
# could drop the Event for a step whose decision is still coming
COMMIT_WINDOW = 16


def _prune_window(decided, *others):
    """Bound per-step/per-round bookkeeping to the newest COMMIT_WINDOW
    decided keys — older entries can never be waited on again. One
    helper for the commit AND fingerprint slots on both coordinator and
    worker, so the four windows can never drift apart. ``others`` may
    be dicts or sets keyed like ``decided``."""
    for old in sorted(decided)[:-COMMIT_WINDOW]:
        decided.pop(old, None)
        for m in others:
            if isinstance(m, set):
                m.discard(old)
            else:
                m.pop(old, None)


class ClusterBase:
    """API shared by coordinator, worker, and the solo degenerate."""

    rank: int = 0
    world: int = 1
    _wire_seq = 0          # sent-frame counter (fault-injection keying)
    _wire_errors = 0       # corrupt frames dropped by this member
    _WIRE_WARN_LIMIT = 5   # warn the first few, count the rest silently
    # zero-arg callable returning this member's heartbeat metric
    # summary; None uses the process metrics registry
    # (observability.metrics.heartbeat_summary). Injectable so
    # in-process multi-rank tests give each member its own numbers.
    metrics_source = None

    def _metrics_summary(self):
        """This member's compact metric summary (rides heartbeats; the
        coordinator aggregates into one fleet view). Never raises —
        telemetry must not take the control plane down."""
        try:
            src = self.metrics_source
            return src() if callable(src) \
                else _metrics.heartbeat_summary()
        except Exception:       # noqa: BLE001 — best-effort by design
            return None

    # -- wire integrity ----------------------------------------------------
    def _send(self, ep, kind, **payload):
        """Seal and send one control-plane message. The fault hook runs
        on the SEALED bytes, so an injected bit-flip is exactly what a
        corrupted TCP frame looks like to the receiver's CRC."""
        msg = _msg(kind, **payload)
        self._wire_seq += 1
        msg.payload = self.faults.on_wire_send(self._wire_seq,
                                               msg.payload)
        ep.send(msg)

    def _open(self, msg):
        """Unseal + parse an inbound message; a frame failing any
        integrity check is dropped and counted (returns None) — the
        periodic/timeout nature of every protocol (heartbeats re-send,
        barriers and commits time out loudly) covers the loss, and
        garbage NEVER reaches protocol parsing."""
        try:
            return _payload(msg)
        except (IntegrityError, ValueError, UnicodeDecodeError) as e:
            self._note_wire_error(e)
            return None

    def _note_wire_error(self, exc):
        self._wire_errors += 1
        _metrics.default_registry().counter(
            "cluster_wire_errors_total",
            "corrupt control-plane frames dropped by this process").inc()
        if self._wire_errors <= self._WIRE_WARN_LIMIT:
            warnings.warn(
                f"cluster rank {self.rank}: dropped corrupt "
                f"control-plane frame #{self._wire_errors} "
                f"({exc}); protocol timeouts/retries absorb the loss",
                stacklevel=2)

    @property
    def wire_errors(self) -> int:
        """Corrupt control-plane frames this member has dropped."""
        return self._wire_errors

    # -- health ------------------------------------------------------------
    def health(self) -> dict:
        raise NotImplementedError

    def check(self):
        """Raise :class:`MembershipError` when membership was lost."""
        dead = self.health().get("dead", [])
        if dead:
            raise MembershipError(dead, self.world)

    # -- barrier -----------------------------------------------------------
    def barrier(self, name: str, timeout: float = 30.0):
        raise NotImplementedError

    # -- two-phase commit ---------------------------------------------------
    def set_commit_hook(self, hook):
        """Coordinator-side: ``hook(step) -> None`` runs exactly once per
        step, after every rank acked and before the commit broadcast —
        the checkpoint layer's marker write."""
        self._commit_hook = hook

    def ack_save(self, step: int, digest=None, data_digest=None):
        """ACK a durably-written shard. ``digest`` (optional) is the
        shard's manifest content digest: the coordinator compares the
        digests of ALL ranks before publishing — replicas that disagree
        mean divergence, and the step stays uncommitted rather than
        vouching for forked state. ``data_digest`` (optional) is the
        rank's data-iterator state digest; it is RECORDED per rank in
        the commit marker (not agreement-checked — the offsets are
        lockstep by construction, but the marker must vouch for
        whatever each rank wrote) so any restore can cross-check the
        data sidecar it lands on."""
        raise NotImplementedError

    def ack_data_digests(self, step: int) -> dict:
        """{rank: data-state digest} gathered from step N's ACKs (the
        commit hook records them in the marker). Empty off-coordinator
        and for steps outside the bounded commit window."""
        return {}

    def wait_commit(self, step: int, timeout: float = 30.0) -> bool:
        raise NotImplementedError

    # -- cross-replica fingerprint agreement --------------------------------
    def fingerprint_agree(self, seq: int, fp: str,
                          timeout: float = 30.0):
        """Exchange this rank's state fingerprint and wait for the
        cluster verdict. ``seq`` is a monotonically increasing check id
        identical across ranks — NOT the step number: a step re-run
        after a quarantine rollback must open a FRESH agreement round,
        never reuse the stale verdict of its first run. Returns
        ``(ok, divergent_ranks)`` — ``ok`` False when the fingerprints
        disagree (``divergent_ranks`` names the minority; attribution
        is majority-vote, so a 1-vs-1 tie names one side arbitrarily).
        A verdict that does not arrive within ``timeout`` returns
        ``(True, [])`` with a warning: a control-plane hiccup must not
        roll back healthy training, and a dead coordinator is caught by
        the membership check."""
        raise NotImplementedError

    def close(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SoloCluster(ClusterBase):
    """World of one: every protocol completes instantly, no sockets —
    the elastic end state (a job restarted down to a single host) runs
    the same code path as the pod it shrank from."""

    def __init__(self, rank: int = 0, faults=None):
        self.rank = int(rank)
        self.world = 1
        self.faults = faults if faults is not None else NULL_PLAN
        self._commit_hook = None
        self._ack_data: dict = {}

    def health(self):
        return {"rank": self.rank, "world": 1, "alive": [self.rank],
                "dead": [], "stragglers": [], "heartbeat_age": {},
                "wire_errors": 0}

    def barrier(self, name, timeout=30.0):
        return

    def ack_save(self, step, digest=None, data_digest=None):
        self.faults.on_ack(int(step))
        # recorded BEFORE the hook runs: the commit marker reads it
        self._ack_data = {int(step): {0: data_digest}}
        if self._commit_hook is not None:
            self._commit_hook(int(step))

    def ack_data_digests(self, step):
        return dict(self._ack_data.get(int(step), {}))

    def wait_commit(self, step, timeout=30.0):
        return True

    def fingerprint_agree(self, seq, fp, timeout=30.0):
        # a world of one has no peer to disagree with; cross-DEVICE
        # divergence is covered by integrity.replica_buffer_mismatches
        return True, []


class Coordinator(ClusterBase):
    """Rank 0: owns the listener, the membership table, barrier and
    commit bookkeeping. Also a full participant (its own arrivals and
    ACKs count like any rank's)."""

    def __init__(self, world: int, coordinator: str,
                 config: ClusterConfig | None = None, faults=None):
        self.rank = 0
        self.world = int(world)
        self.cfg = config or ClusterConfig()
        self.faults = faults if faults is not None else NULL_PLAN
        host, port = _addr(coordinator)
        self._net = net.NetworkThread(port=port)
        self._lock = threading.Lock()
        self._running = True
        self._commit_hook = None
        self._peers: dict[int, net.EndPoint] = {}
        self._last_hb: dict[int, float] = {}
        self._hb_count: dict[int, int] = {}
        self._worker_metrics: dict[int, dict] = {}  # rank -> hb summary
        self._dead: set[int] = set()
        self._stragglers: set[int] = set()
        # barrier name -> {"arrived": set, "event": Event,
        #                  "missing": list|None}
        self._barriers: dict[str, dict] = {}
        # failed-barrier memory (bounded): a rank arriving AFTER the
        # failure gets told immediately instead of burning its own
        # timeout against a ghost slot that can never complete
        self._failed_barriers: dict[str, list] = {}
        self._acks: dict[int, set] = {}
        self._ack_digests: dict[int, dict] = {}  # step -> {rank: digest}
        self._ack_data: dict[int, dict] = {}     # step -> {rank: data dg}
        self._commit_done: dict[int, threading.Event] = {}
        self._commit_ok: dict[int, bool] = {}
        self._commit_claimed: set[int] = set()   # publish/abort decided
        # cross-replica fingerprint agreement (same bounded window)
        self._fp: dict[int, dict] = {}           # seq -> {rank: fp}
        self._fp_done: dict[int, threading.Event] = {}
        self._fp_result: dict[int, tuple] = {}   # seq -> (ok, divergent)
        self._fp_claimed: set[int] = set()       # verdict decided
        self._threads = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="cluster-accept")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._monitor_loop, daemon=True,
                             name="cluster-monitor")
        t.start()
        self._threads.append(t)

    # -- wiring ------------------------------------------------------------
    def _accept_loop(self):
        while self._running:
            try:
                ep = self._net.accept(timeout=self.cfg.recv_slice)
            except ConnectionError:
                return                     # net closed
            if ep is None:
                continue
            # the hello handshake runs on the PEER's thread: one stalled
            # dialer (or a stray connection to the advertised port) must
            # not serialize every other rank's join behind its timeout.
            # Daemon + untracked: a long-lived coordinator accepting
            # dial-and-die churn must not accumulate dead Thread objects
            threading.Thread(target=self._join_then_serve, args=(ep,),
                             daemon=True, name="cluster-join").start()

    def _join_then_serve(self, ep):
        """The versioned hello handshake: verify the sealed hello and
        its protocol version, answer ``hello-ack`` (or ``hello-reject``
        naming both versions), THEN register the peer. A peer speaking
        an incompatible dialect is turned away at the door instead of
        being mis-parsed for the whole run."""
        try:
            hello = ep.recv(timeout=5.0, max_bytes=MAX_MESSAGE_BYTES)
        except (ConnectionError, IntegrityError):
            ep.close()       # dialer died mid-handshake: free the slot
            return
        if hello is None or hello.meta != b"hello":
            ep.close()
            return
        try:
            data = _payload(hello)
        except (IntegrityError, ValueError, UnicodeDecodeError) as e:
            # unsealed (pre-integrity peer) or corrupted hello
            self._note_wire_error(e)
            self._reject(ep, f"unreadable hello ({e})")
            return
        proto = int(data.get("proto", 0))
        if proto != PROTO_VERSION:
            warnings.warn(
                f"cluster: rejecting join from rank "
                f"{data.get('rank', '?')}: protocol version {proto} "
                f"(this coordinator speaks {PROTO_VERSION})",
                stacklevel=2)
            self._reject(ep, f"protocol version {proto} unsupported")
            return
        rank = int(data["rank"])
        try:
            self._send(ep, "hello-ack", proto=PROTO_VERSION,
                       world=self.world)
        except ConnectionError:
            ep.close()
            return
        with self._lock:
            self._peers[rank] = ep
            self._last_hb[rank] = time.monotonic()
            self._dead.discard(rank)
        self._peer_loop(rank, ep)

    def _reject(self, ep, reason):
        try:
            self._send(ep, "hello-reject", proto=PROTO_VERSION,
                       reason=reason)
            ep.drain(timeout=1.0)    # let the verdict reach the dialer
        except ConnectionError:
            pass
        ep.close()

    def _peer_loop(self, rank, ep):
        while self._running:
            try:
                msg = ep.recv(timeout=self.cfg.recv_slice,
                              max_bytes=MAX_MESSAGE_BYTES)
            except ConnectionError:
                return          # monitor will declare it dead by silence
            except IntegrityError as e:
                # oversized-frame guard: the frame was consumed by the
                # network layer — drop, count, keep serving the peer
                self._note_wire_error(e)
                continue
            if msg is None:
                continue
            data = self._open(msg)
            if data is None:
                continue        # corrupt frame: dropped and counted
            kind = msg.meta.decode()
            if kind == "hb":
                with self._lock:
                    self._last_hb[rank] = time.monotonic()
                    self._hb_count[rank] = self._hb_count.get(rank, 0) + 1
                    m = data.get("metrics")
                    if isinstance(m, dict):
                        # per-rank metric summary riding the beat: the
                        # digest below publishes the aggregated view
                        self._worker_metrics[rank] = m
                try:
                    self._send(ep, "hb-ack", **self._digest())
                except ConnectionError:
                    return
            elif kind == "barrier":
                self._barrier_arrive(data["name"], rank)
            elif kind == "ack":
                self._ack_arrive(int(data["step"]), rank,
                                 data.get("digest"),
                                 data.get("data_digest"))
            elif kind == "fp":
                self._fp_arrive(int(data["seq"]), rank, data.get("fp"))

    def _monitor_loop(self):
        while self._running:
            time.sleep(self.cfg.heartbeat_interval / 2)
            now = time.monotonic()
            newly_dead = []
            with self._lock:
                for rank, seen in self._last_hb.items():
                    age = now - seen
                    if age > self.cfg.dead_after and rank not in self._dead:
                        self._dead.add(rank)
                        newly_dead.append(rank)
                    if age > self.cfg.straggler_after:
                        self._stragglers.add(rank)
                    else:
                        self._stragglers.discard(rank)
            for rank in newly_dead:
                warnings.warn(
                    f"cluster: rank {rank} declared dead "
                    f"(no heartbeat for {self.cfg.dead_after:.1f}s)",
                    stacklevel=2)
                # a barrier waiting on a dead rank can never complete:
                # fail it NOW, naming the corpse, instead of hanging out
                # the caller's full timeout
                self._fail_barriers_missing(rank)

    def _digest(self) -> dict:
        # the coordinator is a full participant: its own summary joins
        # the fleet view (computed outside the lock — it only reads the
        # process metrics registry)
        own = self._metrics_summary()
        with self._lock:
            now = time.monotonic()
            expected = set(range(1, self.world))
            connected = set(self._last_hb)
            ages = {str(r): round(now - t, 3)
                    for r, t in self._last_hb.items()}
            summaries = dict(self._worker_metrics)
            stragglers = sorted(self._stragglers - self._dead)
            dead = sorted(self._dead)
            hb_counts = {str(r): c for r, c in self._hb_count.items()}
            wire_errors = self._wire_errors
        if own is not None:
            summaries[0] = own
        reg = _metrics.default_registry()
        reg.gauge("cluster_stragglers",
                  "ranks whose heartbeat is overdue").set(len(stragglers))
        reg.gauge("cluster_dead_ranks",
                  "ranks declared dead by silence").set(len(dead))
        return {
            "world": self.world,
            "alive": sorted({0} | (connected - set(dead))),
            "dead": dead,
            "never_joined": sorted(expected - connected),
            "stragglers": stragglers,
            "heartbeat_age": ages,
            "heartbeats": hb_counts,
            "wire_errors": wire_errors,
            # ONE fleet-wide metric view (min/max/mean step time, total
            # steps and wire errors), aggregated from the summaries each
            # rank attached to its heartbeats — small enough to ride
            # back on every hb-ack, so workers see it too. Ranks whose
            # last beat is older than cfg.stale_after carry DEAD data:
            # excluded from the aggregates, surfaced as {rank: age}
            "worker_metrics": dict(
                _metrics.aggregate_summaries(
                    summaries, ages=ages,
                    stale_after=self.cfg.stale_after),
                stragglers=len(stragglers)),
        }

    # -- health ------------------------------------------------------------
    def health(self):
        d = self._digest()
        d["rank"] = 0
        with self._lock:
            # the full per-rank breakdown only in the local health
            # report (the broadcast digest carries the aggregate);
            # each entry carries its staleness verdict so a reader
            # can tell a live gauge from a dead rank's last words
            by_rank = {str(r): dict(m)
                       for r, m in self._worker_metrics.items()}
        for r, m in by_rank.items():
            age = d.get("heartbeat_age", {}).get(r)
            m["hb_age_s"] = age
            m["stale"] = bool(age is not None
                              and age > self.cfg.stale_after)
        d["worker_metrics_by_rank"] = by_rank
        return d

    # -- barrier -----------------------------------------------------------
    def _barrier_slot(self, name):
        with self._lock:
            slot = self._barriers.get(name)
            if slot is None:
                slot = {"arrived": set(), "event": threading.Event(),
                        "missing": None}
                self._barriers[name] = slot
            return slot

    def _fail_barrier(self, name, slot, missing):
        """Record + announce a barrier failure: remember it (bounded)
        so late arrivals are told immediately, drop the live slot, wake
        local waiters, tell the arrived workers."""
        with self._lock:
            slot["missing"] = missing
            self._failed_barriers[name] = missing
            while len(self._failed_barriers) > 32:
                self._failed_barriers.pop(
                    next(iter(self._failed_barriers)))
            self._barriers.pop(name, None)
        slot["event"].set()
        self._broadcast("barrier-fail", ranks=slot["arrived"],
                        name=name, missing=missing)

    def _barrier_arrive(self, name, rank):
        with self._lock:
            failed = self._failed_barriers.get(name)
            ep = self._peers.get(rank)
        if failed is not None:
            # straggler arriving at an already-failed barrier: answer
            # NOW with the true missing set, instead of leaving it to
            # time out again and falsely blame the coordinator
            if rank != 0 and ep is not None:
                try:
                    self._send(ep, "barrier-fail", name=name,
                               missing=failed)
                except ConnectionError:
                    pass
            return
        slot = self._barrier_slot(name)
        with self._lock:
            slot["arrived"].add(rank)
            complete = len(slot["arrived"]) == self.world
            # a participant that is ALREADY dead will never arrive:
            # fail now, naming the corpse — live ranks merely being
            # slow still get the full timeout
            dead_missing = sorted(self._dead - slot["arrived"])
        if complete:
            slot["event"].set()
            self._broadcast("barrier-ok", ranks=slot["arrived"],
                            name=name)
        elif dead_missing:
            self._fail_barrier(name, slot, dead_missing)

    def _fail_barriers_missing(self, dead_rank):
        with self._lock:
            pending = [(n, s) for n, s in self._barriers.items()
                       if not s["event"].is_set()
                       and dead_rank not in s["arrived"]]
            missing = {n: sorted(self._dead - s["arrived"])
                       for n, s in pending}
        for name, slot in pending:
            self._fail_barrier(name, slot, missing[name])

    def _broadcast(self, kind, ranks=None, **payload):
        with self._lock:
            eps = [(r, ep) for r, ep in self._peers.items()
                   if (ranks is None or r in ranks) and r not in self._dead]
        for _r, ep in eps:
            try:
                self._send(ep, kind, **payload)
            except ConnectionError:
                pass

    def barrier(self, name, timeout=30.0):
        with self._lock:
            failed = self._failed_barriers.get(name)
        if failed is not None:
            raise BarrierTimeout(name, failed, 0.0)
        slot = self._barrier_slot(name)
        self._barrier_arrive(name, 0)
        if not slot["event"].wait(timeout):
            with self._lock:
                missing = sorted(
                    set(range(self.world)) - slot["arrived"])
            self._fail_barrier(name, slot, missing)
        with self._lock:
            self._barriers.pop(name, None)
            missing = slot["missing"]
        if missing:
            raise BarrierTimeout(name, missing, timeout)

    # -- two-phase commit ---------------------------------------------------
    def _commit_slot(self, step):
        with self._lock:
            ev = self._commit_done.get(step)
            if ev is None:
                ev = threading.Event()
                self._commit_done[step] = ev
                self._acks.setdefault(step, set())
            return ev

    def _ack_arrive(self, step, rank, digest=None, data_digest=None):
        ev = self._commit_slot(step)
        with self._lock:
            self._acks[step].add(rank)
            if digest is not None:
                self._ack_digests.setdefault(step, {})[rank] = digest
            if data_digest is not None:
                self._ack_data.setdefault(step, {})[rank] = data_digest
            complete = len(self._acks[step]) == self.world
            # claim the publish under the lock: a quorum completing
            # AFTER wait_commit's timeout aborted the step must not
            # publish a marker every save() caller was told to distrust
            claim = complete and step not in self._commit_claimed
            if claim:
                self._commit_claimed.add(step)
            digests = dict(self._ack_digests.get(step, {}))
        if claim:
            # full replicas must be bit-identical: ACK digests that
            # disagree mean a replica diverged, and a commit marker must
            # never vouch for forked state — the step stays uncommitted
            # (every checkpoint that DOES commit is therefore
            # cross-replica-agreed, which is what makes "roll back to
            # the last committed step" a divergence recovery)
            ok = len({d for d in digests.values()}) <= 1
            if not ok:
                groups: dict = {}
                for r, d in digests.items():
                    groups.setdefault(d, []).append(r)
                warnings.warn(
                    f"checkpoint step {step}: shard content digests "
                    f"disagree across ranks ({groups}) — replicas have "
                    "diverged; the step stays uncommitted", stacklevel=2)
            # publish the marker (the checkpoint layer's atomic write)
            # BEFORE telling anyone the step committed
            if ok and self._commit_hook is not None:
                try:
                    self._commit_hook(step)
                except Exception as e:      # marker write failed: abort
                    warnings.warn(f"commit hook for step {step} failed "
                                  f"({type(e).__name__}: {e}); step "
                                  "stays uncommitted", stacklevel=2)
                    ok = False
            with self._lock:
                self._commit_ok[step] = ok
                _prune_window(self._commit_ok, self._acks,
                              self._ack_digests, self._ack_data,
                              self._commit_done, self._commit_claimed)
            ev.set()
            self._broadcast("commit", step=step, ok=ok)

    def ack_save(self, step, digest=None, data_digest=None):
        self.faults.on_ack(int(step))
        self._ack_arrive(int(step), 0, digest, data_digest)

    def ack_data_digests(self, step):
        with self._lock:
            return dict(self._ack_data.get(int(step), {}))

    def wait_commit(self, step, timeout=30.0):
        step = int(step)
        ev = self._commit_slot(step)
        if not ev.wait(timeout):
            with self._lock:
                aborted = step not in self._commit_claimed
                if aborted:
                    # no publish in flight: ABORT, so a straggler's late
                    # ACK cannot commit a step save() already reported
                    # uncommitted
                    self._commit_claimed.add(step)
                    self._commit_ok[step] = False
            if aborted:
                ev.set()
                self._broadcast("commit", step=step, ok=False)
            else:
                ev.wait(5.0)     # publish decision in flight; let it land
        with self._lock:
            return bool(self._commit_ok.get(step))

    # -- cross-replica fingerprint agreement --------------------------------
    def _fp_slot(self, seq):
        with self._lock:
            ev = self._fp_done.get(seq)
            if ev is None:
                ev = threading.Event()
                self._fp_done[seq] = ev
                self._fp.setdefault(seq, {})
            return ev

    def _fp_arrive(self, seq, rank, fp):
        ev = self._fp_slot(seq)
        with self._lock:
            self._fp[seq][rank] = fp
            complete = len(self._fp[seq]) == self.world
            # claim the verdict under the lock: a straggler's fp
            # landing AFTER fingerprint_agree's timeout already
            # recorded "agreed" must not broadcast a contradicting
            # late verdict (workers quarantining while rank 0 trains
            # on would strand the lockstep barriers) — same rule as
            # _commit_claimed on the commit path
            if not complete or seq in self._fp_claimed:
                return
            self._fp_claimed.add(seq)
            values = list(self._fp[seq].values())
            # deterministic tie-break (count, then the fp string): a
            # 1-vs-1 tie cannot attribute blame either way, but the
            # verdict must not depend on set-iteration hash order
            majority = max(sorted(set(values)), key=values.count)
            divergent = sorted(r for r, v in self._fp[seq].items()
                               if v != majority)
            ok = not divergent
            self._fp_result[seq] = (ok, divergent)
            _prune_window(self._fp_result, self._fp, self._fp_done,
                          self._fp_claimed)
        if not ok:
            warnings.warn(
                "cross-replica fingerprint DISAGREEMENT (check round "
                f"{seq}): rank(s) {divergent} hold a minority state "
                "(silent divergence — quarantine and roll back)",
                stacklevel=2)
        ev.set()
        self._broadcast("fp-result", seq=seq, ok=ok,
                        divergent=divergent)

    def fingerprint_agree(self, seq, fp, timeout=30.0):
        seq = int(seq)
        ev = self._fp_slot(seq)
        self._fp_arrive(seq, 0, fp)
        if not ev.wait(timeout):
            warnings.warn(
                f"fingerprint agreement round {seq} timed out after "
                f"{timeout:.0f}s (a rank stalled?); treating as agreed —"
                " membership checks cover a dead peer", stacklevel=2)
            with self._lock:
                # claim + record the non-verdict: the round is DECIDED
                # as "agreed" — a straggler's late fp can no longer
                # complete it into a contradicting broadcast, and the
                # window pruning reaches the slot (a lost 'fp' frame
                # must not leak its Event forever) — same rules as
                # wait_commit's timeout abort. If the round completed
                # and BROADCAST in the race window between our wait
                # expiring and this lock, that verdict was already
                # sent to every worker: return IT (not the literal
                # "agreed"), or rank 0 would train on while its
                # workers quarantine and the lockstep barriers strand
                if seq not in self._fp_claimed:
                    self._fp_claimed.add(seq)
                    self._fp_result[seq] = (True, [])
                result = self._fp_result.get(seq, (True, []))
                _prune_window(self._fp_result, self._fp, self._fp_done,
                              self._fp_claimed)
            return result
        with self._lock:
            return self._fp_result.get(seq, (True, []))

    # -- teardown ----------------------------------------------------------
    def close(self):
        self._running = False
        self._net.close()


class Worker(ClusterBase):
    """Rank > 0: dials the coordinator, heartbeats on a background
    thread, and learns cluster state from the heartbeat-ack digest."""

    def __init__(self, rank: int, world: int, coordinator: str,
                 config: ClusterConfig | None = None, faults=None):
        self.rank = int(rank)
        self.world = int(world)
        self.cfg = config or ClusterConfig()
        self.faults = faults if faults is not None else NULL_PLAN
        self._net = net.NetworkThread(port=-1)
        self._lock = threading.Lock()
        self._running = True
        self._commit_hook = None
        self._digest: dict = {}
        self._last_ack = time.monotonic()
        self._coordinator_dead = False
        self._dropped = False          # fault-injected silent death
        self._barriers: dict[str, dict] = {}
        self._commit_done: dict[int, threading.Event] = {}
        self._commit_ok: dict[int, bool] = {}
        self._fp_done: dict[int, threading.Event] = {}
        self._fp_result: dict[int, tuple] = {}
        host, port = _addr(coordinator)
        self._ep = self._dial(host, port)
        try:
            self._hello(host, port)
        except BaseException:
            self._net.close()
            raise
        self._threads = []
        for target, name in ((self._rx_loop, "rx"), (self._hb_loop, "hb")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"cluster-{name}-{rank}")
            t.start()
            self._threads.append(t)

    def _hello(self, host, port):
        """Versioned hello: announce our rank + protocol version and
        wait for the coordinator's verdict — ``hello-ack`` joins,
        ``hello-reject`` (or silence from a pre-integrity coordinator
        that cannot read the sealed hello) fails LOUDLY here, at join,
        instead of as mis-parsed messages mid-run."""
        self._send(self._ep, "hello", rank=self.rank,
                   proto=PROTO_VERSION)
        deadline = time.monotonic() + self.cfg.connect_timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise ClusterError(
                    f"rank {self.rank}: no hello-ack from coordinator "
                    f"{host}:{port} within "
                    f"{self.cfg.connect_timeout:.0f}s (version-"
                    "mismatched or unreachable control plane?)")
            try:
                msg = self._ep.recv(timeout=min(1.0, remaining),
                                    max_bytes=MAX_MESSAGE_BYTES)
            except (ConnectionError, IntegrityError) as e:
                raise ClusterError(
                    f"rank {self.rank}: hello handshake with "
                    f"{host}:{port} failed ({e})") from None
            if msg is None:
                continue
            try:
                data = _payload(msg)
            except (IntegrityError, ValueError, UnicodeDecodeError) as e:
                raise ClusterError(
                    f"rank {self.rank}: corrupt hello reply from "
                    f"{host}:{port} ({e})") from None
            kind = msg.meta.decode()
            if kind == "hello-reject":
                raise ClusterError(
                    f"rank {self.rank}: coordinator rejected the join: "
                    f"{data.get('reason', 'no reason given')} "
                    f"(coordinator protocol {data.get('proto')}, ours "
                    f"{PROTO_VERSION})")
            if kind == "hello-ack":
                return
            # anything else this early is a protocol violation
            raise ClusterError(
                f"rank {self.rank}: unexpected {kind!r} during the "
                "hello handshake")

    def _dial(self, host, port):
        deadline = time.monotonic() + self.cfg.connect_timeout
        while True:
            try:
                return self._net.connect(host, port)
            except ConnectionError:
                if time.monotonic() >= deadline:
                    raise ClusterError(
                        f"rank {self.rank}: coordinator {host}:{port} "
                        f"unreachable for {self.cfg.connect_timeout:.0f}s"
                    ) from None
                time.sleep(0.1)

    # -- background loops --------------------------------------------------
    def _hb_loop(self):
        seq = 0
        while self._running:
            seq += 1
            try:
                self.faults.on_heartbeat(seq)
            except _DropPeerSignal:
                # simulate a silent network death: stop beating, leave
                # the socket up (the coordinator must detect by SILENCE)
                with self._lock:
                    self._dropped = True
                return
            if not self._running:
                return
            try:
                # the rank's metric summary rides every beat (a few
                # tens of bytes): the coordinator's health report
                # aggregates them into the fleet view
                self._hb_sent_at = time.monotonic()
                self._send(self._ep, "hb", rank=self.rank, seq=seq,
                           metrics=self._metrics_summary())
            except ConnectionError:
                if self._running:
                    self._mark_coordinator_dead()
                return
            time.sleep(self.cfg.heartbeat_interval)
            if time.monotonic() - self._last_ack > self.cfg.dead_after:
                self._mark_coordinator_dead()
                return

    def _rx_loop(self):
        while self._running:
            try:
                msg = self._ep.recv(timeout=self.cfg.recv_slice,
                                    max_bytes=MAX_MESSAGE_BYTES)
            except ConnectionError:
                if self._running:    # our own close() is not a death
                    self._mark_coordinator_dead()
                return
            except IntegrityError as e:
                self._note_wire_error(e)     # oversized-frame guard
                continue
            if msg is None:
                continue
            data = self._open(msg)
            if data is None:
                continue        # corrupt frame: dropped and counted
            kind = msg.meta.decode()
            if kind == "hb-ack":
                now = time.monotonic()
                with self._lock:
                    self._digest = data
                    self._last_ack = now
                    sent = getattr(self, "_hb_sent_at", None)
                    self._hb_sent_at = None
                if sent is not None:
                    # beat-to-ack round trip (control-plane latency; an
                    # ack matched against the NEWEST un-acked beat, so a
                    # coalesced/slow ack reads as the large RTT it is)
                    _metrics.default_registry().histogram(
                        "cluster_heartbeat_rtt_seconds",
                        "worker heartbeat send to coordinator ack"
                    ).observe(now - sent)
            elif kind in ("barrier-ok", "barrier-fail"):
                with self._lock:
                    slot = self._barriers.get(data["name"])
                if slot is not None:
                    slot["missing"] = data.get("missing") \
                        if kind == "barrier-fail" else None
                    slot["event"].set()
            elif kind == "commit":
                step = int(data["step"])
                with self._lock:
                    ev = self._commit_done.setdefault(
                        step, threading.Event())
                    self._commit_ok[step] = bool(data.get("ok"))
                    # same bounded window the coordinator keeps: a
                    # weeks-long run must not leak an Event per step
                    _prune_window(self._commit_ok, self._commit_done)
                ev.set()
            elif kind == "fp-result":
                seq = int(data["seq"])
                with self._lock:
                    ev = self._fp_done.setdefault(seq,
                                                  threading.Event())
                    self._fp_result[seq] = (
                        bool(data.get("ok")),
                        [int(r) for r in data.get("divergent", [])])
                    _prune_window(self._fp_result, self._fp_done)
                ev.set()

    def _mark_coordinator_dead(self):
        with self._lock:
            if self._dropped:       # fault-injected: we left, not them
                return
            self._coordinator_dead = True

    # -- health ------------------------------------------------------------
    def health(self):
        with self._lock:
            d = dict(self._digest) if self._digest else {
                "world": self.world, "alive": [], "dead": [],
                "stragglers": [], "heartbeat_age": {}}
            d["rank"] = self.rank
            d["coordinator_ack_age"] = round(
                time.monotonic() - self._last_ack, 3)
            # the digest's wire_errors is the COORDINATOR's count; ours
            # rides separately so a one-sided corrupt link is visible
            d["local_wire_errors"] = self._wire_errors
            if self._coordinator_dead:
                dead = set(d.get("dead", []))
                dead.add(0)
                d["dead"] = sorted(dead)
        return d

    # -- barrier -----------------------------------------------------------
    def barrier(self, name, timeout=30.0):
        slot = {"event": threading.Event(), "missing": None}
        with self._lock:
            self._barriers[name] = slot
        try:
            self._send(self._ep, "barrier", name=name, rank=self.rank)
        except ConnectionError:
            raise BarrierTimeout(name, [0], 0.0) from None
        # small slack over the caller's budget: the coordinator times
        # the barrier too and its fail message names the true missing
        # set — only a DEAD coordinator leaves us to our local timeout
        if not slot["event"].wait(timeout + 2 * self.cfg.recv_slice):
            with self._lock:
                self._barriers.pop(name, None)
            raise BarrierTimeout(name, [0], timeout)
        with self._lock:
            self._barriers.pop(name, None)
        if slot["missing"]:
            raise BarrierTimeout(name, slot["missing"], timeout)

    # -- two-phase commit ---------------------------------------------------
    def ack_save(self, step, digest=None, data_digest=None):
        self.faults.on_ack(int(step))
        with self._lock:
            self._commit_done.setdefault(int(step), threading.Event())
        try:
            self._send(self._ep, "ack", step=int(step), rank=self.rank,
                       digest=digest, data_digest=data_digest)
        except ConnectionError:
            self._mark_coordinator_dead()

    def wait_commit(self, step, timeout=30.0):
        with self._lock:
            ev = self._commit_done.setdefault(int(step),
                                              threading.Event())
        if not ev.wait(timeout):
            return False
        with self._lock:
            return bool(self._commit_ok.get(int(step)))

    # -- cross-replica fingerprint agreement --------------------------------
    def fingerprint_agree(self, seq, fp, timeout=30.0):
        seq = int(seq)
        with self._lock:
            ev = self._fp_done.setdefault(seq, threading.Event())
        try:
            self._send(self._ep, "fp", seq=seq, fp=fp,
                       rank=self.rank)
        except ConnectionError:
            self._mark_coordinator_dead()
            return True, []      # membership check reports the death
        if not ev.wait(timeout):
            with self._lock:
                # the verdict may have landed in the race window while
                # the wait expired — honor it if so
                late = self._fp_result.get(seq)
            if late is not None:
                return late
            warnings.warn(
                f"fingerprint agreement round {seq} timed out after "
                f"{timeout:.0f}s (coordinator stalled?); treating as "
                "agreed — membership checks cover a dead coordinator",
                stacklevel=2)
            return True, []
        with self._lock:
            return self._fp_result.get(seq, (True, []))

    # -- teardown ----------------------------------------------------------
    def close(self):
        self._running = False
        self._net.close()


def make_cluster(rank: int, world: int, coordinator: str | None = None,
                 config: ClusterConfig | None = None,
                 faults=None) -> ClusterBase:
    """Build this process's cluster member: :class:`SoloCluster` for a
    world of one, :class:`Coordinator` for rank 0, :class:`Worker`
    otherwise. ``coordinator`` is ``"host:port"`` (the same address the
    jax.distributed coordinator convention uses)."""
    if world <= 1:
        return SoloCluster(rank, faults)
    if coordinator is None:
        raise ValueError("multi-rank cluster needs coordinator='host:port'")
    if int(rank) == 0:
        return Coordinator(world, coordinator, config, faults)
    return Worker(rank, world, coordinator, config, faults)


__all__ = ["PROTO_VERSION", "ClusterConfig", "ClusterError",
           "MembershipError", "BarrierTimeout", "ClusterBase",
           "SoloCluster", "Coordinator", "Worker", "make_cluster"]
