"""Deterministic fault injection for the resilient training runtime.

A :class:`FaultPlan` is a schedule of failures keyed by global step
number, consumed by :class:`~singa_tpu.resilience.runtime.ResilientTrainer`
at well-defined hook points. Every fault fires a configured number of
times and then disarms, so chaos tests are exactly reproducible — no
randomness, no sleeps beyond the milliseconds a hang fault asks for.

Faults::

    plan = (FaultPlan()
            .poison_batch(step=3)          # NaN inputs -> NaN loss/grads
            .fail_step(step=5, times=2)    # transient step exception
            .fail_data(step=7)             # data iterator raises
            .hang_step(step=9, seconds=.05)  # watchdog fodder
            .preempt_at(step=11)           # real SIGTERM to this process
            .crash_after_save(step=13))    # die mid-async-save

Serving-fleet faults (consumed by the serving engine / fleet router):
``fail_submit`` (submit dies on the wire), ``crash_after_admit`` (the
replica dies holding an admitted request — the stranded shape), and
``slow_replica`` (straggling ticks; drives per-try-timeout
re-dispatch). Autoscaler faults (consumed by
``serving.autoscaler.Autoscaler``): ``stale_heartbeat`` (a replica's
observation goes stale — dead data the supervisor must not scale
on), ``flapping_replica`` (spawned replacements crash right after
admission — drives flap damping / quarantine), and ``slow_spawn``
(spin-up stalls — drives the spawn-to-ready accounting behind the
gateway's derived Retry-After).

On-disk chaos (for restore-hardening tests) lives beside the plan:
:func:`truncate_checkpoint` / :func:`corrupt_checkpoint` damage a
committed checkpoint step directory in place.
"""

from __future__ import annotations

import os
import signal
import time

import numpy as np
import jax.numpy as jnp

from ..data import DataWorkerKilled
from ..tensor import Tensor


class FaultInjected(RuntimeError):
    """A transient failure raised by a FaultPlan (retryable)."""


class SimulatedCrash(RuntimeError):
    """A hard crash injected mid-async-save (NOT retryable: the chaos
    test catches it and restarts a fresh trainer, like a supervisor)."""


class DropPeerSignal(BaseException):
    """Raised from FaultPlan.on_heartbeat to simulate a silently-dropped
    peer: the cluster Worker stops heartbeating but keeps its socket up,
    so the coordinator must detect the loss by SILENCE (the realistic
    network-partition shape). BaseException so no blanket Exception
    handler accidentally swallows the injected death."""


class FaultPlan:
    """Deterministic, step-keyed failure schedule (see module doc).

    All ``.fault(...)`` registrations return ``self`` so plans chain.
    ``fired`` logs ``(step, kind)`` tuples for test assertions.
    """

    def __init__(self):
        self._faults = []   # dicts: kind, step, times, extras
        self.fired = []

    def _arm(self, kind, step, times=1, **extra):
        rec = {"kind": kind, "step": int(step), "times": int(times)}
        rec.update(extra)
        self._faults.append(rec)
        return self

    def _take(self, kind, step):
        for rec in self._faults:
            if rec["kind"] == kind and rec["step"] == int(step) \
                    and rec["times"] > 0:
                rec["times"] -= 1
                self.fired.append((int(step), kind))
                return rec
        return None

    # -- registration ------------------------------------------------------
    def poison_batch(self, step, times=1):
        """Replace every floating tensor in step N's batch with NaNs."""
        return self._arm("poison", step, times)

    def fail_step(self, step, times=1, message="injected step failure"):
        """Raise FaultInjected from the training step body."""
        return self._arm("step", step, times, message=message)

    def fail_data(self, step, times=1, message="injected data failure"):
        """Raise FaultInjected from the data-fetch path."""
        return self._arm("data", step, times, message=message)

    def hang_step(self, step, seconds=0.05, times=1):
        """Stall the step body (drives the watchdog timeout)."""
        return self._arm("hang", step, times, seconds=float(seconds))

    def preempt_at(self, step, sig=signal.SIGTERM):
        """Deliver a real preemption signal to this process just before
        step N runs (the trainer's handler turns it into a synchronous
        checkpoint + EXIT_PREEMPTED at the step boundary)."""
        return self._arm("preempt", step, 1, sig=int(sig))

    def crash_after_save(self, step):
        """Raise SimulatedCrash right after step N's async checkpoint
        save is DISPATCHED but before it is awaited — the process dies
        mid-write, exercising restart over a possibly-incomplete latest
        checkpoint."""
        return self._arm("crash_save", step, 1)

    # -- cluster faults ----------------------------------------------------
    def kill_rank(self, step):
        """Hard-kill THIS process (``os._exit(1)``) just before step N
        runs — no atexit, no checkpoint, no goodbye: the way a preempted
        or OOM-killed pod member actually vanishes. Peers must detect
        the loss by heartbeat silence."""
        return self._arm("kill", step, 1)

    def drop_peer(self, beat):
        """From heartbeat number ``beat`` on, this rank goes silent
        (stops heartbeating, socket left up — a network partition, not
        a process death). Drives the coordinator's dead-peer detection
        without killing the test process."""
        return self._arm("drop_peer", beat, 1)

    def delay_heartbeat(self, beat, seconds=0.5, times=1):
        """Stall heartbeat number ``beat`` by ``seconds`` before it is
        sent — straggler fodder for the health monitor."""
        return self._arm("hb_delay", beat, times, seconds=float(seconds))

    def kill_before_ack(self, step):
        """Hard-kill this process AFTER step N's checkpoint shard is
        fully written but BEFORE the ACK reaches the coordinator — the
        two-phase-commit hole: the step must never gain a commit marker
        and ``restore_latest`` must refuse it."""
        return self._arm("kill_ack", step, 1)

    # -- data-pipeline faults ----------------------------------------------
    def corrupt_sample(self, index, times=1):
        """Make the data worker's decode of the sample at EPOCH POSITION
        ``index`` (its slot in the epoch's permutation, not its dataset
        index) raise — the corrupt-JPEG shape of failure. The iterator
        must skip-and-quarantine it (one sample lost, attributed), not
        die. ``times`` spans re-encounters (a later epoch, or a resume
        replaying the same position)."""
        return self._arm("corrupt_sample", index, times)

    def slow_fetch(self, step, seconds=0.5, times=1):
        """Stall the step-N data fetch by ``seconds`` before it runs —
        a straggling filesystem / network read, NOT a failure: nothing
        raises, the batch just arrives late."""
        return self._arm("slow_fetch", step, times,
                         seconds=float(seconds))

    def kill_data_worker(self, index):
        """Kill the prefetch worker abruptly while it decodes the
        sample at epoch position ``index`` — no error record, no
        goodbye (a segfaulting decoder). The consumer must detect the
        death AND name the sample that killed it."""
        return self._arm("kill_worker", index, 1)

    # -- serving-fleet faults ----------------------------------------------
    def fail_submit(self, seq, times=1):
        """Make the engine's submit path raise ``ConnectionError`` for
        ``times`` CONSECUTIVE submissions starting at submit number
        ``seq`` (counting from 1, per engine) — the request dies on the
        wire before the engine sees it. A fleet router must classify
        this as a REPLICA failure (breaker fodder), never a request
        failure. Like ``corrupt_wire``, submit numbers never repeat,
        so ``times`` spans consecutive submits."""
        return self._arm("submit_wire", seq, times)

    def crash_after_admit(self, req_id, times=1):
        """Crash the whole engine the instant after it ADMITS request
        id ``req_id`` — the stranded-request shape: the submit call
        succeeded, the replica died, and the future comes back already
        failed with ``ReplicaCrashed``. Drives a fleet router's
        exactly-once re-dispatch deterministically."""
        return self._arm("admit_crash", req_id, times)

    def slow_replica(self, tick, seconds=0.2, times=1):
        """Stall ``times`` CONSECUTIVE serve-loop ticks starting at
        tick ``tick`` by ``seconds`` each — a straggling replica, not a
        dead one: nothing raises, responses just arrive late. Drives a
        fleet router's per-try timeout → re-dispatch-with-remaining-
        budget path."""
        return self._arm("slow_replica", tick, times,
                         seconds=float(seconds))

    def corrupt_handoff(self, seq, times=1):
        """Flip one bit in each of the next ``times`` SEALED KV-handoff
        frames this engine extracts, starting from handoff number
        ``seq`` (counting from 1, per engine). The survivor's
        ``open_frame`` must refuse the frame typed
        (``HandoffRefused``) and the handoff must fall back to
        recompute re-dispatch — corrupt KV is never injected."""
        return self._arm("handoff_corrupt", seq, times)

    def slow_handoff(self, seq, seconds=0.2, times=1):
        """Stall ``times`` CONSECUTIVE handoff extractions starting at
        handoff number ``seq`` by ``seconds`` each — a straggling
        migration, not a dead one. Drives the deadline-drain budget
        accounting: a handoff that no longer fits the remaining budget
        degrades to recompute re-dispatch."""
        return self._arm("handoff_slow", seq, times,
                         seconds=float(seconds))

    def kill_mid_handoff(self, seq):
        """Hard-kill this process (``os._exit(1)``) in the middle of
        handoff number ``seq`` — after the snapshot is extracted but
        before it reaches a survivor: the dying replica dies HARDER
        mid-migration. The fleet's crash path must still recover the
        request by recompute (or from its last cadence checkpoint)."""
        return self._arm("handoff_kill", seq, 1)

    # -- disaggregated-pool transfer faults --------------------------------
    # The prefill→decode TRANSFER path (post-prefill KV migration
    # between role-tagged pools) has its own delivery hook
    # (``on_transfer_send``) because its failure shapes differ from a
    # drain handoff's: frames can be dropped or DUPLICATED in flight,
    # not just corrupted. Corruption itself is NOT re-registered here
    # — ``corrupt_handoff`` already covers it (the transfer extracts
    # its snapshot through the same ``on_handoff_send`` sealing hook),
    # exactly like ``kill_mid_handoff`` covers dying mid-extraction.

    def slow_transfer(self, seq, seconds=0.2, times=1):
        """Stall ``times`` CONSECUTIVE prefill→decode transfer
        deliveries starting at transfer number ``seq`` by ``seconds``
        each — a congested interconnect, not a failure: the frame
        arrives late. Drives the transfer ladder's deadline-budget
        accounting."""
        return self._arm("transfer_slow", seq, times,
                         seconds=float(seconds))

    def drop_transfer(self, seq, times=1):
        """Silently DROP ``times`` consecutive transfer deliveries
        starting at transfer number ``seq`` — the frame leaves the
        prefill replica and never arrives. The router must treat the
        lost delivery as a failed attempt (retry next-best peer →
        colocate fallback), never hang the request."""
        return self._arm("transfer_drop", seq, times)

    def dup_transfer(self, seq, times=1):
        """DUPLICATE ``times`` consecutive transfer deliveries
        starting at transfer number ``seq`` — the frame arrives twice
        (a retransmit race). The router's exactly-once guard must
        DISCARD the second copy, not double-inject it: one decode
        future per request, ``deliveries == 1``."""
        return self._arm("transfer_dup", seq, times)

    # -- autoscaler faults -------------------------------------------------
    def stale_heartbeat(self, tick, times=1, name=None):
        """Mark a replica's observation STALE for ``times``
        CONSECUTIVE autoscaler observation passes starting at pass
        ``tick`` (counting from 1 per supervisor) — last-known gauges
        with no fresh heartbeat behind them. ``name`` pins the fault
        to one replica (None = the first replica observed each pass).
        The supervisor must exclude the stale gauges from load
        decisions and, past its persistence window, replace the
        silent replica. (Worker-side heartbeat silence is
        ``drop_peer`` / ``delay_heartbeat``; this fault drives the
        SUPERVISOR's view.)"""
        return self._arm("hb_stale", tick, times, name=name)

    def flapping_replica(self, spawn, times=3):
        """Doom ``times`` CONSECUTIVE replica spawns starting at
        spawn number ``spawn`` (counting from 1 per supervisor): each
        spawned replica passes warm admission and then crashes
        immediately — the ready↔dead flap shape. Drives the
        autoscaler's flap damping: after its threshold the seat must
        be QUARANTINED, not respawned forever."""
        return self._arm("flap", spawn, times)

    def slow_spawn(self, spawn, seconds=0.2, times=1):
        """Stall ``times`` CONSECUTIVE replica spawns starting at
        spawn number ``spawn`` by ``seconds`` each — a cold image
        pull, a slow AOT deserialize. Drives the spawn-to-ready
        accounting behind the gateway's derived ``Retry-After`` and
        the supervisor's spawn-timeout path."""
        return self._arm("slow_spawn", spawn, times,
                         seconds=float(seconds))

    # -- integrity faults --------------------------------------------------
    def corrupt_wire(self, seq, times=1):
        """Flip one bit in each of the next ``times`` control-plane
        frames this member sends, starting from send number ``seq``
        (counting from 1, after sealing) — exactly what a corrupted TCP
        frame looks like to the receiver's CRC. The receiver must
        drop-and-count them, never parse them. (Unlike step-keyed
        faults, send numbers never repeat, so ``times`` spans
        CONSECUTIVE frames.)"""
        return self._arm("wire", seq, times)

    def diverge_at(self, step, eps=1e-3, times=1):
        """Silently perturb this rank's first floating parameter by
        ``eps`` right before the step-N fingerprint check — the SDC /
        non-deterministic-kernel shape of failure: state forks with no
        exception anywhere. Only the cross-replica fingerprint (or the
        commit-time ACK digest agreement) can catch it."""
        return self._arm("diverge", step, times, eps=float(eps))

    # -- trainer hook points ----------------------------------------------
    def on_step(self, step, attempt=0):
        """Called inside the (retried, watchdog-timed) step body before
        the model runs."""
        if self._take("kill", step) is not None:
            os._exit(1)          # no cleanup: a real pod death
        rec = self._take("preempt", step)
        if rec is not None:
            os.kill(os.getpid(), rec["sig"])
        rec = self._take("hang", step)
        if rec is not None:
            time.sleep(rec["seconds"])
        # slow_replica matches CONSECUTIVE ticks from its start (tick
        # numbers never repeat — same matching rule as corrupt_wire)
        for rec in self._faults:
            if rec["kind"] == "slow_replica" and rec["times"] > 0 \
                    and int(step) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(step), "slow_replica"))
                time.sleep(rec["seconds"])
                break
        rec = self._take("step", step)
        if rec is not None:
            raise FaultInjected(f"step {step}: {rec['message']}")

    def on_batch(self, step, batch):
        """Possibly poison the fetched batch; returns the batch to use."""
        if self._take("poison", step) is None:
            return batch
        poisoned = []
        for item in batch:
            arr = item.data if isinstance(item, Tensor) else item
            if jnp.issubdtype(jnp.asarray(arr).dtype, jnp.floating):
                nan = jnp.full(jnp.shape(arr), jnp.nan,
                               jnp.asarray(arr).dtype)
                item = Tensor(data=nan, device=getattr(
                    item, "device", None), requires_grad=False) \
                    if isinstance(item, Tensor) else np.asarray(nan)
            poisoned.append(item)
        return tuple(poisoned)

    def on_data(self, step):
        """Called before each data fetch attempt."""
        rec = self._take("slow_fetch", step)
        if rec is not None:
            time.sleep(rec["seconds"])      # late, not failed
        rec = self._take("data", step)
        if rec is not None:
            raise FaultInjected(f"step {step}: {rec['message']}")

    def on_sample(self, index, path):
        """Called by the data worker for every sample it dispatches
        (``index`` is the sample's position in the epoch's
        permutation)."""
        if self._take("kill_worker", index) is not None:
            raise DataWorkerKilled(
                f"data worker killed at epoch position {index} ({path})")
        rec = self._take("corrupt_sample", index)
        if rec is not None:
            raise FaultInjected(
                f"injected corrupt sample at epoch position {index} "
                f"({path})")

    def on_saved(self, step):
        """Called after a checkpoint save was dispatched for step N."""
        if self._take("crash_save", step) is not None:
            raise SimulatedCrash(f"crashed mid-async-save of step {step}")

    def on_heartbeat(self, seq):
        """Called by the cluster Worker before sending heartbeat ``seq``."""
        rec = self._take("hb_delay", seq)
        if rec is not None:
            time.sleep(rec["seconds"])
        if self._take("drop_peer", seq) is not None:
            raise DropPeerSignal(f"dropped at heartbeat {seq}")

    def on_ack(self, step):
        """Called after step N's checkpoint shard is durably written,
        just before the two-phase-commit ACK is sent."""
        if self._take("kill_ack", step) is not None:
            os._exit(1)          # died in the commit hole

    def on_submit(self, seq):
        """Called by the serving engine for every submit attempt
        (``seq`` counts from 1 per engine). An armed ``fail_submit``
        raises ``ConnectionError`` — consecutive matching, like
        ``on_wire_send``."""
        for rec in self._faults:
            if rec["kind"] == "submit_wire" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "submit_wire"))
                raise ConnectionError(
                    f"injected submit wire error (submit {seq})")

    def on_admit(self, req_id):
        """Called right after the serving engine admits request
        ``req_id``; True tells the engine to crash itself NOW (the
        crash-after-admit stranded-request fault)."""
        return self._take("admit_crash", req_id) is not None

    def on_wire_send(self, seq, payload):
        """Called with every SEALED outbound control-plane frame;
        returns the bytes to actually send (possibly bit-flipped)."""
        took = None
        for rec in self._faults:
            # send numbers never repeat, so a wire fault covers the
            # CONSECUTIVE frames starting at its seq (see corrupt_wire)
            if rec["kind"] == "wire" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "wire"))
                took = rec
                break
        if took is None or not payload:
            return payload
        return payload[:-1] + bytes([payload[-1] ^ 0x01])

    def on_handoff_send(self, seq, frame):
        """Called with every SEALED outbound KV-handoff frame (``seq``
        counts from 1 per engine); returns the bytes to actually hand
        off. Handoff numbers never repeat, so all three handoff faults
        match CONSECUTIVE handoffs from their start seq (the
        ``corrupt_wire`` rule). ``kill_mid_handoff`` dies here —
        snapshot extracted, survivor never reached."""
        for rec in self._faults:
            if rec["kind"] == "handoff_kill" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "handoff_kill"))
                os._exit(1)      # died mid-migration
        for rec in self._faults:
            if rec["kind"] == "handoff_slow" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "handoff_slow"))
                time.sleep(rec["seconds"])
                break
        for rec in self._faults:
            if rec["kind"] == "handoff_corrupt" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "handoff_corrupt"))
                if frame:
                    return frame[:-1] + bytes([frame[-1] ^ 0x01])
        return frame

    def on_transfer_send(self, seq, frame):
        """Called once per prefill→decode transfer DELIVERY attempt
        (``seq`` counts from 1 per engine) with the sealed frame;
        returns the list of frames that actually arrive at the decode
        peer: ``[frame]`` (clean), ``[]`` (dropped in flight),
        ``[frame, frame]`` (duplicated — the receiver-side
        exactly-once guard's fodder). ``slow_transfer`` sleeps first.
        Transfer numbers never repeat, so all three faults match
        CONSECUTIVE deliveries from their start seq (the
        ``corrupt_wire`` rule)."""
        for rec in self._faults:
            if rec["kind"] == "transfer_slow" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "transfer_slow"))
                time.sleep(rec["seconds"])
                break
        for rec in self._faults:
            if rec["kind"] == "transfer_drop" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "transfer_drop"))
                return []
        for rec in self._faults:
            if rec["kind"] == "transfer_dup" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "transfer_dup"))
                return [frame, frame]
        return [frame]

    def on_observe(self, seq, name=None):
        """Called by the autoscaler for each replica it observes in
        pass ``seq`` (counting from 1 per supervisor). True marks
        this replica's observation STALE (``stale_heartbeat``).
        Observation passes never repeat, so matching is consecutive
        (the ``corrupt_wire`` rule); a fault armed with a ``name``
        only fires for that replica."""
        for rec in self._faults:
            if rec["kind"] != "hb_stale" or rec["times"] <= 0 \
                    or int(seq) < rec["step"]:
                continue
            want = rec.get("name")
            if want is not None and name is not None \
                    and str(want) != str(name):
                continue
            rec["times"] -= 1
            self.fired.append((int(seq), "hb_stale"))
            return True
        return False

    def on_spawn(self, seq):
        """Called at the start of replica spawn number ``seq``
        (counting from 1 per supervisor). Sleeps for an armed
        ``slow_spawn``; returns True when an armed
        ``flapping_replica`` dooms this spawn (crash right after
        admission). Spawn numbers never repeat — consecutive
        matching, like ``on_wire_send``."""
        for rec in self._faults:
            if rec["kind"] == "slow_spawn" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "slow_spawn"))
                time.sleep(rec["seconds"])
                break
        for rec in self._faults:
            if rec["kind"] == "flap" and rec["times"] > 0 \
                    and int(seq) >= rec["step"]:
                rec["times"] -= 1
                self.fired.append((int(seq), "flap"))
                return True
        return False

    def on_fingerprint(self, step, model):
        """Called right before the step-N cross-replica fingerprint is
        computed; a ``diverge_at`` fault mutates the first floating
        parameter in place (no exception — silent divergence)."""
        rec = self._take("diverge", step)
        if rec is None:
            return
        for t in model.get_states().values():
            arr = getattr(t, "data", None)
            if arr is not None and jnp.issubdtype(
                    jnp.asarray(arr).dtype, jnp.floating):
                t.data = jnp.asarray(arr) + jnp.asarray(
                    rec["eps"], jnp.asarray(arr).dtype)
                return


class _NullPlan(FaultPlan):
    """Hook no-ops for the common no-faults case."""

    def on_step(self, step, attempt=0):
        pass

    def on_batch(self, step, batch):
        return batch

    def on_data(self, step):
        pass

    def on_sample(self, index, path):
        pass

    def on_saved(self, step):
        pass

    def on_heartbeat(self, seq):
        pass

    def on_ack(self, step):
        pass

    def on_submit(self, seq):
        pass

    def on_admit(self, req_id):
        return False

    def on_wire_send(self, seq, payload):
        return payload

    def on_handoff_send(self, seq, frame):
        return frame

    def on_transfer_send(self, seq, frame):
        return [frame]

    def on_observe(self, seq, name=None):
        return False

    def on_spawn(self, seq):
        return False

    def on_fingerprint(self, step, model):
        pass


NULL_PLAN = _NullPlan()


# -- on-disk checkpoint chaos ----------------------------------------------

def _step_dir(directory, step):
    root = os.path.join(str(directory), str(int(step)))
    if not os.path.isdir(root):
        raise FileNotFoundError(f"no checkpoint step dir at {root}")
    return root


def truncate_checkpoint(directory, step):
    """Truncate every file under checkpoint ``step`` to half its size —
    the classic torn write a preemption leaves behind. Returns the
    number of files damaged."""
    root = _step_dir(directory, step)
    count = 0
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            path = os.path.join(dirpath, fn)
            size = os.path.getsize(path)
            with open(path, "r+b") as f:
                f.truncate(size // 2)
            count += 1
    return count


def corrupt_checkpoint(directory, step, byte=0xFF):
    """Overwrite the head of every file under checkpoint ``step`` with
    garbage (bit-rot / partial overwrite). Returns files damaged."""
    root = _step_dir(directory, step)
    count = 0
    for dirpath, _dirs, files in os.walk(root):
        for fn in files:
            path = os.path.join(dirpath, fn)
            size = os.path.getsize(path)
            if size == 0:
                continue
            with open(path, "r+b") as f:
                f.write(bytes([byte]) * min(1024, size))
            count += 1
    return count


def bitflip_checkpoint(directory, step, nbits=1):
    """Flip ``nbits`` single bits mid-file in every DATA chunk store
    under checkpoint ``step`` — the realistic SDC shape: metadata and
    manifests are untouched (damaging those makes orbax's own parser
    raise, which is the easy case), so the checkpoint still loads
    cleanly and only the tensor BYTES are wrong. Nothing but a content
    digest can catch it. Orbax keeps redundant chunk stores (plain +
    ocdbt), so every copy is damaged; flips land in the back half of
    each file, away from any leading format header. Returns the list
    of damaged file paths."""
    root = _step_dir(directory, step)

    def _scan(skip_meta):
        out = []
        for dirpath, _dirs, files in os.walk(root):
            for fn in files:
                # metadata/manifest files fail PARSING when damaged —
                # orbax catches that itself. The SDC shape under test
                # is a flip in the raw tensor bytes (the d/ chunk
                # stores), which only a content digest can see.
                if skip_meta and (fn.startswith("_")
                                  or "manifest" in fn
                                  or fn.endswith(".json")):
                    continue
                path = os.path.join(dirpath, fn)
                if os.path.getsize(path) > 0:
                    out.append(path)
        return out

    targets = _scan(skip_meta=True) or _scan(skip_meta=False)
    if not targets:
        raise FileNotFoundError(f"no file to bit-flip under {root}")
    for path in targets:
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            for i in range(int(nbits)):
                off = size // 2 + (i * 97) % max(1, size - size // 2)
                f.seek(off)
                b = f.read(1)
                f.seek(off)
                f.write(bytes([(b[0] if b else 0) ^ 0x01]))
    return targets
