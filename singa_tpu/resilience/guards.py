"""NaN/divergence step guards with dynamic loss scaling.

:class:`GuardedOptimizer` wraps any optimizer (plain ``Optimizer`` or
``DistOpt``) and replaces its plain training driver with a guarded one.
Everything happens ON DEVICE, inside the compiled step the Model layer
traces — no per-gradient host readbacks:

- the loss is seeded into backward pre-multiplied by the optimizer's
  ``loss_scale`` (a power of two: bit-exact for in-range f32 grads, and
  the classic underflow shield for fp16/bf16), gradients are unscaled
  before use;
- one global grad-norm accumulates across parameters; a step is *bad*
  when the loss or that norm is non-finite (or exceeds the configured
  divergence ceilings);
- the whole state update — params, momentum/moments, step counter — is
  computed and then masked with ``where(ok, new, old)``, so a bad step
  is a no-op on every state tensor: an injected NaN can never land in
  the parameters;
- forward-mutated model state the optimizer never sees (BatchNorm
  running statistics — rebound from the batch BEFORE the guard runs)
  is covered by *shadow* tensors holding each stat's value as of the
  last good step: on a bad step the stat is restored from its shadow,
  so poisoned batch statistics cannot leak into eval/checkpoints
  either. Shadows are threaded state (checkpointed under
  ``guard-shadow/``); the Model layer materialises them before the
  step compiles (``bind_model``/``materialize_shadows``);
- on a bad step the loss scale backs off; after ``growth_interval``
  consecutive good steps it grows back (dynamic loss scaling).

The guard's own counters (bad/good streak, total skipped, last grad
norm) are scalar state tensors: they thread through the compiled step
like optimizer aux, persist through every checkpoint route under the
``guard/`` prefix, and cost the host exactly ONE scalar readback per
step to inspect (``bad_streak_value`` — what ``ResilientTrainer`` polls
to decide rollback).

Under a ``DistOpt`` the badness verdict is derived from the all-reduced
gradients (plus an all-reduced loss-badness flag), so every mesh shard
agrees on skip-vs-apply and replicated state cannot fork.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import autograd
from ..opt import DistOpt
from ..tensor import Tensor


def _scalar(value=0.0, name=None):
    t = Tensor(shape=(), dtype=jnp.float32, requires_grad=False)
    t.data = jnp.asarray(float(value), jnp.float32)
    t.name = name
    return t


class GuardedOptimizer:
    """Skip-bad-steps wrapper around an optimizer (see module docstring).

    Only the plain driving path (``optimizer(loss)`` /
    ``backward_and_update``) is guarded; the specialised DistOpt drivers
    (``backward_and_update_half``, sparse/partial variants) pass through
    unguarded via attribute delegation.

    ``dynamic_loss_scale=False`` pins the scale (skip-step and streak
    accounting still run — the pure-guard mode for f32 training).
    """

    def __init__(self, optimizer, *, dynamic_loss_scale=True,
                 init_scale=1.0, growth_factor=2.0, backoff_factor=0.5,
                 growth_interval=2000, min_scale=2.0 ** -14,
                 max_scale=2.0 ** 24, max_loss=None, max_grad_norm=None):
        self.inner = optimizer
        self.dynamic_loss_scale = bool(dynamic_loss_scale)
        self.growth_factor = float(growth_factor)
        self.backoff_factor = float(backoff_factor)
        self.growth_interval = int(growth_interval)
        self.min_scale = float(min_scale)
        self.max_scale = float(max_scale)
        self.max_loss = max_loss
        self.max_grad_norm = max_grad_norm
        self.opt.loss_scale.data = jnp.asarray(float(init_scale),
                                               jnp.float32)
        self.bad_streak = _scalar(name="guard/bad_streak")
        self.good_streak = _scalar(name="guard/good_streak")
        self.skipped_total = _scalar(name="guard/skipped_total")
        self.last_grad_norm = _scalar(name="guard/last_grad_norm")
        self._model = None
        self._shadows = {}      # model-state name -> shadow Tensor

    @classmethod
    def for_policy(cls, optimizer, policy):
        """The default companion of a 16-bit precision policy
        (``Model.compile(policy=...)`` wraps a plain optimizer through
        here): dynamic loss scaling ON, started at the policy's default
        scale — 2^15 for float16 compute (the classic underflow shield),
        neutral 1.0 for bfloat16 (same exponent range as f32; the
        dynamic backoff/growth machinery stays armed against the
        occasional overflow/NaN step). An optimizer the user already
        wrapped keeps its own configuration and never passes through
        here."""
        return cls(optimizer, dynamic_loss_scale=True,
                   init_scale=policy.default_loss_scale)

    # -- forward-mutated state shadows ------------------------------------
    def bind_model(self, model):
        """Called by Model.set_optimizer: lets the guard see model state
        the optimizer never touches (BN running stats)."""
        self._model = model

    def _shadowable_states(self):
        if self._model is None:
            return
        opt_ids = {id(t) for t in self.inner.state_tensors()}
        for name, t in self._model.get_states().items():
            # trainable params (requires_grad) are masked via their
            # gradient pairs; everything else is forward-mutated state
            if not t.requires_grad and id(t) not in opt_ids:
                yield name, t

    def materialize_shadows(self):
        """Create shadow tensors from the CURRENT (pre-step, concrete)
        values — Model._ensure_state calls this right before the step
        compiles, so shadows are threaded through it like any state."""
        import jax
        for name, t in self._shadowable_states():
            if name not in self._shadows and \
                    not isinstance(t.data, jax.core.Tracer):
                # a DISTINCT buffer: the live tensor and its shadow are
                # both donated step state, and XLA rejects donating the
                # same buffer twice
                sh = Tensor(data=jnp.array(t.data, copy=True),
                            device=t.device, requires_grad=False)
                sh.spec = t.spec
                sh.name = f"guard-shadow/{name}"
                self._shadows[name] = sh

    # -- plumbing ----------------------------------------------------------
    @property
    def opt(self):
        """The innermost base optimizer (unwraps a DistOpt)."""
        inner = self.inner
        return inner.opt if isinstance(inner, DistOpt) else inner

    def __getattr__(self, name):
        if name == "inner":
            raise AttributeError(name)
        return getattr(self.inner, name)

    def _own_state(self):
        return {"guard/bad_streak": self.bad_streak,
                "guard/good_streak": self.good_streak,
                "guard/skipped_total": self.skipped_total,
                "guard/last_grad_norm": self.last_grad_norm}

    _SHADOW = "guard-shadow/"

    def state_tensors(self):
        return self.inner.state_tensors() + \
            list(self._own_state().values()) + list(self._shadows.values())

    def state_tensor_dict(self):
        d = self.inner.state_tensor_dict()
        d.update(self._own_state())
        d.update({self._SHADOW + k: v for k, v in self._shadows.items()})
        return d

    def _set_shadow(self, name, array, spec=None):
        sh = self._shadows.get(name)
        if sh is None:
            sh = Tensor(data=array, requires_grad=False)
            sh.spec = spec
            sh.name = self._SHADOW + name
            self._shadows[name] = sh
        else:
            sh.data = jnp.asarray(array)

    def restore_state_tensor(self, name, array, spec=None):
        own = self._own_state()
        if name in own:
            own[name].data = jnp.asarray(array)
        elif name.startswith(self._SHADOW):
            self._set_shadow(name[len(self._SHADOW):], array, spec)
        else:
            self.inner.restore_state_tensor(name, array, spec)

    def get_states(self):
        states = self.inner.get_states()
        states.update({k: np.asarray(t.data)
                       for k, t in self._own_state().items()})
        states.update({self._SHADOW + k: np.asarray(t.data)
                       for k, t in self._shadows.items()})
        return states

    def set_states(self, states):
        own = self._own_state()
        rest = {}
        for k, v in states.items():
            if k in own:
                own[k].data = jnp.asarray(v, dtype=jnp.float32)
            elif k.startswith(self._SHADOW):
                self._set_shadow(k[len(self._SHADOW):], np.asarray(v))
            else:
                rest[k] = v
        self.inner.set_states(rest)

    def announce_aux_specs(self, params_by_name):
        self.inner.announce_aux_specs(params_by_name)

    def step(self):
        self.inner.step()

    # -- host-side readbacks ----------------------------------------------
    def bad_streak_value(self) -> int:
        """Consecutive bad (skipped) steps — the ONE scalar the driver
        reads back per step to decide rollback."""
        return int(float(np.asarray(self.bad_streak.data)))

    def stats(self) -> dict:
        return {
            "loss_scale": float(np.asarray(self.opt.loss_scale.data)),
            "bad_streak": int(float(np.asarray(self.bad_streak.data))),
            "good_streak": int(float(np.asarray(self.good_streak.data))),
            "skipped_total": int(float(np.asarray(
                self.skipped_total.data))),
            "grad_norm": float(np.asarray(self.last_grad_norm.data)),
        }

    def record_metrics(self, registry=None):
        """Publish the guard scalars (ONE host readback batch — the
        same five scalars :meth:`stats` reads) into the metrics
        registry as gauges, and return the stats dict. The resilient
        trainer calls this at run finalization and on every blackbox
        dump, NOT per step: the step path keeps its single
        ``bad_streak_value`` readback."""
        from ..observability import metrics as _metrics
        reg = registry if registry is not None \
            else _metrics.default_registry()
        s = self.stats()
        reg.gauge("guard_loss_scale",
                  "current dynamic loss scale").set(s["loss_scale"])
        reg.gauge("guard_skipped_steps_total",
                  "guard-skipped (bad) steps since state creation; a "
                  "gauge because the value rides checkpoints"
                  ).set(s["skipped_total"])
        reg.gauge("guard_last_grad_norm",
                  "global gradient norm of the newest step"
                  ).set(s["grad_norm"])
        reg.gauge("guard_bad_streak",
                  "consecutive guard-flagged bad steps"
                  ).set(s["bad_streak"])
        return s

    def reset_streaks(self, extra_backoff=False):
        """Zero the streak counters (after the driver rolled state back
        to a checkpoint); optionally back the restored loss scale off
        once more so the retried stretch does not re-diverge at the
        scale that just failed."""
        self.bad_streak.data = jnp.zeros((), jnp.float32)
        self.good_streak.data = jnp.zeros((), jnp.float32)
        if extra_backoff and self.dynamic_loss_scale:
            ls = self.opt.loss_scale
            ls.data = jnp.maximum(
                ls.data.astype(jnp.float32) * self.backoff_factor,
                self.min_scale)

    # -- the guarded driver ------------------------------------------------
    def __call__(self, loss):
        self.backward_and_update(loss)

    def backward_and_update(self, loss):
        dist = self.inner if isinstance(self.inner, DistOpt) else None
        base = self.opt
        scale = base.loss_scale.data.astype(jnp.float32)
        loss_arr = loss.data

        # seed backward with the scale so every gradient comes out
        # pre-multiplied (underflow shield); unscale before use
        dy = jnp.full(jnp.shape(loss_arr), scale).astype(loss_arr.dtype)
        inv = 1.0 / scale
        norm_sq = jnp.zeros((), jnp.float32)
        pairs = []
        wire = DistOpt._policy_wire() if dist is not None else None
        # fp8 training (QuantPolicy "fp8_mixed"): gradients are rounded
        # through the e5m2 grid after unscaling — the loss scale is the
        # underflow shield that makes the narrow fp8 mantissa safe, so
        # the quantized-grad path rides THIS driver by design
        from .. import mixed_precision as _mp
        _pol = _mp.active_policy()
        grad_q = getattr(_pol, "grad_quant", None)
        stream = autograd.backward(loss, dy=dy)
        if dist is not None:
            # the reduction rides DistOpt's shared chokepoint
            # (grad_reduce_stream): per-grad streaming psums by default
            # — issued as backward yields, so XLA overlaps them with
            # remaining backward compute — or the bucketed/no-overlap
            # form when the DistOpt is configured for it; under a
            # 16-bit policy the wire carries the policy's comm dtype
            # (the unscale below is f32 either way)
            stream = dist.grad_reduce_stream(stream, wire=wire)
        for p, g in stream:
            arr = g.data
            excl = dist._shard_axes(p) if dist is not None else ()
            if dist is not None:
                arr = arr / dist.communicator.effective_world_size()
            arr = arr.astype(jnp.float32) * inv
            if grad_q is not None:
                from ..quant.core import fake_cast
                # e5m2 grad emulation, post-unscale: the norm below and
                # the applied update both see the quantized values, so
                # the badness verdict judges what actually lands
                arr = fake_cast(arr, grad_q)
            contrib = jnp.sum(arr * arr)
            if excl:
                # a shard-excluded param (expert/tensor-parallel) holds a
                # DISTINCT grad slice per shard: sum its norm contribution
                # over those axes, or shards would compute different
                # verdicts from the same step and fork replicated state
                from ..parallel.communicator import active_axis
                axes = tuple(a for a in excl if active_axis(a))
                if axes:
                    import jax
                    contrib = jax.lax.psum(contrib, axes)
            norm_sq = norm_sq + contrib
            g.data = arr.astype(p.dtype)
            pairs.append((p, g))

        # badness verdict — on device, replicated-consistent: a NaN loss
        # on ONE shard must skip the step on ALL shards, so the loss
        # flag rides an all-reduce (grad badness already does, through
        # the summed gradients feeding norm_sq)
        loss_bad = 1.0 - jnp.all(jnp.isfinite(
            loss_arr.astype(jnp.float32))).astype(jnp.float32)
        if self.max_loss is not None:
            loss_bad = jnp.maximum(loss_bad, jnp.any(
                loss_arr.astype(jnp.float32) > self.max_loss
            ).astype(jnp.float32))
        if dist is not None:
            loss_bad = dist.all_reduce(loss_bad)
        norm_ok = jnp.isfinite(norm_sq)
        if self.max_grad_norm is not None:
            norm_ok = jnp.logical_and(
                norm_ok, norm_sq <= float(self.max_grad_norm) ** 2)
        ok = jnp.logical_and(loss_bad == 0.0, norm_ok)

        # run the full update, then mask EVERY touched state tensor so a
        # bad step is a perfect no-op (fresh aux born this step masks
        # back to its zero init)
        before = {id(t): (t, t.data) for t in self.inner.state_tensors()}
        for p, _g in pairs:
            before.setdefault(id(p), (p, p.data))
        for p, g in pairs:
            base.apply(p.name or f"param/{id(p)}", p, g)
        base.step()
        for t, old in before.values():
            if t.data is not old:
                t.data = jnp.where(ok, t.data, old)
        for t in self.inner.state_tensors():
            if id(t) not in before:
                t.data = jnp.where(ok, t.data, jnp.zeros_like(t.data))

        # forward-mutated model state (BN running stats) was rebound
        # from the batch BEFORE this guard ran, so its pre-step value is
        # gone from the live tensor — restore from the shadow (its value
        # as of the last good step), then refresh the shadow
        for name, t in self._shadowable_states():
            sh = self._shadows.get(name)
            if sh is None:
                continue    # not materialized yet (abstract rehearsal)
            t.data = jnp.where(ok, t.data, sh.data.astype(t.dtype))
            sh.data = t.data.astype(sh.dtype)

        # guard bookkeeping (outside the mask: streaks must advance on
        # bad steps — that is their whole point)
        okf = ok.astype(jnp.float32)
        bad = self.bad_streak.data
        good = self.good_streak.data
        self.bad_streak.data = jnp.where(ok, 0.0, bad + 1.0)
        self.good_streak.data = jnp.where(ok, good + 1.0, 0.0)
        self.skipped_total.data = self.skipped_total.data + (1.0 - okf)
        self.last_grad_norm.data = jnp.sqrt(norm_sq)
        if self.dynamic_loss_scale:
            grown = jnp.where(
                jnp.mod(good + 1.0, float(self.growth_interval)) == 0.0,
                scale * self.growth_factor, scale)
            new_scale = jnp.where(ok, grown, scale * self.backoff_factor)
            base.loss_scale.data = jnp.clip(new_scale, self.min_scale,
                                            self.max_scale)
