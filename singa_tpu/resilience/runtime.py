"""The fault-tolerant training driver (checkpoint-restart loop).

:class:`ResilientTrainer` owns the loop a production TPU job needs
around ``model(tx, ty)``:

- **Preemption**: SIGTERM/SIGINT set a flag; at the next step boundary
  the trainer checkpoints synchronously and exits with
  :data:`EXIT_PREEMPTED` (75, BSD's EX_TEMPFAIL: "transient — retry").
  The restart supervisor contract is: exit code 75 means *restart me*;
  the restarted trainer resumes from the preemption checkpoint with
  bit-identical state (params, optimizer aux, loss-scale, guard
  counters all ride the checkpoint).
- **Transient failures**: step exceptions and data-iterator exceptions
  retry with exponential backoff + deterministic jitter; an optional
  watchdog runs each step on a worker thread. A step that overruns the
  timeout gets one grace period: finishing late is used as-is, a step
  that raised late is retried, and a step STILL running after the grace
  raises a fatal :class:`StepTimeoutError` — a hung backend cannot be
  retried in-process (the zombie thread could land its update mid-retry),
  so the supervisor restart from checkpoint is the recovery.
- **Divergence**: when the model's optimizer is a
  :class:`~singa_tpu.resilience.guards.GuardedOptimizer`, the trainer
  polls its bad-streak counter (one scalar readback) and, after
  ``rollback_after`` consecutive bad steps, rolls state back to the
  last good checkpoint and continues (bounded by ``max_rollbacks``).
- **Restart**: every ``run`` begins with
  ``CheckpointManager.restore_latest``, which itself scans backward
  past corrupt/incomplete checkpoints (singa_tpu/checkpoint.py).

Usage::

    trainer = ResilientTrainer(model, "ckpts", save_interval_steps=50)
    summary = trainer.run(batches, num_steps=10_000)

where ``batches`` is any (re-)iterable yielding the positional args of
one training step (tuples of Tensors). Exhausted re-iterables
re-iterate (epoch wrap); endless generators work as-is; a FINITE
one-shot generator that runs dry mid-training raises a clear error
(it cannot be rewound).
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
import warnings

from .. import data as _data_mod
from ..checkpoint import CheckpointManager, DistributedCheckpointManager
from ..integrity import replica_buffer_mismatches, state_fingerprint
from ..observability import metrics as _metrics
from ..observability import perf as _perf
from ..observability import spans as _spans
from .cluster import BarrierTimeout, MembershipError
from .faults import NULL_PLAN
from .guards import GuardedOptimizer

# BSD EX_TEMPFAIL: the documented "preempted — checkpointed cleanly,
# restart me" exit code for the restart supervisor. Distinct from 0
# (done), 1 (crash), and 42-style user codes.
EXIT_PREEMPTED = 75

# Repeated cross-replica divergence (silent data corruption or a
# non-deterministic kernel) after quarantine-and-rollback already
# retried: DISTINCT from 75 because "relaunch the same command" is the
# wrong medicine — the fleet should cordon/replace the suspect host
# before restarting (resume still works: every committed checkpoint is
# cross-replica-agreed). 76 is BSD EX_PROTOCOL — "remote said the
# impossible" is close enough in spirit to a replica whose bytes
# disagree with its peers'.
EXIT_DIVERGED = 76


class DivergenceError(RuntimeError):
    """Replicas diverged again after quarantine-and-rollback — the
    supervisor contract is exit :data:`EXIT_DIVERGED` (76): investigate
    or cordon the divergent host, THEN relaunch (resume lands on the
    last cross-replica-agreed checkpoint)."""

    def __init__(self, step, divergent, rollbacks):
        self.step = int(step)
        self.divergent = list(divergent)
        super().__init__(
            f"cross-replica divergence at step {step} persisted after "
            f"{rollbacks} quarantine-rollback(s)"
            + (f" (divergent: {self.divergent})" if divergent else "")
            + f"; exiting {EXIT_DIVERGED} — cordon the suspect host "
            "before restarting")


class StepTimeoutError(RuntimeError):
    """A training step exceeded the watchdog timeout.

    Carries the worker thread plus its result/exception slots so the
    driver can decide safely: a LATE completion within the grace join
    is used as-is; a still-running worker makes the timeout fatal —
    retrying while a zombie step can still land its (state-mutating)
    update would race on the shared tensors."""

    def __init__(self, message, worker=None, result=None, raised=None):
        super().__init__(message)
        self.worker = worker
        self.result = result if result is not None else {}
        self.raised = raised if raised is not None else []


class _Preempted(Exception):
    """Internal control flow: a preemption checkpoint has committed."""


class ResilientTrainer:
    """Checkpoint-restart training loop (see module docstring).

    Parameters beyond the obvious:

    - ``step_retries`` / ``data_retries``: transient-failure retry
      budgets per step / per batch fetch.
    - ``backoff_base`` / ``backoff_cap`` / ``jitter``: retry delay is
      ``min(cap, base * 2**attempt) * (1 + jitter*u)`` with ``u`` drawn
      from a seeded RNG — exponential backoff, deterministic jitter.
    - ``step_timeout``: seconds before a step is declared overdue; one
      grace period follows (late success used, late failure retried,
      still-hung fatal). None disables the watchdog thread.
    - ``rollback_after``: consecutive guard-flagged bad steps before
      state rolls back to the last checkpoint (None disables; requires
      a GuardedOptimizer to ever trigger).
    - ``exit_on_preempt``: raise ``SystemExit(EXIT_PREEMPTED)`` after
      the preemption checkpoint (the supervisor contract); False makes
      ``run`` return its summary with ``preempted=True`` instead (for
      embedding in a larger host process).
    - ``faults``: a FaultPlan for chaos testing.
    - ``cluster``: a :mod:`~singa_tpu.resilience.cluster` member. When
      given, checkpoints go through the two-phase
      :class:`~singa_tpu.checkpoint.DistributedCheckpointManager`
      (commit marker only after every rank's ACK), cluster health is
      checked at every step boundary, and a lost peer (or a failed
      start rendezvous) exits :data:`EXIT_PREEMPTED` — membership loss
      is RECOVERABLE: the supervisor restarts at the smaller world size
      and ``run`` resumes from the last *committed* checkpoint,
      re-sharded onto the new mesh.
    - ``manifest_extra``: dict recorded in every commit marker (e.g.
      ``per_replica_batch`` — the elastic batch accounting reads it on
      resume, see ``parallel.communicator.rescale_batch``).
    - ``fingerprint_every``: every N steps, fingerprint the full model
      + optimizer state and check that replicas agree — bit-exactly:
      per-device buffer comparison locally
      (:func:`~singa_tpu.integrity.replica_buffer_mismatches`) and a
      digest exchange over the cluster for multi-rank runs
      (:meth:`~singa_tpu.resilience.cluster.ClusterBase.
      fingerprint_agree`). A disagreement means silent divergence (SDC,
      non-deterministic kernel): the step is QUARANTINED — never
      checkpointed — and state rolls back to the last *verified,
      cluster-agreed* checkpoint. 0 (the default) disables the check
      entirely: zero added work on the step path.
    - ``max_divergence_rollbacks``: quarantine-rollbacks allowed before
      the run exits :data:`EXIT_DIVERGED` (76) — repeated divergence
      means bad hardware, and "restart the same pod" is not a fix.
    - ``profile_every``: every N steps, run the step under a
      ``jax.profiler`` trace (``Model.profile_step``) and refresh the
      ``profile_fusion_*`` gauges — the continuous per-fusion view the
      MFU work reads. 0 (the default) disables sampling; non-sample
      steps pay one integer check, and the compiled step's
      ``n_traces`` pin is untouched (the profiler wraps the
      already-compiled dispatch).
    - ``anomaly_factor`` / ``anomaly_sustain`` / ``anomaly_warmup``:
      arm the step-time anomaly sentinel — ``anomaly_sustain``
      consecutive steps slower than ``anomaly_factor``× the rolling
      baseline fire an attributed ``step_anomaly`` event, a one-shot
      profile capture on the next step, and a blackbox dump. None
      (the default) disables the sentinel.
    - ``aot``: cold-start elimination (``singa_tpu.aot``). ``True``
      keeps an ``aot/`` sidecar beside the checkpoints (a path keeps
      it there instead): the persistent compilation cache is
      installed under ``<aot>/xla-cache``, the compiled train step is
      exported after the first step (single-device models; a
      mesh-sharded step rides the cache alone), and a restarted
      worker's restore path deserializes a MATCHING artifact instead
      of retracing — any mismatch (version, topology, avals, digest,
      policy) falls back to a loud fresh compile and quarantines the
      stale artifact. The run summary reports ``compile_sources``
      (observations per ``compile_seconds`` source label) and
      ``aot`` (per-program outcomes), the chaos ``warm-restart``
      gate's evidence. None (the default) changes nothing.
    """

    def __init__(self, model, ckpt_dir, *, max_to_keep=3,
                 save_interval_steps=1, step_retries=3, data_retries=3,
                 backoff_base=0.1, backoff_cap=5.0, jitter=0.25,
                 step_timeout=None, rollback_after=3, max_rollbacks=3,
                 exit_on_preempt=True, install_signal_handlers=True,
                 faults=None, seed=0, verbose=True, cluster=None,
                 commit_timeout=60.0, start_barrier_timeout=60.0,
                 preempt_commit_timeout=10.0, manifest_extra=None,
                 fingerprint_every=0, max_divergence_rollbacks=2,
                 telemetry_dir=None, profile_every=0,
                 anomaly_factor=None, anomaly_sustain=3,
                 anomaly_warmup=10, aot=None):
        self.model = model
        self.cluster = cluster
        self._rank = cluster.rank if cluster is not None else 0
        # flight-recorder blackbox home (``blackbox-<rank>.jsonl``):
        # beside the checkpoints unless the caller routes it elsewhere
        self.telemetry_dir = os.path.abspath(str(
            telemetry_dir if telemetry_dir is not None
            else os.path.join(str(ckpt_dir), "telemetry")))
        self.start_barrier_timeout = float(start_barrier_timeout)
        self.preempt_commit_timeout = float(preempt_commit_timeout)
        if cluster is not None:
            self.mgr = DistributedCheckpointManager(
                ckpt_dir, cluster, max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                commit_timeout=commit_timeout,
                manifest_extra=manifest_extra)
        else:
            self.mgr = CheckpointManager(
                ckpt_dir, max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps)
        self.step_retries = int(step_retries)
        self.data_retries = int(data_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        self.step_timeout = step_timeout
        self.rollback_after = rollback_after
        self.max_rollbacks = int(max_rollbacks)
        self.fingerprint_every = int(fingerprint_every)
        self.max_divergence_rollbacks = int(max_divergence_rollbacks)
        self.exit_on_preempt = bool(exit_on_preempt)
        self.install_signal_handlers = bool(install_signal_handlers)
        self.faults = faults if faults is not None else NULL_PLAN
        if cluster is not None and \
                getattr(cluster, "faults", NULL_PLAN) is NULL_PLAN:
            # one plan drives every hook point: a caller that armed
            # kill_before_ack on the trainer's plan gets it fired from
            # the cluster's ack path too
            cluster.faults = self.faults
        self.verbose = bool(verbose)
        self._rng = random.Random(seed)
        self._sleep = time.sleep          # injectable in tests
        self._preempt_signal = None
        self._data = None
        self._it = None
        # telemetry handles (get-or-create on the process registry):
        # every operation below is a host-side dict update — the
        # compiled step path (and its n_traces pin) is untouched
        reg = _metrics.default_registry()
        self._m_steps = reg.counter(
            "train_steps_total", "completed training steps")
        self._m_step_time = reg.histogram(
            "train_step_seconds", "wall-clock duration of one step")
        self._m_fetch = reg.histogram(
            "data_fetch_seconds", "wall-clock wait for the next batch")
        self._m_throughput = reg.gauge(
            "train_throughput_samples_per_sec",
            "samples/s of the newest step (batch dim0 / step seconds)")
        self._m_mfu = reg.gauge(
            "train_mfu", "achieved/peak FLOP fraction of the newest "
            "step (needs a cached XLA cost analysis and a known chip)")
        self._m_retries = reg.counter(
            "train_retries_total", "transient-failure retries",
            labels=("kind",))
        self._m_timeouts = reg.counter(
            "train_step_timeouts_total", "watchdog-overdue steps")
        self._m_rollbacks = reg.counter(
            "train_rollbacks_total",
            "state rollbacks to a checkpoint", labels=("kind",))
        self._m_bad_streak = reg.gauge(
            "guard_bad_streak", "consecutive guard-flagged bad steps")
        self._m_first_step = reg.gauge(
            "restart_to_first_step_seconds",
            "run() entry to first completed step — the cold-start "
            "regression gate (compile + restore + first batch)")
        self._step_flops = None       # resolved lazily after step 1
        self._last_blackbox = None
        self._cur_step = None
        # performance observability: the sampling profiler always
        # exists (the sentinel arms one-shot captures through it even
        # at profile_every=0); the sentinel only when asked for
        self._profiler = _perf.SamplingProfiler(profile_every)
        self._step_was_profiled = False
        self._sentinel = _perf.AnomalySentinel(
            factor=anomaly_factor, sustain=anomaly_sustain,
            warmup=anomaly_warmup) if anomaly_factor else None
        # cold-start elimination: persistent compile cache + AOT
        # train-step artifacts in an aot/ sidecar beside the
        # checkpoints (class docstring)
        self._aot_store = None
        if aot:
            from ..aot import cache as _aot_cache
            from ..aot import export as _aot_export
            aot_dir = os.path.join(str(ckpt_dir), "aot") \
                if aot is True else os.path.abspath(str(aot))
            _aot_cache.install(_aot_cache.cache_dir_for(aot_dir))
            self._aot_store = _aot_export.AotStore(aot_dir)
            # Model._run_step consults the store before tracing a
            # fresh signature (the warm-restart load path)
            model._aot_store = self._aot_store

    # -- logging -----------------------------------------------------------
    def _log(self, msg):
        if self.verbose:
            print(f"[resilient] {msg}", flush=True)

    # -- signal handling ---------------------------------------------------
    def _handler(self, signum, frame):
        # only record: all real work (sync checkpoint, exit) happens at
        # the next step boundary, never inside the handler
        self._preempt_signal = signum

    def _install_handlers(self):
        if not self.install_signal_handlers:
            return None
        try:
            prev = {s: signal.signal(s, self._handler)
                    for s in (signal.SIGTERM, signal.SIGINT)}
        except ValueError:
            # signal.signal only works on the main thread; degrade to
            # no preemption handling rather than refusing to train
            warnings.warn(
                "ResilientTrainer: not on the main thread, preemption "
                "signal handlers NOT installed", stacklevel=3)
            return None
        return prev

    @staticmethod
    def _restore_handlers(prev):
        if prev:
            for s, h in prev.items():
                signal.signal(s, h)

    def _check_preempt(self, completed_step, start):
        """At a step boundary: if a preemption signal arrived, commit a
        synchronous checkpoint of the completed step and stop."""
        if self._preempt_signal is None:
            return
        signame = signal.Signals(self._preempt_signal).name
        if completed_step >= start:
            if self.mgr.latest_step() != completed_step:
                if isinstance(self.mgr, DistributedCheckpointManager):
                    # a forced off-schedule save only reaches quorum
                    # when EVERY rank was preempted at this boundary
                    # (whole-pod maintenance — the common TPU case); a
                    # per-node preemption cannot commit, so wait only
                    # briefly and leave resume to the last committed
                    # step rather than eating the kill grace
                    ok = self.mgr.save(
                        completed_step, self.model, force=True,
                        commit_timeout=self.preempt_commit_timeout,
                        data_state=self._data_state())
                    if not ok:
                        self._log(
                            f"{signame}: preemption checkpoint of step "
                            f"{completed_step} did not commit; resume "
                            "will use the last committed step")
                else:
                    self.mgr.save(completed_step, self.model,
                                  force=True,
                                  data_state=self._data_state())
            self.mgr.wait()     # synchronous: the bytes must be down
            self._log(f"{signame}: checkpointed step {completed_step}, "
                      f"exiting {EXIT_PREEMPTED} for the supervisor")
        else:
            self._log(f"{signame} before any step completed; "
                      f"exiting {EXIT_PREEMPTED} without a checkpoint")
        raise _Preempted()

    # -- retry plumbing ----------------------------------------------------
    def _backoff(self, attempt, what, summary, kind):
        from ..data import backoff_delay
        delay = backoff_delay(attempt, self.backoff_base,
                              self.backoff_cap, self.jitter, self._rng)
        summary[kind] += 1
        self._m_retries.inc(kind=kind)
        self._log(f"{what}: transient failure, retrying "
                  f"in {delay * 1e3:.0f} ms "
                  f"(attempt {attempt + 1})")
        self._sleep(delay)

    def _next_batch(self, step, summary):
        attempt = 0
        failed = None
        while True:
            try:
                self.faults.on_data(step)
                if self._it is None:
                    self._it = iter(self._data)
                try:
                    batch = next(self._it)
                except StopIteration:
                    self._it = iter(self._data)   # epoch wrap
                    batch = next(self._it)
                if not isinstance(batch, (tuple, list)):
                    batch = (batch,)
                self._yielded_any = True
                return self.faults.on_batch(step, tuple(batch))
            except StopIteration:
                # a generator that raised is CLOSED, not exhausted:
                # this StopIteration is the corpse of the retried
                # failure — the ONE shared rule
                # (data.raise_retried_failure, also the
                # RetryingIterator.__next__ rule) surfaces the real
                # error instead of truncating the stream
                _data_mod.raise_retried_failure(failed)
                if getattr(self, "_yielded_any", False):
                    raise RuntimeError(
                        "data source is exhausted and not re-iterable "
                        "(a one-shot generator?); pass a re-iterable "
                        "like NumpyBatchIter, or an endless generator"
                    ) from None
                raise RuntimeError(
                    "data source yielded no batches") from None
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as e:
                if attempt >= self.data_retries:
                    raise
                failed = e
                self._backoff(attempt, f"data fetch (step {step})",
                              summary, "data_retries")
                attempt += 1

    # a profiled step's wall-clock is dominated by the trace dump +
    # parse, not the step: its watchdog budget scales by this factor so
    # routine sampling can never trip a spurious (or fatal) timeout
    PROFILE_TIMEOUT_FACTOR = 4

    def _call_step(self, step, batch, attempt):
        """One step attempt: fault hooks + the model call, optionally
        under the watchdog thread."""
        # cleared per ATTEMPT, not per observe: a profiled attempt that
        # dies before _observe_step must not leak its flag onto the
        # next successful step (which would silently drop that step
        # from the step-time/MFU/sentinel series)
        self._step_was_profiled = False
        will_profile = self._profiler.should_sample(step) and \
            hasattr(self.model, "profile_step")

        def body():
            self.faults.on_step(step, attempt)
            if will_profile:
                # the sampled step runs THROUGH the already-compiled
                # dispatch under a profiler trace (measure_step_fusions)
                # — no retrace, one trace dump, gauges refreshed. The
                # flag keeps its inflated wall-clock (trace dump +
                # parse dominate) OUT of the step-time/MFU/throughput
                # series — its cost lands in profile_capture_seconds
                self._step_was_profiled = True
                t0 = time.perf_counter()
                events = []
                out, table = self.model.profile_step(
                    *batch, record=False, events_out=events)
                # the step-timeline decomposition (timeline_* gauges,
                # exposed-comm, MFU-loss waterfall) rides the same
                # capture; FLOP counts only when someone already paid
                # for a cost analysis (never forced on the step path)
                peak = _metrics.device_peak_flops(getattr(
                    self._jax_device(), "device_kind", None))
                self._profiler.record(
                    step, table, capture_s=time.perf_counter() - t0,
                    events=events, step_flops=self._step_flops,
                    peak_flops=peak)
                return out
            return self.model(*batch)

        if self.step_timeout is None:
            return body()
        timeout = self.step_timeout * \
            (self.PROFILE_TIMEOUT_FACTOR if will_profile else 1)
        result, raised = {}, []
        # carry the caller's contextvars into the worker: a use_layout()
        # scope (ops/layout.py ContextVar) entered around run() must be
        # visible to lazy conv/BN handle init inside the step
        import contextvars
        ctx = contextvars.copy_context()

        def work():
            try:
                result["out"] = ctx.run(body)
            except BaseException as e:     # noqa: BLE001 — re-raised below
                raised.append(e)

        worker = threading.Thread(target=work, daemon=True,
                                  name=f"resilient-step-{step}")
        worker.start()
        worker.join(timeout)
        if worker.is_alive():
            err = StepTimeoutError(
                f"step {step} exceeded the {timeout}s "
                "watchdog timeout"
                + (" (profiled-step budget)" if will_profile else ""),
                worker=worker, result=result, raised=raised)
            err.timeout = timeout   # the grace join reuses this budget
            raise err
        if raised:
            raise raised[0]
        return result.get("out")

    def _run_step(self, step, batch, summary):
        attempt = 0
        while True:
            try:
                return self._call_step(step, batch, attempt)
            except (KeyboardInterrupt, SystemExit):
                raise
            except StepTimeoutError as e:
                # grace-join the overdue worker one more timeout period:
                # a SLOW step that completes in the grace is simply used
                # (its update already landed); a step still running after
                # that is fatal — we cannot retry while a zombie thread
                # may yet land its state mutation concurrently
                summary["step_timeouts"] += 1
                self._m_timeouts.inc()
                grace = getattr(e, "timeout", self.step_timeout)
                e.worker.join(grace)
                if e.worker.is_alive():
                    raise StepTimeoutError(
                        f"step {step} still running after "
                        f"{2 * grace}s; a hung backend "
                        "cannot be retried in-process — exit and let "
                        "the supervisor restart from the checkpoint"
                    ) from None
                if not e.raised:
                    self._log(f"step {step} finished late "
                              "(within the watchdog grace); using it")
                    return e.result.get("out")
                if attempt >= self.step_retries:
                    raise e.raised[0]
                self._backoff(attempt, f"train step {step}",
                              summary, "step_retries")
                attempt += 1
            except Exception:
                if attempt >= self.step_retries:
                    raise
                self._backoff(attempt, f"train step {step}",
                              summary, "step_retries")
                attempt += 1

    # -- data-pipeline state -----------------------------------------------
    def _data_state(self):
        """The data source's ``state_dict()`` (None for a source that
        predates the protocol) — captured at EVERY save so a restored
        checkpoint rewinds the sample stream in lockstep with the
        tensors."""
        sd = getattr(self._data, "state_dict", None)
        return sd() if callable(sd) else None

    def _apply_data_state(self, resume_step):
        """Rewind the data pipeline in LOCKSTEP with a model-state
        restore (run start, guard rollback, divergence quarantine):
        load the restored checkpoint's data state and drop the live
        epoch iterator so the next fetch re-enters the source at the
        loaded offset — the consumed sample sequence stays bit-
        identical to a fault-free run's (exactly-once)."""
        state = getattr(self.mgr, "restored_data_state", None)
        # probe through delegating wrappers (a DevicePrefetcher around
        # a plain generator HAS load_state_dict but nothing to apply it
        # to): not-checkpointable must land on the warning below, not a
        # TypeError mid-restore
        loadable = _data_mod.can_load_state(self._data)
        ld = getattr(self._data, "load_state_dict", None) \
            if loadable else None
        if state is not None and callable(ld):
            self._data.load_state_dict(state)
            self._it = None
            self._data_resumed = True
            self._log(f"data stream rewound to the checkpointed "
                      f"offset (epoch {state.get('epoch')}, "
                      f"position {state.get('position')})")
        elif state is not None:
            warnings.warn(
                "the restored checkpoint carries data-iterator state "
                "but this data source is not checkpointable (no "
                "load_state_dict); the sample stream will NOT resume "
                "where the saved run left off", stacklevel=3)
        elif resume_step and callable(ld):
            warnings.warn(
                f"resumed at step {resume_step} from a checkpoint "
                "without data-iterator state (saved before data-state "
                "capture?); the sample stream restarts from the "
                "iterator's current position — exactly-once is NOT "
                "guaranteed for this resume", stacklevel=3)

    # -- cluster health ----------------------------------------------------
    def _check_cluster(self):
        """At a step boundary: raise MembershipError if a peer (or the
        coordinator) was lost — the run() handler turns it into the
        exit-75 supervisor contract."""
        if self.cluster is not None:
            self.cluster.check()

    # -- flight recorder ---------------------------------------------------
    def _jax_device(self):
        dev = getattr(self.model, "dev", None)
        return getattr(dev, "jax_device", None)

    def _blackbox_dump(self, reason, step=None, error=None):
        """Dump the in-memory flight recorder to
        ``<telemetry_dir>/blackbox-<rank>.jsonl`` — called on every
        ABNORMAL path (preemption, divergence, watchdog kill,
        membership loss, rollback, crash) so a post-mortem shows the
        last N seconds of spans and a final metrics snapshot, not just
        an exit code. A crash/watchdog dump additionally carries the
        HBM stats and a bounded ``jax.live_arrays()`` allocation
        breakdown — the OOM post-mortem. Never raises: losing the
        blackbox must not change how the run dies."""
        try:
            guard = self._guard()
            extra = {"guard": guard.stats()} if guard is not None else {}
            if error is not None:
                extra["error"] = \
                    f"{type(error).__name__}: {error}"[:500]
            if reason in ("crash", "watchdog_kill") or error is not None:
                hbm = _perf.hbm_stats(self._jax_device())
                if hbm:
                    extra["hbm"] = hbm
                live = _perf.live_array_report()
                if live:
                    extra["live_arrays"] = live
            path = os.path.join(self.telemetry_dir,
                                f"blackbox-{self._rank}.jsonl")
            self._last_blackbox = _spans.recorder().dump(
                path, reason, rank=self._rank,
                step=step if step is not None else self._cur_step,
                extra=extra)
            self._log(f"flight recorder dumped to "
                      f"{self._last_blackbox} ({reason})")
        except Exception as e:      # noqa: BLE001 — best-effort by design
            warnings.warn(f"flight-recorder dump failed "
                          f"({type(e).__name__}: {e})", stacklevel=2)

    def _finalize_summary(self, summary):
        """Observability that must survive EVERY exit path (success,
        preemption, membership loss): guard stats, data-pipeline
        flakiness counters, final cluster health."""
        guard = self._guard()
        if guard is not None:
            # one host readback of the guard scalars, recorded as
            # gauges too (loss scale, skipped total, grad norm)
            summary["skipped_steps"] = \
                guard.record_metrics()["skipped_total"]
        if self._last_blackbox is not None:
            summary["blackbox"] = self._last_blackbox
        from ..data import RetryingIterator
        summary["data_resumed"] = bool(getattr(self, "_data_resumed",
                                               False))
        # walk the wrapper chain — DevicePrefetcher (.iterator),
        # RetryingIterator (._src_obj), user staging adapters (.inner)
        # — so retry counters and per-sample quarantine attribution are
        # visible in the run summary no matter how the pipeline is
        # stacked, not just in warnings that scrolled away
        obj, seen = self._data, set()
        while obj is not None and id(obj) not in seen:
            seen.add(id(obj))
            if isinstance(obj, RetryingIterator) and \
                    "data_source" not in summary:
                summary["data_source"] = obj.counters()
            q = getattr(obj, "quarantined", None)
            if q and "data_quarantined" not in summary:
                summary["data_quarantined"] = [dict(r) for r in q]
                summary["data_skipped"] = int(
                    getattr(obj, "skip_count", len(q)))
            obj = next((w for w in (getattr(obj, "_src_obj", None),
                                    getattr(obj, "iterator", None),
                                    getattr(obj, "inner", None))
                        if w is not None), None)
        # cold-start evidence: where this run's executables came from
        # (the warm-restart chaos gate asserts zero "fresh" on a warm
        # path) and the compiled step's trace count — cheap host reads
        summary["compile_sources"] = _perf.compile_source_counts()
        rec = getattr(self.model, "_last_run_rec", None)
        if rec is not None:
            summary["n_traces"] = rec.get("n_traces")
        if self._aot_store is not None:
            summary["aot"] = dict(self._aot_store.outcomes)
        if self.cluster is not None:
            try:
                summary["cluster"] = self.cluster.health()
            except Exception:       # a torn-down cluster is not an error
                pass

    # -- divergence rollback ----------------------------------------------
    def _guard(self):
        opt = getattr(self.model, "optimizer", None)
        return opt if isinstance(opt, GuardedOptimizer) else None

    def _lockstep_restore(self, prefix, step, n):
        """The ONE rollback body both recovery paths (guard-streak
        rollback, fingerprint quarantine) share, so their ordering can
        never drift apart. Rollback must be LOCKSTEP: a rank rewinding
        alone would ack different step numbers forever and no
        checkpoint could ever commit again — a rank whose trigger is
        LOCAL (a hardware fault) strands its peers at the first
        barrier → BarrierTimeout → exit 75 → the supervisor restart is
        the consistent recovery. The resume barrier's name carries the
        resumed step (same agreement rule as the startup resume
        barrier): a rank whose shards fell back FURTHER than its peers
        strands them there instead of training at inconsistent
        parameter versions. Returns the step to resume from."""
        if self.cluster is not None and self.cluster.world > 1:
            with _spans.span("barrier", barrier=f"{prefix}-{step}-{n}"):
                self.cluster.barrier(f"{prefix}-{step}-{n}",
                                     timeout=self.start_barrier_timeout)
        self.mgr.wait()          # never restore under an in-flight save
        with _spans.span("restore", reason=prefix, step=step):
            resume = self.mgr.restore_latest(self.model)
        if self.cluster is not None and self.cluster.world > 1:
            with _spans.span("barrier",
                             barrier=f"{prefix}-resume-{resume}-{n}"):
                self.cluster.barrier(f"{prefix}-resume-{resume}-{n}",
                                     timeout=self.start_barrier_timeout)
        if isinstance(self.mgr, DistributedCheckpointManager):
            # agreement reached: markers at/after the resume point
            # vouch for a timeline about to be re-run
            self.mgr.invalidate_markers_from(resume)
        # the data stream rewinds WITH the tensors — on every rollback
        # and quarantine path, not just at run start: the re-run steps
        # must consume the exact batches the quarantined timeline did
        self._apply_data_state(resume)
        return resume

    def _maybe_rollback(self, step, bad_streak, summary):
        """Returns the step to continue from (rolled back), or None."""
        guard = self._guard()
        if guard is None or self.rollback_after is None:
            return None
        if bad_streak < self.rollback_after:
            return None
        if summary["rollbacks"] >= self.max_rollbacks:
            raise RuntimeError(
                f"training diverged: {self.rollback_after} consecutive "
                f"bad steps after {summary['rollbacks']} rollbacks")
        resume = self._lockstep_restore("rollback", step,
                                        summary["rollbacks"])
        guard.reset_streaks(extra_backoff=True)
        summary["rollbacks"] += 1
        self._m_rollbacks.inc(kind="guard")
        _spans.event("rollback", step=step, resume=resume, kind="guard")
        self._blackbox_dump("rollback", step=step)
        warnings.warn(
            f"{self.rollback_after} consecutive bad steps at step "
            f"{step}; rolled back to checkpoint, resuming at step "
            f"{resume} (rollback {summary['rollbacks']}/"
            f"{self.max_rollbacks})", stacklevel=2)
        return resume

    # -- cross-replica fingerprint: quarantine and rollback ----------------
    def _state_arrays(self):
        from ..checkpoint import _state_tensor_dict
        return {k: t.data
                for k, t in _state_tensor_dict(self.model).items()}

    def _fingerprint_check(self, step, summary):
        """Bit-exact cross-replica agreement on the FULL training state.
        Returns True when every replica agrees; False (with the
        divergents named) quarantines the step."""
        # chaos hook: diverge_at silently perturbs this rank's state —
        # the exact SDC shape the detector exists for
        self.faults.on_fingerprint(step, self.model)
        arrays = self._state_arrays()
        summary["fingerprints"] += 1
        # the agreement round is keyed by the CHECK count, not the step
        # number: in lockstep every rank counts the same rounds, and a
        # step re-run after a rollback opens a fresh round instead of
        # reusing its first run's stale verdict
        seq = summary["fingerprints"]
        divergent = []
        # local front: replicated per-device buffers must be identical
        local = replica_buffer_mismatches(arrays)
        if local:
            divergent += [f"{n}@{d}" for n, ds in local.items()
                          for d in ds]
        # cluster front: every rank's state digest must be identical
        if self.cluster is not None and self.cluster.world > 1:
            fp = state_fingerprint(arrays)
            ok, ranks = self.cluster.fingerprint_agree(
                seq, fp, timeout=self.start_barrier_timeout)
            if not ok:
                divergent += [f"rank{r}" for r in ranks] or ["unknown"]
        if divergent:
            warnings.warn(
                f"step {step}: cross-replica fingerprint mismatch "
                f"({divergent}) — quarantining the step and rolling "
                "back to the last verified checkpoint", stacklevel=2)
            summary["divergent"] = sorted(set(summary["divergent"])
                                          | set(divergent))
            return False
        return True

    def _quarantine_rollback(self, step, summary):
        """A diverged step is never checkpointed; roll every rank back
        (LOCKSTEP, like ``_maybe_rollback``) to the last verified —
        and, under a cluster, cross-replica-AGREED — checkpoint.
        Returns the step to resume from; raises
        :class:`DivergenceError` when the budget is spent."""
        summary["quarantined_steps"] += 1
        if summary["divergence_rollbacks"] >= \
                self.max_divergence_rollbacks:
            raise DivergenceError(step, summary["divergent"],
                                  summary["divergence_rollbacks"])
        # every rank saw the same fp-result broadcast, so all arrive at
        # the lockstep barriers together
        resume = self._lockstep_restore("quarantine", step,
                                        summary["divergence_rollbacks"])
        guard = self._guard()
        if guard is not None:
            guard.reset_streaks()
        summary["divergence_rollbacks"] += 1
        self._m_rollbacks.inc(kind="quarantine")
        _spans.event("quarantine", step=step, resume=resume,
                     divergent=summary["divergent"])
        self._blackbox_dump("quarantine", step=step)
        warnings.warn(
            f"quarantined diverged step {step}; rolled back to the "
            f"last verified checkpoint, resuming at step {resume} "
            f"(divergence rollback {summary['divergence_rollbacks']}/"
            f"{self.max_divergence_rollbacks})", stacklevel=2)
        return resume

    # -- per-step telemetry ------------------------------------------------
    def _observe_step(self, step, step_s, batch, summary, run_t0,
                      first):
        """Host-side step accounting: duration histogram, throughput,
        MFU when an XLA cost analysis is already cached (never forces a
        compile on the step path), HBM gauges (one ``memory_stats``
        read; a no-op off-accelerator after the first probe), the
        anomaly sentinel, and — once per run — the restart-to-
        first-step latency that gates cold-start regressions.

        A PROFILED step's wall-clock is dominated by the trace dump +
        parse, not the step: it still counts in train_steps_total, but
        its duration stays out of the step-time histogram, the
        throughput/MFU gauges, and the sentinel — operators must never
        read the sampling overhead as a performance regression (the
        real sampling cost is profile_capture_seconds)."""
        profiled = getattr(self, "_step_was_profiled", False)
        self._step_was_profiled = False
        self._m_steps.inc()
        if not profiled:
            self._m_step_time.observe(step_s)
        if first:
            lat = time.perf_counter() - run_t0
            summary["first_step_latency_s"] = round(lat, 6)
            self._m_first_step.set(lat)
            _spans.event("first_step", latency_s=lat,
                         resumed_at=summary["start"])
            # resolve the step's flop count ONCE, cheaply: only a cost
            # analysis someone already paid for (verbosity>=2, a prior
            # compiled_step_info/profile_step call) is consulted
            sf = getattr(self.model, "step_flops", None)
            if callable(sf):
                try:
                    self._step_flops = sf(compute=False)
                except Exception:       # audit is best-effort telemetry
                    self._step_flops = None
            if self._aot_store is not None:
                # the compiled step exists from THIS step on: persist
                # it so the next restart deserializes instead of
                # retracing. skip_if_current makes the warm steady
                # state free; failure degrades to cache-only warm
                # starts, loudly, never a dead trainer.
                from ..aot import export as _aot_export
                try:
                    with _spans.span("aot.export_train_step"):
                        _aot_export.export_train_step(
                            self.model, self._aot_store,
                            skip_if_current=True)
                except (KeyboardInterrupt, SystemExit):
                    raise
                except Exception as e:      # noqa: BLE001 — degrade
                    warnings.warn(
                        f"AOT train-step export unavailable "
                        f"({type(e).__name__}: {e}); restarts warm "
                        "from the compile cache only", stacklevel=2)
        if step_s > 0 and not profiled:
            first_arr = next((b for b in batch
                              if hasattr(b, "shape") and
                              getattr(b, "shape", ())), None)
            if first_arr is not None and len(first_arr.shape) > 0:
                self._m_throughput.set(first_arr.shape[0] / step_s)
            if self._step_flops:
                dev = getattr(self.model, "dev", None)
                peak = _metrics.device_peak_flops(getattr(
                    getattr(dev, "jax_device", None), "device_kind",
                    None))
                if peak:
                    self._m_mfu.set(self._step_flops / step_s / peak)
        # HBM at the step boundary (bytes_in_use / peak / limit gauges)
        _perf.record_hbm(self._jax_device(), site="train")
        # the first step carries the XLA compile: feeding it to the
        # sentinel would seed the baseline orders of magnitude high
        # and blind it for the whole EMA decay
        if self._sentinel is not None and not first and not profiled \
                and self._sentinel.observe(step, step_s):
            # sustained spike: the sentinel already left the attributed
            # step_anomaly event — capture a one-shot profile on the
            # next step and leave the blackbox behind now
            self._profiler.force_next()
            self._blackbox_dump("step_anomaly", step=step)

    # -- the loop ----------------------------------------------------------
    def run(self, data, num_steps, step_callback=None):
        """Train until global step ``num_steps``, surviving what the
        FaultPlan / real world throws. Returns a summary dict; raises
        ``SystemExit(EXIT_PREEMPTED)`` on preemption (see class doc)."""
        self._data = data
        self._it = None
        self._yielded_any = False
        self._data_resumed = False
        self._preempt_signal = None     # a reused trainer starts clean
        self._cur_step = None
        self._last_blackbox = None
        run_t0 = time.perf_counter()
        first_step_done = False
        summary = {"start": None, "steps_run": 0, "rollbacks": 0,
                   "step_retries": 0, "data_retries": 0,
                   "step_timeouts": 0, "skipped_steps": 0,
                   "preempted": False, "membership_lost": False,
                   "dead_ranks": [], "elastic": None,
                   "fingerprints": 0, "quarantined_steps": 0,
                   "divergence_rollbacks": 0, "divergent": [],
                   "diverged": False, "first_step_latency_s": None}
        prev_handlers = self._install_handlers()
        # ambient span attribution: every record made under this run —
        # trainer spans, checkpoint/cluster events, spans inside the
        # watchdog worker (it copies the context) — carries this rank
        span_ctx = _spans.context(rank=self._rank)
        span_ctx.__enter__()
        try:
            if self.cluster is not None and self.cluster.world > 1:
                # rendezvous BEFORE restore: a rank that never shows up
                # is named now, not discovered as a hung collective later
                with _spans.span("barrier", barrier="run-start"):
                    self.cluster.barrier(
                        "run-start", timeout=self.start_barrier_timeout)
            with _spans.span("restore", reason="run-start"):
                start = self.mgr.restore_latest(self.model)
            summary["start"] = start
            if self.cluster is not None and self.cluster.world > 1:
                # resume-step agreement: the barrier NAME carries the
                # resumed step, so a rank that fell back to an older
                # checkpoint (all same-step shard sources corrupt)
                # strands its peers here and everyone exits 75 LOUDLY
                # instead of training at inconsistent parameter
                # versions where no checkpoint could ever commit again
                with _spans.span("barrier", barrier=f"resume-{start}"):
                    self.cluster.barrier(
                        f"resume-{start}",
                        timeout=self.start_barrier_timeout)
            if isinstance(self.mgr, DistributedCheckpointManager):
                # agreement reached (barrier above, or a world of one):
                # markers at/after the resume point vouch for a
                # timeline about to be re-run — cleared now so a later
                # pre-ACK death cannot hide behind a stale marker
                self.mgr.invalidate_markers_from(start)
            self._apply_data_state(start)
            if start:
                self._log(f"resumed from checkpoint; continuing at "
                          f"step {start}")
            manifest = getattr(self.mgr, "restored_manifest", None)
            if manifest is not None and self.cluster is not None:
                saved_world = int(manifest.get("world",
                                               self.cluster.world))
                if saved_world != self.cluster.world:
                    from ..parallel.communicator import rescale_batch
                    per, gb = rescale_batch(manifest, self.cluster.world)
                    summary["elastic"] = {
                        "saved_world": saved_world,
                        "world": self.cluster.world,
                        "per_replica_batch": per, "global_batch": gb}
                    self._log(
                        f"elastic resume: world {saved_world} -> "
                        f"{self.cluster.world}" +
                        (f", global batch -> {gb} (per-replica {per} "
                         "kept)" if per is not None else ""))
            step = start
            self._check_preempt(step - 1, start)
            self._check_cluster()
            guard = self._guard()
            info = getattr(getattr(self.model, "optimizer", None),
                           "telemetry_info", None)
            if callable(info):
                try:        # one static run-config record, never per step
                    _spans.event("run_config", start=start,
                                 num_steps=num_steps, **info())
                except Exception:       # noqa: BLE001 — telemetry only
                    pass
            while step < num_steps:
                self._cur_step = step
                t_fetch = time.perf_counter()
                with _spans.span("data.next", step=step):
                    batch = self._next_batch(step, summary)
                self._m_fetch.observe(time.perf_counter() - t_fetch)
                t_step = time.perf_counter()
                with _spans.span("step", step=step):
                    out = self._run_step(step, batch, summary)
                step_s = time.perf_counter() - t_step
                summary["steps_run"] += 1
                self._observe_step(step, step_s, batch, summary,
                                   run_t0, first=not first_step_done)
                first_step_done = True
                # cross-replica fingerprint on its cadence, BEFORE the
                # save: a diverged step is quarantined — it must never
                # be checkpointed, and the rollback target is the last
                # verified (and cluster-agreed) step. Off by default:
                # fingerprint_every=0 adds zero work here.
                if self.fingerprint_every and \
                        (step + 1) % self.fingerprint_every == 0 and \
                        not self._fingerprint_check(step, summary):
                    step = self._quarantine_rollback(step, summary)
                    continue
                # ONE scalar readback per step; a guard-flagged bad step
                # is never checkpointed, so the newest checkpoint always
                # predates the bad streak and rollback actually rewinds
                bad = guard.bad_streak_value() if guard is not None else 0
                self._m_bad_streak.set(bad)  # value already read back
                if bad == 0:
                    # the data state rides every save: captured AFTER
                    # the step, so it counts this step's batch as
                    # consumed and a resume fetches the NEXT one
                    with _spans.span("checkpoint.save", step=step):
                        self.mgr.save(step, self.model,
                                      data_state=self._data_state())
                    self.faults.on_saved(step)
                if step_callback is not None:
                    step_callback(step, out)
                self._check_preempt(step, start)
                self._check_cluster()
                resumed = self._maybe_rollback(step, bad, summary)
                step = resumed if resumed is not None else step + 1
            self.mgr.wait()
            self._finalize_summary(summary)
            return summary
        except _Preempted:
            summary["preempted"] = True
            self._blackbox_dump("preempted")
            self._finalize_summary(summary)
            if self.exit_on_preempt:
                raise SystemExit(EXIT_PREEMPTED) from None
            return summary
        except DivergenceError as e:
            # NOT recoverable by a plain restart: replicas forked twice
            # despite rolling back to agreed state — suspect hardware.
            # Exit DISTINCT from 75 so the supervisor cordons/replaces
            # the divergent host first; resume still lands on the last
            # cross-replica-agreed checkpoint.
            summary["diverged"] = True
            self._blackbox_dump("diverged", step=e.step)
            self._finalize_summary(summary)
            self._log(f"{e}")
            if self.exit_on_preempt:
                raise SystemExit(EXIT_DIVERGED) from None
            return summary
        except StepTimeoutError:
            # fatal watchdog kill (the in-process grace already ran out
            # in _run_step): the supervisor restart is the recovery —
            # leave the last N seconds of evidence behind first
            self._blackbox_dump("watchdog_kill")
            raise
        except (MembershipError, BarrierTimeout) as e:
            # RECOVERABLE: the job is still viable at a smaller world.
            # Same supervisor contract as preemption — exit 75, restart
            # (now with fewer ranks), resume from the last COMMITTED
            # checkpoint re-sharded onto the new mesh. No checkpoint is
            # attempted here: a commit could never complete without the
            # dead rank's ACK, and the last committed step is consistent.
            summary["membership_lost"] = True
            summary["dead_ranks"] = list(getattr(e, "dead", [])) or \
                list(getattr(e, "missing", []))
            self._blackbox_dump("membership_lost")
            self._finalize_summary(summary)
            self._log(f"{e}; exiting {EXIT_PREEMPTED} for the "
                      "supervisor (restart at the surviving world size)")
            if self.exit_on_preempt:
                raise SystemExit(EXIT_PREEMPTED) from None
            return summary
        except Exception as e:      # noqa: BLE001 — re-raised below
            # any other crash (device OOM, an XLA failure past the
            # retry budget, a bug): leave the post-mortem behind — the
            # dump carries HBM stats and the live-array allocation
            # breakdown, so an OOM names where the memory went
            self._blackbox_dump("crash", error=e)
            raise
        finally:
            span_ctx.__exit__(None, None, None)
            self._restore_handlers(prev_handlers)

    def close(self):
        self.mgr.close()
