"""Resilient training runtime: survive what a real TPU job actually hits.

The reference stack has no failure story — NCCL/MPI errors simply exit
the process (include/singa/io/communicator.h:40-67). A pod-scale job
loses preemptible capacity, sees transient data/device hiccups, and
occasionally diverges numerically; losing a warm process is especially
expensive on TPU where the XLA compile alone can take minutes. This
package adds the three layers that keep work alive:

- :mod:`runtime` — :class:`ResilientTrainer`: a checkpoint-restart
  training driver with SIGTERM/SIGINT preemption handling (sync-save
  then exit with :data:`EXIT_PREEMPTED` for the restart supervisor),
  exponential-backoff retry of transient step/data failures, an
  optional per-step watchdog timeout, and automatic rollback to the
  last good checkpoint on sustained divergence.
- :mod:`guards` — :class:`GuardedOptimizer`: per-step NaN/Inf detection
  on loss and global grad-norm, computed on-device inside the compiled
  step (one scalar readback per step on the host side), with in-graph
  skip-step masking and dynamic loss-scale backoff. A bad step can
  never land in the parameters.
- :mod:`faults` — :class:`FaultPlan`: deterministic fault injection
  (poisoned batches, raising steps/iterators, hangs, SIGTERM delivery,
  crash-mid-async-save, killed/dropped/straggling cluster peers, death
  in the two-phase-commit hole) plus on-disk checkpoint corruption
  helpers, driving the chaos tests in ``tests/test_resilience.py`` and
  ``tests/test_multiprocess.py``.
- :mod:`cluster` — coordinator/worker cluster health over the
  :mod:`singa_tpu.network` control plane: heartbeats with dead-peer and
  straggler detection, barriers that *name the missing ranks* instead
  of hanging, and the ACK/commit protocol behind the two-phase
  :class:`~singa_tpu.checkpoint.DistributedCheckpointManager`.
  Membership loss is recoverable: exit 75, restart at the surviving
  world size, resume from the last committed checkpoint (world-size-
  elastic re-sharding included).

On top of the fail-stop story above sits the END-TO-END INTEGRITY
layer (:mod:`singa_tpu.integrity`): checkpoint shards carry content
digests verified on restore (and re-verified at rest by
``CheckpointManager.scrub`` / ``tools/scrub_checkpoints.py``), every
control-plane frame rides a CRC behind a versioned hello, and a
periodic cross-replica fingerprint quarantines silently-diverged
state and rolls back to the last verified, cluster-agreed checkpoint
— exiting :data:`EXIT_DIVERGED` (76, distinct from 75: cordon the
suspect host, don't just relaunch) when divergence repeats.
"""

from .runtime import (EXIT_DIVERGED, EXIT_PREEMPTED,      # noqa: F401
                      DivergenceError, ResilientTrainer,
                      StepTimeoutError)
from .guards import GuardedOptimizer                      # noqa: F401
from .faults import (FaultInjected, FaultPlan,            # noqa: F401
                     SimulatedCrash, bitflip_checkpoint,
                     corrupt_checkpoint, truncate_checkpoint)
from .cluster import (BarrierTimeout, ClusterConfig,      # noqa: F401
                      ClusterError, MembershipError, SoloCluster,
                      make_cluster)
