"""TCP message-passing network: Python API over the native endpoint layer
(native/singa_network.cc).

The capability peer of the reference's EndPoint network
(include/singa/io/network.h:62-136, src/io/network/endpoint.cc): tagged
messages with separate metadata and payload, per-peer endpoints with
queued non-blocking sends and blocking receives, a factory that surfaces
inbound connections, and delivery acknowledgements. In this framework it
is the control-plane side channel for multi-host deployments — tensor
traffic rides XLA collectives over ICI/DCN (parallel/communicator.py),
never this socket layer.

Usage::

    srv = NetworkThread(port=0)            # port 0 -> ephemeral
    cli = NetworkThread(port=-1)           # -1 -> no listener (client only)
    ep = cli.connect("127.0.0.1", srv.port)
    ep.send(Message(b"meta", b"payload"))
    peer = srv.accept(timeout=5.0)         # EndPoint for the inbound side
    msg = peer.recv(timeout=5.0)
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import subprocess
import threading

from .integrity import (IntegrityError, MAX_MESSAGE_BYTES, open_frame,
                        seal_frame)

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(_HERE), "native")
_PACKAGED_LIB = os.path.join(_HERE, "native", "libsinga_network.so")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsinga_network.so")

CONN_INIT = 0
CONN_PENDING = 1
CONN_EST = 2
CONN_ERROR = 3

_lib = None
_load_failed = False            # cache a failed probe: never re-spawn make


def _load():
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    path = None
    if os.path.exists(_PACKAGED_LIB):
        path = _PACKAGED_LIB
    else:
        src = os.path.join(_NATIVE_DIR, "singa_network.cc")
        if os.path.exists(src):
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR,
                                "libsinga_network.so"],
                               check=True, capture_output=True, timeout=300)
            except (subprocess.SubprocessError, OSError):
                pass
        if os.path.exists(_LIB_PATH):
            path = _LIB_PATH
    if path is None:
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
        _bind(lib)
    except (OSError, AttributeError):
        # unloadable, or a stale prebuilt .so missing a newer symbol —
        # degrade to "unavailable" rather than crashing callers
        _load_failed = True
        return None
    _lib = lib
    return lib


def _bind(lib):
    lib.sg_net_create.restype = ctypes.c_void_p
    lib.sg_net_create.argtypes = [ctypes.c_int]
    lib.sg_net_port.restype = ctypes.c_int
    lib.sg_net_port.argtypes = [ctypes.c_void_p]
    lib.sg_net_shutdown.argtypes = [ctypes.c_void_p]
    lib.sg_net_destroy.argtypes = [ctypes.c_void_p]
    lib.sg_net_connect.restype = ctypes.c_int64
    lib.sg_net_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
    lib.sg_net_accept_ep.restype = ctypes.c_int64
    lib.sg_net_accept_ep.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.sg_ep_send.restype = ctypes.c_int64
    lib.sg_ep_send.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_char_p, ctypes.c_uint64,
                               ctypes.c_char_p, ctypes.c_uint64]
    lib.sg_ep_recv_wait.restype = ctypes.c_int
    lib.sg_ep_recv_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_uint64),
                                    ctypes.POINTER(ctypes.c_uint64)]
    lib.sg_ep_recv_copy.restype = ctypes.c_int
    lib.sg_ep_recv_copy.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.c_void_p, ctypes.c_uint64,
                                    ctypes.c_void_p, ctypes.c_uint64]
    lib.sg_ep_close.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.sg_ep_pending.restype = ctypes.c_int
    lib.sg_ep_pending.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.sg_ep_drain.restype = ctypes.c_int
    lib.sg_ep_drain.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                ctypes.c_int]
    lib.sg_ep_status.restype = ctypes.c_int
    lib.sg_ep_status.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.sg_ep_peer.restype = ctypes.c_int
    lib.sg_ep_peer.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                               ctypes.c_char_p, ctypes.c_int]


def available() -> bool:
    """True when the native network layer built/loaded."""
    return _load() is not None


class Message:
    """A tagged message: metadata + payload byte strings (reference
    Message include/singa/io/network.h:62-89)."""

    def __init__(self, meta: bytes = b"", payload: bytes = b""):
        self.meta = bytes(meta)
        self.payload = bytes(payload)
        self.id = None

    def __repr__(self):
        return (f"Message(meta={len(self.meta)}B, "
                f"payload={len(self.payload)}B, id={self.id})")


class EndPoint:
    """One peer connection: queued sends, blocking receives, delivery
    tracking (reference EndPoint include/singa/io/network.h:92-117).

    ``recv`` is safe to call from several threads — a per-endpoint lock
    serializes the wait/copy pair against the C layer.
    """

    def __init__(self, net: "NetworkThread", handle: int):
        self._net = net
        self._h = handle
        self._recv_lock = threading.Lock()

    def send(self, msg: Message) -> int:
        """Queue ``msg``; returns its id. Raises on a dead endpoint."""
        with self._net._guard() as h:
            mid = _load().sg_ep_send(h, self._h, msg.meta,
                                     len(msg.meta), msg.payload,
                                     len(msg.payload))
        if mid < 0:
            raise ConnectionError("endpoint is in error state")
        msg.id = mid
        return mid

    def recv(self, timeout: float = 5.0,
             max_bytes: int | None = None) -> Message | None:
        """Next message, or None on timeout. Raises when the connection
        died and nothing is queued. ``max_bytes`` (optional) rejects a
        frame whose meta or payload exceeds it — the frame is consumed
        and a typed :class:`~singa_tpu.integrity.IntegrityError` raised
        before the Python-side buffers are built. Unset by default: the
        general message layer supports anything up to the native
        runtime's own 1 GiB frame cap; ``recv_sealed`` (the
        control-plane path) applies :data:`~singa_tpu.integrity.
        MAX_MESSAGE_BYTES`.

        The native wait runs in SHORT slices with the net guard released
        between them, so ``NetworkThread.close()`` is never blocked for a
        caller-chosen recv timeout, and one endpoint's long recv does not
        serialize the whole Net against close.

        A ``NetworkThread.close()`` racing an ALREADY-pending recv —
        including one still waiting on the per-endpoint lock behind a
        concurrent receiver — makes that recv return ``None`` (the
        clean "nothing arrived" shape its caller must handle anyway);
        only a recv STARTED after close — a programming error — raises
        ``ConnectionError``."""
        import time as _time
        deadline = _time.monotonic() + max(0.0, timeout)
        with self._net._cond:
            was_open = self._net._h is not None
        with self._recv_lock:
            while True:
                remaining = deadline - _time.monotonic()
                slice_ms = int(min(max(remaining, 0.0), 0.2) * 1000)
                try:
                    with self._net._guard() as h:
                        ms = ctypes.c_uint64()
                        ps = ctypes.c_uint64()
                        rc = _load().sg_ep_recv_wait(
                            h, self._h, slice_ms,
                            ctypes.byref(ms), ctypes.byref(ps))
                        if rc < 0:
                            raise ConnectionError("endpoint closed")
                        if rc > 0 and max_bytes is not None and \
                                (ms.value > max_bytes or
                                 ps.value > max_bytes):
                            # a frame far beyond what this caller's
                            # protocol ever sends: don't build the
                            # Python-side buffers for it. The frame is
                            # CONSUMED (zero-capacity copy pops it; the
                            # native layer truncates, never overflows)
                            # so the endpoint stays usable, then the
                            # typed error surfaces.
                            _load().sg_ep_recv_copy(h, self._h, None, 0,
                                                    None, 0)
                            raise IntegrityError(
                                f"oversized frame (meta {ms.value}B / "
                                f"payload {ps.value}B > "
                                f"{max_bytes}B cap): corrupt "
                                "length header? (frame dropped)")
                        if rc > 0:
                            meta = ctypes.create_string_buffer(
                                max(1, ms.value))
                            payload = ctypes.create_string_buffer(
                                max(1, ps.value))
                            rc2 = _load().sg_ep_recv_copy(
                                h, self._h, meta, ms.value, payload,
                                ps.value)
                            if rc2 < 0:
                                # closed between the wait and the copy
                                raise ConnectionError("endpoint closed")
                            return Message(meta.raw[:ms.value],
                                           payload.raw[:ps.value])
                except ConnectionError:
                    # our own Net closed under a pending recv -> clean
                    # None; a peer-dead endpoint (Net still up) or a
                    # recv started after close still raises
                    with self._net._cond:
                        closed_now = self._net._h is None
                    if was_open and closed_now:
                        return None
                    raise
                if remaining <= 0:
                    return None

    def send_sealed(self, msg: Message) -> int:
        """``send`` with the integrity frame header (magic + protocol
        version + CRCs over meta and payload + length fields) sealed
        onto the payload — the counterpart ``recv_sealed`` verifies it.
        The frame format is :func:`singa_tpu.integrity.seal_frame` —
        the SAME frames the cluster layer builds (it seals via
        ``integrity.seal_frame`` directly, because its fault-injection
        and drop-and-count hooks sit between sealing and the socket);
        these helpers are the convenience pair for other EndPoint
        users."""
        return self.send(Message(msg.meta,
                                 seal_frame(msg.meta, msg.payload)))

    def recv_sealed(self, timeout: float = 5.0) -> Message | None:
        """``recv`` + verify-and-strip of the integrity frame header.
        Returns None on timeout like ``recv``; a frame that fails any
        check (magic, version, truncation, length, CRC — or the
        control-plane ``MAX_MESSAGE_BYTES`` cap, enforced before the
        Python buffers are built) raises
        :class:`~singa_tpu.integrity.IntegrityError` — the corrupt
        frame is consumed, so the connection stays usable and the
        caller decides whether to drop-and-count or tear down."""
        msg = self.recv(timeout, max_bytes=MAX_MESSAGE_BYTES)
        if msg is None:
            return None
        return Message(msg.meta, open_frame(msg.meta, msg.payload))

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait until every sent message has been acknowledged."""
        with self._net._guard() as h:
            return _load().sg_ep_drain(h, self._h,
                                       int(timeout * 1000)) == 1

    def close(self):
        """Drop this connection and free its queues (the NetworkThread
        stays up for other endpoints)."""
        try:
            with self._net._guard() as h:
                _load().sg_ep_close(h, self._h)
        except ConnectionError:
            pass                 # the whole NetworkThread is already gone

    @property
    def pending(self) -> int:
        with self._net._guard() as h:
            return _load().sg_ep_pending(h, self._h)

    @property
    def status(self) -> int:
        with self._net._guard() as h:
            return _load().sg_ep_status(h, self._h)

    @property
    def peer(self) -> str:
        with self._net._guard() as h:
            buf = ctypes.create_string_buffer(128)
            _load().sg_ep_peer(h, self._h, buf, 128)
            return buf.value.decode()


class NetworkThread:
    """Background IO thread multiplexing every endpoint (reference
    NetworkThread include/singa/io/network.h:136+ over libev; here
    poll(2) in native code).

    ``port=0`` listens on an ephemeral port (read ``.port``); ``port=-1``
    runs client-only with no listener.
    """

    def __init__(self, port: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native network layer unavailable (no C++ toolchain?)")
        self._cond = threading.Condition()
        self._inflight = 0
        self._h = lib.sg_net_create(port)
        if not self._h:
            raise OSError(f"could not bind port {port}")

    @contextlib.contextmanager
    def _guard(self):
        """Enter a native call: refuses when closed, and keeps the Net
        alive until the call leaves (close() frees only after the
        in-flight count drains — no use-after-free on a close race)."""
        with self._cond:
            if not self._h:
                raise ConnectionError("NetworkThread is closed")
            self._inflight += 1
            h = self._h
        try:
            yield h
        finally:
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()

    @property
    def port(self) -> int:
        with self._guard() as h:
            return _load().sg_net_port(h)

    def connect(self, host: str, port: int) -> EndPoint:
        with self._guard() as nh:
            h = _load().sg_net_connect(nh, host.encode(), port)
        if h == 0:
            raise ConnectionError(f"could not connect to {host}:{port}")
        return EndPoint(self, h)

    def accept(self, timeout: float = 5.0) -> EndPoint | None:
        """Next inbound endpoint, or None on timeout (reference
        EndPointFactory::getNewEps)."""
        with self._guard() as nh:
            h = _load().sg_net_accept_ep(nh, int(timeout * 1000))
        return EndPoint(self, h) if h else None

    def close(self):
        """Tear down: refuse new calls, wake + drain every blocked call,
        then free the native Net."""
        with self._cond:
            if not self._h:
                return
            h, self._h = self._h, None       # no new entries
            _load().sg_net_shutdown(h)       # wake blocked waiters
            while self._inflight > 0:
                self._cond.wait()
        _load().sg_net_destroy(h)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


__all__ = ["Message", "EndPoint", "NetworkThread", "available",
           "CONN_INIT", "CONN_PENDING", "CONN_EST", "CONN_ERROR"]
