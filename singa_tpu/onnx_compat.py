"""ONNX interop layer: real ``onnx`` package when installed, else the
bundled wire-compatible protos (singa_tpu/onnx_proto/onnx.proto).

Exposes the tiny slice of the onnx python API that ``singa_tpu.sonnx``
needs — ``helper.make_*``, ``numpy_helper.to_array/from_array``,
``TensorProto`` dtype ids, ``load/save`` — with identical serialized bytes
either way, so models exported here open in stock onnx tooling.
"""

from __future__ import annotations

import numpy as np


class UnsupportedOnnxDtype(ValueError):
    """A TensorProto ``data_type`` this build cannot decode into a
    numpy array (e.g. an exotic fp8 variant in a quantized model).
    Carries the dtype NAME, not just the enum int, so a failed
    quantized-model import says what it hit instead of a bare
    KeyError."""


try:  # prefer the real package when present
    import onnx as _onnx
    from onnx import helper, numpy_helper  # noqa: F401
    TensorProto = _onnx.TensorProto
    AttributeProto = _onnx.AttributeProto
    ModelProto = _onnx.ModelProto
    GraphProto = _onnx.GraphProto
    NodeProto = _onnx.NodeProto
    load = _onnx.load
    save = _onnx.save
    HAS_REAL_ONNX = True
except ImportError:
    from . import onnx_proto as _pb
    TensorProto = _pb.TensorProto
    AttributeProto = _pb.AttributeProto
    ModelProto = _pb.ModelProto
    GraphProto = _pb.GraphProto
    NodeProto = _pb.NodeProto
    HAS_REAL_ONNX = False

    _NP_TO_ONNX = {
        np.dtype(np.float32): TensorProto.FLOAT,
        np.dtype(np.uint8): TensorProto.UINT8,
        np.dtype(np.int8): TensorProto.INT8,
        np.dtype(np.uint16): TensorProto.UINT16,
        np.dtype(np.int16): TensorProto.INT16,
        np.dtype(np.int32): TensorProto.INT32,
        np.dtype(np.int64): TensorProto.INT64,
        np.dtype(np.bool_): TensorProto.BOOL,
        np.dtype(np.float16): TensorProto.FLOAT16,
        np.dtype(np.float64): TensorProto.DOUBLE,
        np.dtype(np.uint32): TensorProto.UINT32,
        np.dtype(np.uint64): TensorProto.UINT64,
    }
    try:
        # quantized-model interop (BFLOAT16 = 16 is in the bundled
        # proto enum; the fp8 ids are the stock onnx values, accepted
        # numerically so a file produced by newer tooling still opens)
        import ml_dtypes as _mld
        _NP_TO_ONNX[np.dtype(_mld.bfloat16)] = TensorProto.BFLOAT16
        _NP_TO_ONNX[np.dtype(_mld.float8_e4m3fn)] = 17   # FLOAT8E4M3FN
        _NP_TO_ONNX[np.dtype(_mld.float8_e5m2)] = 19     # FLOAT8E5M2
    except ImportError:
        pass
    _ONNX_TO_NP = {v: k for k, v in _NP_TO_ONNX.items()}

    # names for ids this reader knows OF but cannot decode — so an
    # import of e.g. a FLOAT8E4M3FNUZ-quantized model fails naming the
    # dtype instead of with a bare KeyError on an integer
    _ONNX_DTYPE_NAMES = {
        0: "UNDEFINED", 1: "FLOAT", 2: "UINT8", 3: "INT8", 4: "UINT16",
        5: "INT16", 6: "INT32", 7: "INT64", 8: "STRING", 9: "BOOL",
        10: "FLOAT16", 11: "DOUBLE", 12: "UINT32", 13: "UINT64",
        14: "COMPLEX64", 15: "COMPLEX128", 16: "BFLOAT16",
        17: "FLOAT8E4M3FN", 18: "FLOAT8E4M3FNUZ", 19: "FLOAT8E5M2",
        20: "FLOAT8E5M2FNUZ", 21: "UINT4", 22: "INT4", 23: "FLOAT4E2M1",
    }

    def _onnx_to_np(data_type):
        try:
            return _ONNX_TO_NP[data_type]
        except KeyError:
            name = _ONNX_DTYPE_NAMES.get(int(data_type),
                                         f"id {data_type}")
            raise UnsupportedOnnxDtype(
                f"ONNX TensorProto dtype {name} ({data_type}) is not "
                "supported by singa_tpu's bundled ONNX reader "
                "(supported: "
                f"{sorted(str(d) for d in _NP_TO_ONNX)})") from None

    class _Helper:
        """make_* builders mirroring onnx.helper semantics."""

        @staticmethod
        def np_dtype_to_tensor_dtype(dtype):
            try:
                return _NP_TO_ONNX[np.dtype(dtype)]
            except KeyError:
                raise UnsupportedOnnxDtype(
                    f"numpy dtype {np.dtype(dtype)!s} has no ONNX "
                    "TensorProto id in singa_tpu's bundled writer "
                    f"(supported: {sorted(str(d) for d in _NP_TO_ONNX)})"
                ) from None

        @staticmethod
        def tensor_dtype_to_np_dtype(tensor_dtype):
            return _onnx_to_np(tensor_dtype)

        @staticmethod
        def make_attribute(name, value):
            a = AttributeProto(name=name)
            if isinstance(value, float):
                a.f = value
                a.type = AttributeProto.FLOAT
            elif isinstance(value, bool):
                a.i = int(value)
                a.type = AttributeProto.INT
            elif isinstance(value, (int, np.integer)):
                a.i = int(value)
                a.type = AttributeProto.INT
            elif isinstance(value, str):
                a.s = value.encode("utf-8")
                a.type = AttributeProto.STRING
            elif isinstance(value, bytes):
                a.s = value
                a.type = AttributeProto.STRING
            elif isinstance(value, TensorProto):
                a.t.CopyFrom(value)
                a.type = AttributeProto.TENSOR
            elif isinstance(value, (list, tuple, np.ndarray)):
                vals = list(value)
                if all(isinstance(v, (int, np.integer)) for v in vals):
                    a.ints.extend(int(v) for v in vals)
                    a.type = AttributeProto.INTS
                elif all(isinstance(v, (int, float, np.floating, np.integer))
                         for v in vals):
                    a.floats.extend(float(v) for v in vals)
                    a.type = AttributeProto.FLOATS
                elif all(isinstance(v, (str, bytes)) for v in vals):
                    a.strings.extend(
                        v.encode("utf-8") if isinstance(v, str) else v
                        for v in vals)
                    a.type = AttributeProto.STRINGS
                else:
                    raise ValueError(
                        f"unsupported attribute list for {name}: {vals!r}")
            else:
                raise ValueError(
                    f"unsupported attribute value for {name}: {value!r}")
            return a

        @classmethod
        def make_node(cls, op_type, inputs, outputs, name=None, domain=None,
                      **attrs):
            n = NodeProto(op_type=op_type, input=list(inputs),
                          output=list(outputs))
            if name:
                n.name = name
            if domain:
                n.domain = domain
            for k in sorted(attrs):
                if attrs[k] is not None:
                    n.attribute.append(cls.make_attribute(k, attrs[k]))
            return n

        @staticmethod
        def make_tensor_value_info(name, elem_type, shape):
            v = _pb.ValueInfoProto(name=name)
            v.type.tensor_type.elem_type = elem_type
            if shape is not None:
                for d in shape:
                    dim = v.type.tensor_type.shape.dim.add()
                    if isinstance(d, (int, np.integer)):
                        dim.dim_value = int(d)
                    elif d is not None:
                        dim.dim_param = str(d)
            return v

        @staticmethod
        def make_tensor(name, data_type, dims, vals, raw=False):
            t = TensorProto(name=name, data_type=data_type,
                            dims=list(dims))
            if raw:
                t.raw_data = vals if isinstance(vals, bytes) else bytes(vals)
            else:
                np_dtype = _onnx_to_np(data_type)
                arr = np.asarray(vals, dtype=np_dtype).ravel()
                t.raw_data = arr.tobytes()
            return t

        @staticmethod
        def make_graph(nodes, name, inputs, outputs, initializer=None,
                       value_info=None):
            g = GraphProto(name=name)
            g.node.extend(nodes)
            g.input.extend(inputs)
            g.output.extend(outputs)
            if initializer:
                g.initializer.extend(initializer)
            if value_info:
                g.value_info.extend(value_info)
            return g

        @staticmethod
        def make_operatorsetid(domain, version):
            return _pb.OperatorSetIdProto(domain=domain, version=version)

        @staticmethod
        def make_model(graph, producer_name="singa_tpu",
                       opset_imports=None, ir_version=6, **kwargs):
            m = ModelProto(ir_version=ir_version,
                           producer_name=producer_name)
            m.graph.CopyFrom(graph)
            if opset_imports is None:
                opset_imports = [
                    _pb.OperatorSetIdProto(domain="", version=11)]
            m.opset_import.extend(opset_imports)
            return m

        @staticmethod
        def get_attribute_value(attr):
            return _get_attribute_value(attr)

    helper = _Helper()

    class _NumpyHelper:
        @staticmethod
        def from_array(arr, name=None):
            arr = np.asarray(arr)
            t = TensorProto(data_type=_NP_TO_ONNX[arr.dtype],
                            dims=list(arr.shape),
                            raw_data=np.ascontiguousarray(arr).tobytes())
            if name:
                t.name = name
            return t

        @staticmethod
        def to_array(t):
            dtype = _onnx_to_np(t.data_type)
            shape = tuple(t.dims)
            if t.raw_data:
                return np.frombuffer(t.raw_data, dtype=dtype).reshape(shape)
            if t.float_data:
                return np.asarray(t.float_data, np.float32).astype(
                    dtype).reshape(shape)
            if t.int64_data:
                return np.asarray(t.int64_data, np.int64).astype(
                    dtype).reshape(shape)
            if t.int32_data:
                return np.asarray(t.int32_data, np.int32).astype(
                    dtype).reshape(shape)
            if t.double_data:
                return np.asarray(t.double_data, np.float64).astype(
                    dtype).reshape(shape)
            return np.zeros(shape, dtype)

    numpy_helper = _NumpyHelper()

    def load(path):
        m = ModelProto()
        with open(path, "rb") as f:
            m.ParseFromString(f.read())
        return m

    def save(model, path):
        with open(path, "wb") as f:
            f.write(model.SerializeToString())


def _get_attribute_value(attr):
    """AttributeProto -> python value (works for both backends)."""
    AT = AttributeProto
    if attr.type == AT.FLOAT:
        return attr.f
    if attr.type == AT.INT:
        return attr.i
    if attr.type == AT.STRING:
        return attr.s.decode("utf-8") if isinstance(attr.s, bytes) else attr.s
    if attr.type == AT.TENSOR:
        return attr.t
    if attr.type == AT.FLOATS:
        return list(attr.floats)
    if attr.type == AT.INTS:
        return list(attr.ints)
    if attr.type == AT.STRINGS:
        return [s.decode("utf-8") if isinstance(s, bytes) else s
                for s in attr.strings]
    raise ValueError(f"unsupported attribute type {attr.type}")


def attribute_dict(node):
    """All of a node's attributes as a name->value dict."""
    return {a.name: _get_attribute_value(a) for a in node.attribute}
