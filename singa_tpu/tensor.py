"""Tensor: an nd-array with device placement and autograd hooks, on jax.Array.

Capability parity with the reference Tensor (include/singa/core/tensor.h:55-312
and python/singa/tensor.py), redesigned TPU-first:

- the payload is a ``jax.Array`` (or an XLA tracer while a model step is being
  traced), so every op lowers to XLA and fuses — there is no Block, no
  DeviceMemPool, no TYPE_LANG_SWITCH backend dispatch
  (src/core/tensor/tensor.cc:760-812); XLA *is* the single backend;
- "in-place" mutation (``copy_from_numpy``, optimizer axpy into params,
  BN running stats) rebinds ``self.data`` — under ``jax.jit`` tracing this is
  pure value threading, which the Model layer turns into donated buffers;
- autograd fields (``creator``/``requires_grad``/``stores_grad``) match
  python/singa/tensor.py:91-125 so the define-by-run tape in
  ``singa_tpu.autograd`` works identically.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import device as device_mod

__all__ = [
    "Tensor", "float16", "bfloat16", "float32", "float64", "int32", "int64",
    "int8", "uint8", "from_numpy", "to_numpy", "to_host",
    "from_raw_tensor", "from_raw_tensors",
    "zeros_like", "ones_like", "zeros", "ones", "random", "product", "sizeof",
    "reshape", "transpose", "contiguous", "copy_data_to_from",
    "abs", "exp", "ceil", "log", "sigmoid", "sign", "sqrt", "square", "tanh",
    "relu", "sum", "pow", "average", "softmax", "lt", "le", "gt", "ge", "eq",
    "add", "sub", "eltwise_mult", "mult", "div", "axpy", "einsum", "repeat",
    "tensordot", "bernoulli", "gaussian", "uniform", "add_column", "add_row",
    "sum_columns", "sum_rows", "copy_from_numpy", "concatenate",
]

# dtype aliases (reference core.proto DataType, src/proto/core.proto:26)
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int32 = jnp.int32
int64 = jnp.int64
int8 = jnp.int8
uint8 = jnp.uint8


def _raw(x):
    """Unwrap Tensor → jax array; pass arrays/scalars through."""
    return x.data if isinstance(x, Tensor) else x


class Tensor:
    """nd-array with device placement, dtype, and autograd metadata.

    ``spec`` (class default None = replicated) is an optional
    ``jax.sharding.PartitionSpec`` announcing how this tensor is laid out
    over the device mesh; the Model layer threads it into the compiled
    step's shard_map in/out specs (tensor-parallel layers set it on their
    weights).
    """

    spec = None

    def __init__(self, shape=(), device=None, dtype=None, data=None,
                 requires_grad=True, stores_grad=False, creator=None,
                 name=None):
        if device is None:
            device = device_mod.get_default_device()
        self.device = device
        if data is not None:
            # honor the data's own dtype unless one is given explicitly
            if isinstance(data, Tensor):
                data = data.data
            elif isinstance(data, np.ndarray):
                data = device.put(data.astype(np.dtype(dtype))
                                  if dtype is not None else data)
            elif isinstance(data, jax.Array):
                # already on device (the common hot path: every compiled
                # step output) — no asarray/dtype-lattice work needed
                if dtype is not None and data.dtype != jnp.dtype(dtype):
                    data = data.astype(dtype)
            else:
                data = jnp.asarray(data)
                if dtype is not None:
                    data = data.astype(dtype)
            self.data = data
        else:
            self.data = jnp.zeros(tuple(shape),
                                  dtype=dtype if dtype is not None
                                  else float32,
                                  device=device.jax_device)
        self.requires_grad = requires_grad
        self.stores_grad = stores_grad
        self.creator = creator
        self.name = name
        self.grad = None  # populated by autograd.backward when retained

    # ---- metadata -------------------------------------------------------
    @property
    def shape(self):
        return tuple(self.data.shape)

    @property
    def ndim(self):
        return self.data.ndim

    @property
    def dtype(self):
        return self.data.dtype

    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    def memsize(self):
        return self.size() * self.data.dtype.itemsize

    def is_empty(self):
        return self.size() == 0

    def is_transpose(self):
        # XLA arrays are always materialised contiguously; stride-view
        # transposes (reference tensor.h:107-127) do not exist here.
        return False

    def ndim_(self):
        return self.ndim

    # ---- placement / conversion ----------------------------------------
    def to_device(self, device):
        self.device = device
        if not _is_tracer(self.data):
            self.data = device.put(self.data)
        return self

    def to_host(self):
        return self.to_device(device_mod.get_default_device())

    def as_type(self, dtype):
        t = self.clone()
        t.data = t.data.astype(dtype)
        return t

    def astype(self, dtype):
        return self.as_type(dtype)

    def numpy(self):
        return np.asarray(jax.device_get(self.data))

    def tolist(self):
        return self.numpy().tolist()

    def item(self):
        return self.numpy().item()

    # ---- mutation (value rebinding) -------------------------------------
    def copy_from_numpy(self, np_array, offset=0):
        assert offset == 0, "offset copy not supported"
        arr = np.ascontiguousarray(np_array).reshape(self.shape)
        arr = arr.astype(np.dtype(self.dtype))
        if _is_tracer(self.data):
            self.data = jnp.asarray(arr)
        else:
            self.data = self.device.put(arr)
        return self

    def copy_data(self, other: "Tensor"):
        self.data = jnp.asarray(_raw(other), dtype=self.dtype).reshape(self.shape)
        return self

    def copy_from(self, other):
        if isinstance(other, np.ndarray):
            return self.copy_from_numpy(other)
        return self.copy_data(other)

    def reset_like(self, other: "Tensor"):
        self.data = jnp.zeros(other.shape, dtype=other.dtype,
                              device=self.device.jax_device)
        return self

    def set_value(self, x):
        self.data = jnp.full(self.shape, x, dtype=self.dtype,
                             device=self.device.jax_device)
        return self

    # ---- random fillers (functional curand; reference tensor.py fillers) --
    def gaussian(self, mean=0.0, std=1.0):
        k = self.device.rand_key()
        self.data = mean + std * jax.random.normal(k, self.shape,
                                                   dtype=self.dtype)
        return self

    def uniform(self, low=0.0, high=1.0):
        k = self.device.rand_key()
        self.data = jax.random.uniform(k, self.shape, dtype=self.dtype,
                                       minval=low, maxval=high)
        return self

    def bernoulli(self, p):
        k = self.device.rand_key()
        self.data = jax.random.bernoulli(k, p, self.shape).astype(self.dtype)
        return self

    # ---- shape ops ------------------------------------------------------
    def reshape(self, shape):
        t = self.clone()
        t.data = jnp.reshape(t.data, shape)
        return t

    def T(self):  # noqa: N802 - reference API (tensor.h Tensor::T)
        return self.transpose()

    def transpose(self, axes=None):
        t = self.clone()
        t.data = jnp.transpose(t.data, axes)
        return t

    def flatten(self):
        return self.reshape((self.size(),))

    def repeat(self, repeats, axis):
        t = self.clone()
        t.data = jnp.repeat(t.data, repeats, axis=axis)
        return t

    def clone(self):
        t = Tensor.__new__(Tensor)
        t.data = self.data
        t.device = self.device
        t.requires_grad = self.requires_grad
        t.stores_grad = self.stores_grad
        t.creator = None
        t.name = self.name
        t.grad = None
        return t

    def deepcopy(self):
        t = self.clone()
        t.data = jnp.array(self.data) if not _is_tracer(self.data) else self.data
        return t

    # ---- elementwise / arithmetic (eager; autograd ops live in autograd.py)
    def __add__(self, o):
        return _wrap(self.data + _raw(o), self)

    __radd__ = __add__

    def __sub__(self, o):
        return _wrap(self.data - _raw(o), self)

    def __rsub__(self, o):
        return _wrap(_raw(o) - self.data, self)

    def __mul__(self, o):
        return _wrap(self.data * _raw(o), self)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return _wrap(self.data / _raw(o), self)

    def __rtruediv__(self, o):
        return _wrap(_raw(o) / self.data, self)

    __div__ = __truediv__

    def __neg__(self):
        return _wrap(-self.data, self)

    def __pow__(self, o):
        return _wrap(self.data ** _raw(o), self)

    def __lt__(self, o):
        return _wrap((self.data < _raw(o)).astype(float32), self)

    def __le__(self, o):
        return _wrap((self.data <= _raw(o)).astype(float32), self)

    def __gt__(self, o):
        return _wrap((self.data > _raw(o)).astype(float32), self)

    def __ge__(self, o):
        return _wrap((self.data >= _raw(o)).astype(float32), self)

    def __matmul__(self, o):
        return _wrap(self.data @ _raw(o), self)

    # in-place variants mutate by rebinding (reference += on CTensor)
    def __iadd__(self, o):
        self.data = self.data + _raw(o)
        return self

    def __isub__(self, o):
        self.data = self.data - _raw(o)
        return self

    def __imul__(self, o):
        self.data = self.data * _raw(o)
        return self

    def __itruediv__(self, o):
        self.data = self.data / _raw(o)
        return self

    def __getitem__(self, keys):
        return _wrap(self.data[keys], self)

    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __repr__(self):
        body = ("<traced>" if _is_tracer(self.data)
                else np.array2string(self.numpy(), threshold=24))
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"device={self.device.name()}, data={body})")

    # misc math used by reference scripts
    def l2(self):
        # reference: nrm2 / Size() (src/core/tensor/tensor.cc:833-843)
        return float(jnp.sqrt(jnp.sum(self.data * self.data)) /
                     max(1, self.size()))

    def l1(self):
        # reference: asum / Size() (src/core/tensor/tensor.cc:815-827)
        return float(jnp.sum(jnp.abs(self.data)) / max(1, self.size()))


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _wrap(arr, like: Tensor) -> Tensor:
    t = Tensor.__new__(Tensor)
    t.data = arr
    t.device = like.device
    t.requires_grad = like.requires_grad
    t.stores_grad = False
    t.creator = None
    t.name = None
    t.grad = None
    return t


# ---------------------------------------------------------------------------
# module-level functional API (parity with python/singa/tensor.py free fns)
# ---------------------------------------------------------------------------

def from_numpy(np_array, dev=None) -> Tensor:
    if np_array.dtype == np.float64:
        np_array = np_array.astype(np.float32)
    if np_array.dtype == np.int64:
        np_array = np_array.astype(np.int32)
    return Tensor(data=np_array, device=dev, dtype=np_array.dtype,
                  requires_grad=False)


def to_numpy(t: Tensor) -> np.ndarray:
    return t.numpy()


def to_host(t: Tensor) -> Tensor:
    return t.clone().to_host()


def from_raw_tensor(arr, dev=None) -> Tensor:
    return Tensor(data=arr, device=dev)


def from_raw_tensors(arrs, dev=None) -> list:
    """List form of :func:`from_raw_tensor` (reference tensor.py:795)."""
    return [from_raw_tensor(a, dev) for a in arrs]


def zeros_like(t: Tensor) -> Tensor:
    return Tensor(shape=t.shape, device=t.device, dtype=t.dtype)


def ones_like(t: Tensor) -> Tensor:
    out = Tensor(shape=t.shape, device=t.device, dtype=t.dtype)
    out.data = jnp.ones(t.shape, dtype=t.dtype,
                        device=out.device.jax_device)
    return out


def zeros(shape, dtype=float32, device=None) -> Tensor:
    return Tensor(shape=shape, dtype=dtype, device=device)


def ones(shape, dtype=float32, device=None) -> Tensor:
    t = Tensor(shape=shape, dtype=dtype, device=device)
    t.data = jnp.ones(shape, dtype=dtype, device=t.device.jax_device)
    return t


def random(shape, device=None) -> Tensor:
    t = Tensor(shape=shape, device=device)
    t.uniform(0.0, 1.0)
    return t


def product(shape) -> int:
    return int(np.prod(shape)) if len(shape) else 1


def sizeof(dtype) -> int:
    return np.dtype(dtype).itemsize


def contiguous(t: Tensor) -> Tensor:
    return t.clone()


def reshape(t: Tensor, shape) -> Tensor:
    return t.reshape(shape)


def transpose(t: Tensor, axes=None) -> Tensor:
    return t.transpose(axes)


def copy_data_to_from(dst: Tensor, src: Tensor, size=None,
                      dst_offset=0, src_offset=0) -> None:
    assert dst_offset == 0 and src_offset == 0
    if size is None or size == dst.size():
        dst.copy_data(src)
    else:
        flat_src = jnp.ravel(_raw(src))[:size]
        flat_dst = jnp.ravel(dst.data)
        dst.data = flat_dst.at[:size].set(flat_src).reshape(dst.shape)


def copy_from_numpy(t: Tensor, arr) -> None:
    t.copy_from_numpy(arr)


def _unary(fn):
    def g(t):
        return _wrap(fn(_raw(t)), t)
    return g


abs = _unary(jnp.abs)  # noqa: A001 - parity with reference module API
exp = _unary(jnp.exp)
ceil = _unary(jnp.ceil)
log = _unary(jnp.log)
sign = _unary(jnp.sign)
sqrt = _unary(jnp.sqrt)
square = _unary(jnp.square)
tanh = _unary(jnp.tanh)


def sigmoid(t):
    return _wrap(jax.nn.sigmoid(_raw(t)), t)


def relu(t):
    return _wrap(jax.nn.relu(_raw(t)), t)


def sum(t, axis=None, out=None):  # noqa: A001
    r = jnp.sum(_raw(t), axis=axis)
    if out is not None:
        out.data = r
        return out
    return _wrap(r, t) if r.ndim else float(r)


def pow(t, x, out=None):  # noqa: A001
    r = _raw(t) ** _raw(x)
    if out is not None:
        out.data = r
        return out
    return _wrap(r, t)


def average(t, axis=None):
    r = jnp.mean(_raw(t), axis=axis)
    return _wrap(r, t) if r.ndim else float(r)


def softmax(t, out=None):
    r = jax.nn.softmax(_raw(t), axis=-1)
    if out is not None:
        out.data = r
        return out
    return _wrap(r, t)


def _cmp(fn):
    def g(t, x):
        return _wrap(fn(_raw(t), _raw(x)).astype(float32), t)
    return g


lt = _cmp(jnp.less)
le = _cmp(jnp.less_equal)
gt = _cmp(jnp.greater)
ge = _cmp(jnp.greater_equal)
eq = _cmp(jnp.equal)


def add(lhs, rhs, ret=None):
    r = _raw(lhs) + _raw(rhs)
    if ret is not None:
        ret.data = r
        return ret
    return _wrap(r, lhs if isinstance(lhs, Tensor) else rhs)


def sub(lhs, rhs, ret=None):
    r = _raw(lhs) - _raw(rhs)
    if ret is not None:
        ret.data = r
        return ret
    return _wrap(r, lhs if isinstance(lhs, Tensor) else rhs)


def eltwise_mult(lhs, rhs, ret=None):
    r = _raw(lhs) * _raw(rhs)
    if ret is not None:
        ret.data = r
        return ret
    return _wrap(r, lhs if isinstance(lhs, Tensor) else rhs)


def div(lhs, rhs, ret=None):
    r = _raw(lhs) / _raw(rhs)
    if ret is not None:
        ret.data = r
        return ret
    return _wrap(r, lhs if isinstance(lhs, Tensor) else rhs)


def mult(A, B, C=None, alpha=1.0, beta=0.0):
    """GEMM: C = alpha*A@B + beta*C (reference tensor.py Mult/GEMM)."""
    r = alpha * (_raw(A) @ _raw(B))
    if C is not None:
        r = r + beta * _raw(C)
        C.data = r
        return C
    return _wrap(r, A)


def axpy(alpha, x, y):
    """y += alpha * x, in place on y (cuBLAS axpy equivalent; the optimizer
    hot path, reference opt.py:269-310)."""
    y.data = y.data + alpha * _raw(x)
    return y


def einsum(ops, *args):
    arrs = [_raw(a) for a in args]
    like = next(a for a in args if isinstance(a, Tensor))
    return _wrap(jnp.einsum(ops, *arrs), like)


def tensordot(A, B, axes=2):
    return _wrap(jnp.tensordot(_raw(A), _raw(B), axes=axes), A)


def repeat(t, repeats, axis=None):
    return _wrap(jnp.repeat(_raw(t), repeats, axis=axis), t)


def concatenate(tensors, axis=0):
    arrs = [_raw(t) for t in tensors]
    return _wrap(jnp.concatenate(arrs, axis=axis), tensors[0])


def bernoulli(p, t: Tensor):
    return t.bernoulli(p)


def gaussian(mean, std, t: Tensor):
    return t.gaussian(mean, std)


def uniform(low, high, t: Tensor):
    return t.uniform(low, high)


def add_column(alpha, v, beta, M):
    """M = alpha*v (as column, broadcast) + beta*M."""
    M.data = alpha * _raw(v)[:, None] + beta * M.data
    return M


def add_row(alpha, v, beta, M):
    M.data = alpha * _raw(v)[None, :] + beta * M.data
    return M


def sum_columns(M):
    return _wrap(jnp.sum(_raw(M), axis=1), M)


def sum_rows(M):
    return _wrap(jnp.sum(_raw(M), axis=0), M)


def to_host_array(arr):
    """Host numpy copy of a (possibly mesh-sharded) jax array. Under
    multi-process training an array sharded across hosts is gathered over
    the process group first (collective: every participating process must
    call this together); replicated or locally-addressable arrays copy
    directly. (Distinct from the reference-parity ``to_host(t)`` above,
    which moves a Tensor to the host device.)"""
    if hasattr(arr, "sharding") and \
            not getattr(arr, "is_fully_addressable", True) and \
            not getattr(arr, "is_fully_replicated", False):
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(arr,
                                                            tiled=True))
    return np.asarray(jax.device_get(arr))


def to_host_tree(named):
    """Host copies of a dict of arrays, batching the cross-process
    gathers of every host-sharded entry into ONE collective (a checkpoint
    with N sharded params pays one dispatch, not N)."""
    from jax.experimental import multihost_utils
    out = {}
    sharded = {}
    for k, a in named.items():
        if hasattr(a, "sharding") and \
                not getattr(a, "is_fully_addressable", True) and \
                not getattr(a, "is_fully_replicated", False):
            sharded[k] = a
        else:
            out[k] = np.asarray(jax.device_get(a))
    if sharded:
        gathered = multihost_utils.process_allgather(sharded, tiled=True)
        for k, v in gathered.items():
            out[k] = np.asarray(v)
    return out
