"""Wire-compatible minimal ONNX protos (see onnx.proto)."""

from .onnx_pb2 import (AttributeProto, GraphProto, ModelProto, NodeProto,
                       OperatorSetIdProto, StringStringEntryProto,
                       TensorProto, TensorShapeProto, TypeProto,
                       ValueInfoProto)

__all__ = [
    "AttributeProto", "GraphProto", "ModelProto", "NodeProto",
    "OperatorSetIdProto", "StringStringEntryProto", "TensorProto",
    "TensorShapeProto", "TypeProto", "ValueInfoProto",
]
