"""Caffe model import (capability parity with the reference's vendored
caffe schema, src/proto/caffe.proto — the reference ships the proto but no
wired converter; here the import path is real and tested).

``load(prototxt[, caffemodel])`` parses a Caffe net definition (protobuf
text format) plus optional trained weights (binary ``NetParameter``) and
returns a :class:`CaffeNet` — a normal :class:`~singa_tpu.model.Model`
whose forward chains our layers, so the imported net jits, trains, and
exports to ONNX like a native model.

Supported layer types: Convolution, Pooling (MAX/AVE, global), InnerProduct,
ReLU (incl. negative_slope), Sigmoid, TanH, Softmax, Dropout, Flatten, LRN,
BatchNorm (+ folded Scale), Eltwise-free linear chains. Data/Input layers
define the input; unknown config fields are skipped by protobuf.
"""

from __future__ import annotations

import numpy as np

from google.protobuf import text_format

from . import layer as layer_mod
from . import autograd
from .caffe_proto import caffe_pb2
from .model import Model
from .tensor import Tensor


_SKIP_TYPES = {"Data", "Input", "Accuracy", "SoftmaxWithLoss", "Silence"}


class CaffeNet(Model):
    """A linear chain of converted layers (AlexNet/LeNet-style caffe nets
    are sequential; branching nets are out of scope, as in the reference)."""

    def __init__(self, entries):
        super().__init__()
        self._entries = entries          # [(name, callable-or-layer)]
        for i, (name, fn) in enumerate(entries):
            if isinstance(fn, layer_mod.Layer):
                setattr(self, f"l{i}_{name}".replace(".", "_"), fn)

    def forward(self, x):
        for _name, fn in self._entries:
            x = fn(x)
        return x

    def train_one_batch(self, x, y):
        out = self.forward(x)
        loss = autograd.softmax_cross_entropy(out, y)
        self.optimizer(loss)
        return out, loss


def _pair_of(param, scalar_field, h_field, w_field, default):
    """Caffe geometry: a (possibly repeated) base field OR explicit
    _h/_w overrides. Conv uses repeated fields, Pooling scalars."""
    if param.HasField(h_field):
        return (getattr(param, h_field), getattr(param, w_field))
    v = getattr(param, scalar_field)
    vals = list(v) if not isinstance(v, int) else ([v] if v else [])
    if vals:
        return (vals[0], vals[0]) if len(vals) == 1 else tuple(vals[:2])
    return default


def _convert_layer(lp):
    """LayerParameter -> (callable, param_loader) or None to skip."""
    ty = lp.type
    if ty in _SKIP_TYPES:
        return None
    if ty == "Convolution":
        p = lp.convolution_param
        ks = _pair_of(p, "kernel_size", "kernel_h", "kernel_w", (3, 3))
        st = _pair_of(p, "stride", "stride_h", "stride_w", (1, 1))
        pad = _pair_of(p, "pad", "pad_h", "pad_w", (0, 0))
        dil = list(p.dilation) or [1]
        conv = layer_mod.Conv2d(p.num_output, ks, stride=st, padding=pad,
                                dilation=(dil[0], dil[0]) if len(dil) == 1
                                else tuple(dil[:2]),
                                group=p.group, bias=p.bias_term)

        def load(blobs, lay=conv, pp=p):
            lay.W.copy_from_numpy(blobs[0])      # (out, in/g, kh, kw)
            if pp.bias_term and len(blobs) > 1:
                lay.b.copy_from_numpy(blobs[1])
        return conv, load
    if ty == "Pooling":
        p = lp.pooling_param
        if p.global_pooling:
            if p.pool == caffe_pb2.PoolingParameter.AVE:
                return (lambda x: autograd.globalaveragepool(x)), None
            raise NotImplementedError("global MAX pooling")
        ks = _pair_of(p, "kernel_size", "kernel_h", "kernel_w", (2, 2))
        st = _pair_of(p, "stride", "stride_h", "stride_w", (1, 1))
        pad = (p.pad_h or p.pad, p.pad_w or p.pad)
        cls = layer_mod.MaxPool2d \
            if p.pool == caffe_pb2.PoolingParameter.MAX \
            else layer_mod.AvgPool2d
        return cls(ks, st, pad), None
    if ty == "InnerProduct":
        p = lp.inner_product_param
        fc = layer_mod.Linear(p.num_output, bias=p.bias_term)
        flat = layer_mod.Flatten()

        def apply(x, fc=fc, flat=flat):
            if len(x.shape) > 2:
                x = flat(x)          # caffe IP flattens from axis 1
            return fc(x)

        def load(blobs, lay=fc, pp=p):
            W = blobs[0]             # caffe: (out, in)
            lay.W.copy_from_numpy(np.ascontiguousarray(W.T)
                                  if not pp.transpose else W)
            if pp.bias_term and len(blobs) > 1:
                lay.b.copy_from_numpy(blobs[1])
        apply._layers = (flat, fc)
        return apply, load
    if ty == "ReLU":
        slope = lp.relu_param.negative_slope
        if slope:
            return (lambda x, s=slope: autograd.leakyrelu(x, s)), None
        return layer_mod.ReLU(), None
    if ty == "Sigmoid":
        return layer_mod.Sigmoid(), None
    if ty == "TanH":
        return layer_mod.Tanh(), None
    if ty == "Softmax":
        return layer_mod.SoftMax(), None
    if ty == "Dropout":
        return layer_mod.Dropout(lp.dropout_param.dropout_ratio), None
    if ty == "Flatten":
        return layer_mod.Flatten(lp.flatten_param.axis), None
    if ty == "LRN":
        p = lp.lrn_param
        return layer_mod.LRN(p.local_size, p.alpha, p.beta, p.k), None
    if ty == "BatchNorm":
        p = lp.batch_norm_param
        bn = layer_mod.BatchNorm2d(momentum=p.moving_average_fraction)

        def load(blobs, lay=bn):
            # caffe blobs: mean, var, scale_factor (a 1-element blob)
            sf = blobs[2][0] if len(blobs) > 2 and blobs[2].size else 1.0
            sf = 1.0 / sf if sf != 0 else 1.0
            lay.running_mean.copy_from_numpy(
                np.asarray(blobs[0] * sf, np.float32))
            lay.running_var.copy_from_numpy(
                np.asarray(blobs[1] * sf, np.float32))
        return bn, load
    if ty == "Scale":
        p = lp.scale_param
        # standalone channel-wise scale after BatchNorm: gamma (+ beta)
        state = {}

        def apply(x, state=state):
            g = state.get("gamma")
            if g is None:
                c = x.shape[1]
                state["gamma"] = g = Tensor(
                    data=np.ones((1, c, 1, 1), np.float32),
                    device=x.device, requires_grad=True, stores_grad=True)
                state["beta"] = Tensor(
                    data=np.zeros((1, c, 1, 1), np.float32),
                    device=x.device, requires_grad=True, stores_grad=True)
            y = autograd.mul(x, g)
            if state.get("beta") is not None:
                y = autograd.add(y, state["beta"])
            return y

        def load(blobs, state=state, pp=p):
            c = blobs[0].size
            state["gamma"] = Tensor(
                data=blobs[0].reshape(1, c, 1, 1).astype(np.float32),
                requires_grad=True, stores_grad=True)
            beta = blobs[1] if pp.bias_term and len(blobs) > 1 \
                else np.zeros(c, np.float32)
            state["beta"] = Tensor(
                data=np.asarray(beta).reshape(1, c, 1, 1).astype(
                    np.float32),
                requires_grad=True, stores_grad=True)
        return apply, load
    raise NotImplementedError(f"caffe layer type {ty!r}")


class CaffeConverter:
    """Parse + convert (the role of the reference lineage's converter over
    its caffe.proto)."""

    def __init__(self, net_proto, caffemodel_path=None):
        if isinstance(net_proto, caffe_pb2.NetParameter):
            self.net = net_proto
        else:
            with open(net_proto) as f:
                self.net = text_format.Parse(f.read(),
                                             caffe_pb2.NetParameter())
        self.weights = None
        if caffemodel_path is not None:
            self.weights = caffe_pb2.NetParameter()
            if isinstance(caffemodel_path, (bytes, bytearray)):
                self.weights.ParseFromString(caffemodel_path)
            else:
                with open(caffemodel_path, "rb") as f:
                    self.weights.ParseFromString(f.read())

    def input_shape(self):
        n = self.net
        if n.input_shape:
            return tuple(n.input_shape[0].dim)
        if n.input_dim:
            return tuple(n.input_dim)
        return None

    def create_net(self):
        entries, loaders = [], {}
        for lp in self.net.layer:
            conv = _convert_layer(lp)
            if conv is None:
                continue
            fn, loader = conv
            entries.append((lp.name, fn))
            if loader is not None:
                loaders[lp.name] = loader
        net = CaffeNet(entries)
        net._param_loaders = loaders
        return net

    def load_weights(self, net, x):
        """Materialise layer params (one forward on ``x``) then copy the
        caffemodel blobs in, matched by layer name."""
        if self.weights is None:
            return net
        net.forward(x)
        by_name = {lp.name: lp for lp in self.weights.layer}
        for name, loader in net._param_loaders.items():
            lp = by_name.get(name)
            if lp is None or not lp.blobs:
                continue
            blobs = []
            for b in lp.blobs:
                arr = np.asarray(b.data, np.float32)
                dims = tuple(b.shape.dim) if b.shape.dim else tuple(
                    d for d in (b.num, b.channels, b.height, b.width) if d)
                blobs.append(arr.reshape(dims) if dims else arr)
            loader(blobs)
        return net


def load(prototxt, caffemodel=None, sample_input=None):
    """One-call import: returns a ready CaffeNet; when ``caffemodel`` and
    ``sample_input`` are given the trained weights are loaded."""
    cv = CaffeConverter(prototxt, caffemodel)
    net = cv.create_net()
    if caffemodel is not None:
        if sample_input is None:
            shape = cv.input_shape()
            if shape is None:
                raise ValueError("pass sample_input (or declare input_shape "
                                 "in the prototxt) to load weights")
            sample_input = Tensor(
                data=np.zeros(shape, np.float32), requires_grad=False)
        cv.load_weights(net, sample_input)
    return net
