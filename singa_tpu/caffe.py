"""Caffe model import (capability parity with the reference's vendored
caffe schema, src/proto/caffe.proto — the reference ships the proto but no
wired converter; here the import path is real and tested).

``load(prototxt[, caffemodel])`` parses a Caffe net definition (protobuf
text format) plus optional trained weights (binary ``NetParameter``) and
returns a :class:`CaffeNet` — a normal :class:`~singa_tpu.model.Model`
whose forward chains our layers, so the imported net jits, trains,
checkpoints (all converted params appear in ``get_states``), and exports
to ONNX like a native model.

Supported layer types: Convolution, Pooling (MAX/AVE, global, caffe's CEIL
output sizing), InnerProduct, ReLU (incl. negative_slope), Sigmoid, TanH,
Softmax, Dropout, Flatten, LRN, BatchNorm (eps/use_global_stats honored)
+ Scale pairs. Data/Input layers define the input; unknown config fields
are skipped by protobuf.
"""

from __future__ import annotations

import numpy as np

from google.protobuf import text_format

from . import layer as layer_mod
from . import autograd
from .caffe_proto import caffe_pb2
from .model import Model
from .tensor import Tensor


_SKIP_TYPES = {"Data", "Input", "Accuracy", "SoftmaxWithLoss", "Silence"}


class CaffeNet(Model):
    """A linear chain of converted layers (AlexNet/LeNet-style caffe nets
    are sequential; branching nets are out of scope, as in the reference)."""

    def __init__(self, entries):
        super().__init__()
        self._entries = entries          # [(name, callable-or-layer)]
        for i, (name, fn) in enumerate(entries):
            if isinstance(fn, layer_mod.Layer):
                setattr(self, f"l{i}_{name}".replace(".", "_"), fn)

    def forward(self, x):
        for _name, fn in self._entries:
            x = fn(x)
        return x

    def train_one_batch(self, x, y):
        # deploy-style prototxts end in a Softmax layer; train on the
        # LOGITS (softmax_cross_entropy applies its own softmax) and
        # return the probabilities the net advertises
        entries = self._entries
        has_prob = entries and isinstance(entries[-1][1], layer_mod.SoftMax)
        body = entries[:-1] if has_prob else entries
        out = x
        for _name, fn in body:
            out = fn(out)
        loss = autograd.softmax_cross_entropy(out, y)
        self.optimizer(loss)
        if has_prob:
            out = entries[-1][1](out)
        return out, loss


class _CaffeInnerProduct(layer_mod.Layer):
    """caffe InnerProduct: implicit flatten from axis 1, W is (out, in)."""

    def __init__(self, p):
        super().__init__()
        self.flat = layer_mod.Flatten()
        self.fc = layer_mod.Linear(p.num_output, bias=p.bias_term)
        self.transpose = bool(p.transpose)

    def forward(self, x):
        if len(x.shape) > 2:
            x = self.flat(x)
        return self.fc(x)

    def load_blobs(self, blobs):
        W = blobs[0]                     # caffe: (out, in)
        self.fc.W.copy_from_numpy(W if self.transpose
                                  else np.ascontiguousarray(W.T))
        if self.fc.bias and len(blobs) > 1:
            self.fc.b.copy_from_numpy(blobs[1])


class _CaffeScale(layer_mod.Layer):
    """caffe Scale: per-channel gamma (+ beta), usually after BatchNorm."""

    def __init__(self, bias_term):
        super().__init__()
        self.bias_term = bool(bias_term)

    def initialize(self, x):
        c = x.shape[1]
        dev = x.device
        self.gamma = Tensor(data=np.ones((1, c, 1, 1), np.float32),
                            device=dev, requires_grad=True,
                            stores_grad=True)
        self.beta = Tensor(data=np.zeros((1, c, 1, 1), np.float32),
                           device=dev, requires_grad=True, stores_grad=True)

    def forward(self, x):
        y = autograd.mul(x, self.gamma)
        return autograd.add(y, self.beta) if self.bias_term else y

    def load_blobs(self, blobs):
        c = blobs[0].size
        self.gamma.copy_from_numpy(
            blobs[0].reshape(1, c, 1, 1).astype(np.float32))
        if self.bias_term and len(blobs) > 1:
            self.beta.copy_from_numpy(
                np.asarray(blobs[1]).reshape(1, c, 1, 1).astype(np.float32))

    def _own_params(self):
        p = {"gamma": self.gamma}
        if self.bias_term:
            p["beta"] = self.beta
        return p


class _CaffePool(layer_mod.Layer):
    """caffe pooling computes output sizes with CEIL; reproduce it with
    asymmetric extra padding so the window grid matches exactly (MAX pads
    with -inf, AVE with zeros and caffe's count-include-pad division)."""

    def __init__(self, is_max, ks, st, pad):
        super().__init__()
        self.is_max = is_max
        self.ks, self.st, self.pad = ks, st, pad

    def initialize(self, x):
        (kh, kw), (sh, sw), (ph, pw) = self.ks, self.st, self.pad
        h, w = x.shape[2], x.shape[3]
        eh = (sh - (h + 2 * ph - kh) % sh) % sh
        ew = (sw - (w + 2 * pw - kw) % sw) % sw
        self.pool = layer_mod.Pooling2d(
            (kh, kw), (sh, sw), ((ph, ph + eh), (pw, pw + ew)),
            is_max=self.is_max)

    def forward(self, x):
        return self.pool(x)


def _pair_of(param, scalar_field, h_field, w_field, default):
    """Caffe geometry: a (possibly repeated) base field OR explicit
    _h/_w overrides. Conv uses repeated fields, Pooling scalars."""
    if param.HasField(h_field):
        return (getattr(param, h_field), getattr(param, w_field))
    v = getattr(param, scalar_field)
    vals = list(v) if not isinstance(v, int) else ([v] if v else [])
    if vals:
        return (vals[0], vals[0]) if len(vals) == 1 else tuple(vals[:2])
    return default


def _convert_layer(lp):
    """LayerParameter -> Layer/callable, or None to skip. Layers with
    loadable caffemodel blobs expose ``load_blobs``."""
    ty = lp.type
    if ty in _SKIP_TYPES:
        return None
    if ty == "Convolution":
        p = lp.convolution_param
        ks = _pair_of(p, "kernel_size", "kernel_h", "kernel_w", (3, 3))
        st = _pair_of(p, "stride", "stride_h", "stride_w", (1, 1))
        pad = _pair_of(p, "pad", "pad_h", "pad_w", (0, 0))
        dil = list(p.dilation) or [1]
        conv = layer_mod.Conv2d(p.num_output, ks, stride=st, padding=pad,
                                dilation=(dil[0], dil[0]) if len(dil) == 1
                                else tuple(dil[:2]),
                                group=p.group, bias=p.bias_term)

        def load(blobs, lay=conv, pp=p):
            lay.W.copy_from_numpy(blobs[0])      # (out, in/g, kh, kw)
            if pp.bias_term and len(blobs) > 1:
                lay.b.copy_from_numpy(blobs[1])
        conv.load_blobs = load
        return conv
    if ty == "Pooling":
        p = lp.pooling_param
        if p.global_pooling:
            if p.pool == caffe_pb2.PoolingParameter.AVE:
                return lambda x: autograd.globalaveragepool(x)
            raise NotImplementedError("global MAX pooling")
        ks = _pair_of(p, "kernel_size", "kernel_h", "kernel_w", (2, 2))
        st = _pair_of(p, "stride", "stride_h", "stride_w", (1, 1))
        pad = (p.pad_h or p.pad, p.pad_w or p.pad)
        return _CaffePool(p.pool == caffe_pb2.PoolingParameter.MAX,
                          ks, st, pad)
    if ty == "InnerProduct":
        return _CaffeInnerProduct(lp.inner_product_param)
    if ty == "ReLU":
        slope = lp.relu_param.negative_slope
        if slope:
            return lambda x, s=slope: autograd.leakyrelu(x, s)
        return layer_mod.ReLU()
    if ty == "Sigmoid":
        return layer_mod.Sigmoid()
    if ty == "TanH":
        return layer_mod.Tanh()
    if ty == "Softmax":
        return layer_mod.SoftMax()
    if ty == "Dropout":
        return layer_mod.Dropout(lp.dropout_param.dropout_ratio)
    if ty == "Flatten":
        return layer_mod.Flatten(lp.flatten_param.axis)
    if ty == "LRN":
        p = lp.lrn_param
        return layer_mod.LRN(p.local_size, p.alpha, p.beta, p.k)
    if ty == "BatchNorm":
        p = lp.batch_norm_param
        freeze = p.HasField("use_global_stats") and p.use_global_stats
        bn = layer_mod.BatchNorm2d(momentum=p.moving_average_fraction,
                                   eps=p.eps, freeze_stats=freeze)

        def load(blobs, lay=bn):
            # caffe blobs: mean, var, scale_factor (a 1-element blob)
            sf = blobs[2][0] if len(blobs) > 2 and blobs[2].size else 1.0
            sf = 1.0 / sf if sf != 0 else 1.0
            lay.running_mean.copy_from_numpy(
                np.asarray(blobs[0] * sf, np.float32))
            lay.running_var.copy_from_numpy(
                np.asarray(blobs[1] * sf, np.float32))
        bn.load_blobs = load
        return bn
    if ty == "Scale":
        return _CaffeScale(lp.scale_param.bias_term)
    raise NotImplementedError(f"caffe layer type {ty!r}")


class CaffeConverter:
    """Parse + convert (the role of the reference lineage's converter over
    its caffe.proto)."""

    def __init__(self, net_proto, caffemodel_path=None):
        if isinstance(net_proto, caffe_pb2.NetParameter):
            self.net = net_proto
        else:
            with open(net_proto) as f:
                self.net = text_format.Parse(f.read(),
                                             caffe_pb2.NetParameter())
        self.weights = None
        if caffemodel_path is not None:
            self.weights = caffe_pb2.NetParameter()
            if isinstance(caffemodel_path, (bytes, bytearray)):
                self.weights.ParseFromString(caffemodel_path)
            else:
                with open(caffemodel_path, "rb") as f:
                    self.weights.ParseFromString(f.read())

    def input_shape(self):
        n = self.net
        if n.input_shape:
            return tuple(n.input_shape[0].dim)
        if n.input_dim:
            return tuple(n.input_dim)
        return None

    def create_net(self):
        entries = []
        for lp in self.net.layer:
            fn = _convert_layer(lp)
            if fn is not None:
                entries.append((lp.name, fn))
        return CaffeNet(entries)

    def load_weights(self, net, x):
        """Materialise layer params (one forward on ``x``) then copy the
        caffemodel blobs in, matched by layer name."""
        if self.weights is None:
            return net
        net.forward(x)
        by_name = {lp.name: lp for lp in self.weights.layer}
        for name, fn in net._entries:
            loader = getattr(fn, "load_blobs", None)
            lp = by_name.get(name)
            if loader is None or lp is None or not lp.blobs:
                continue
            blobs = []
            for b in lp.blobs:
                arr = np.asarray(b.data, np.float32)
                dims = tuple(b.shape.dim) if b.shape.dim else tuple(
                    d for d in (b.num, b.channels, b.height, b.width) if d)
                blobs.append(arr.reshape(dims) if dims else arr)
            loader(blobs)
        return net


def load(prototxt, caffemodel=None, sample_input=None):
    """One-call import: returns a ready CaffeNet; when ``caffemodel`` and
    ``sample_input`` are given the trained weights are loaded."""
    cv = CaffeConverter(prototxt, caffemodel)
    net = cv.create_net()
    if caffemodel is not None:
        if sample_input is None:
            shape = cv.input_shape()
            if shape is None:
                raise ValueError("pass sample_input (or declare input_shape "
                                 "in the prototxt) to load weights")
            sample_input = Tensor(
                data=np.zeros(shape, np.float32), requires_grad=False)
        cv.load_weights(net, sample_input)
    return net
