"""singa_tpu — a TPU-native deep learning framework.

A from-scratch, idiomatic JAX/XLA/Pallas re-design with the capabilities of
Apache SINGA (reference layer map in SURVEY.md). Currently shipped: the
Tensor/Device core, a define-by-run autograd engine whose graph mode is
``jax.jit``, the layer / model / optimizer Python API (with checkpoint
save/load on Model), and a distributed optimizer on mesh collectives.

Import style matches the reference package (``from singa import ...`` →
``from singa_tpu import ...``).
"""

__version__ = "0.1.0"

from . import device        # noqa: F401
from . import tensor        # noqa: F401
from . import autograd      # noqa: F401
from . import layer         # noqa: F401
from . import model         # noqa: F401
from . import opt           # noqa: F401
from . import initializer   # noqa: F401
from . import ops           # noqa: F401
from . import parallel      # noqa: F401

from .tensor import Tensor  # noqa: F401
from .model import Model    # noqa: F401
