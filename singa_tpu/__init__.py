"""singa_tpu — a TPU-native deep learning framework.

A from-scratch, idiomatic JAX/XLA/Pallas re-design with the capabilities of
Apache SINGA (reference layer map in SURVEY.md): the Tensor/Device core, a
define-by-run autograd engine whose graph mode is ``jax.jit``, the layer /
model / optimizer Python API (with checkpoint save/load on Model), a
distributed optimizer on mesh collectives, ONNX import/export, a native
C++ IO runtime (record files, codecs, image transforms), snapshot
checkpoints, data pipelines, metrics, and a Sequential-style trainer.

Import style matches the reference package (``from singa import ...`` →
``from singa_tpu import ...``). Heavier subsystems (sonnx, io, data,
image_tool, net, snapshot) import lazily via __getattr__.
"""

__version__ = "0.1.0"

from . import device        # noqa: F401
from . import tensor        # noqa: F401
from . import autograd      # noqa: F401
from . import layer         # noqa: F401
from . import model         # noqa: F401
from . import opt           # noqa: F401
from . import initializer   # noqa: F401
from . import ops           # noqa: F401
from . import parallel      # noqa: F401
from . import metric        # noqa: F401
from . import utils         # noqa: F401
from . import mixed_precision  # noqa: F401

from .tensor import Tensor  # noqa: F401
from .model import Model    # noqa: F401
from .mixed_precision import Policy  # noqa: F401

_LAZY = ("sonnx", "io", "data", "datasets", "image_tool", "net",
         "snapshot", "native", "channel", "caffe", "network",
         "checkpoint", "profiling", "resilience", "observability",
         "serving", "aot")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
