"""Define-by-run autograd engine + the ~120-op surface, on XLA.

Capability parity with the reference engine (python/singa/autograd.py):

- ``Operator._do_forward`` records ``src`` links exactly like
  autograd.py:270-314;
- ``infer_dependency`` ref-counts the upstream graph (autograd.py:71-102);
- ``backward(y, dy)`` is a lazy generator yielding ``(param, grad)`` in
  reverse-topological order (autograd.py:128-224) so optimizers can overlap
  update (and, distributed, all-reduce) with the rest of backward.

TPU-first redesign: every ``forward`` is a pure ``jax.numpy`` function, so a
whole train step (forward + this tape + optimizer) traces under ``jax.jit``
into one XLA computation — the reference's buffered C++ Graph
(src/core/scheduler/scheduler.cc) becomes XLA scheduling/fusion for free.
Backward rules default to ``jax.vjp`` of the op's own forward, which is both
exactly consistent with forward and XLA-fused; ops override ``backward`` only
when vjp semantics are not what the reference specifies.
"""

from __future__ import annotations

import sys
import types

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor
from .autograd_base import (CTX, Operator, Dummy, backward, gradients,
                            infer_dependency, is_training, set_training,
                            _raw)
# ops cast compute operands / upcast fragile reductions through the ONE
# precision-contract module (f32-accumulate discipline lives there)
from .mixed_precision import cast_compute as _cast_compute
from .mixed_precision import accum_f32 as _f32a


class _AutogradModule(types.ModuleType):
    """Lets reference-style ``autograd.training = True`` toggle the shared
    engine context (CTX) that ops and the Model layer consult."""

    @property
    def training(self):
        return CTX.training

    @training.setter
    def training(self, flag):
        CTX.training = bool(flag)


sys.modules[__name__].__class__ = _AutogradModule


# ===========================================================================
# Op library. Classes mirror reference names; snake_case functional wrappers
# below. Forward bodies are jax.numpy; backwards default to vjp.
# ===========================================================================

# ---- arithmetic -----------------------------------------------------------

class Add(Operator):
    def forward(self, a, b):
        return a + b


class Sub(Operator):
    def forward(self, a, b):
        return a - b


class Mul(Operator):
    def forward(self, a, b):
        return a * b


class Div(Operator):
    def forward(self, a, b):
        return a / b


class Pow(Operator):
    def forward(self, a, b):
        return a ** b


class Negative(Operator):
    def forward(self, x):
        return -x


class Reciprocal(Operator):
    def forward(self, x):
        return 1.0 / x


class AddBias(Operator):
    """y = x + b broadcast along an axis (reference autograd.AddBias)."""

    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def forward(self, x, b):
        # policy discipline: a bias is not numerically fragile — under a
        # 16-bit policy it joins the activation's precision instead of
        # silently upcasting the whole activation back to its own
        x, b = _cast_compute(x, b)
        if self.axis == 0:
            return x + b.reshape((1,) + b.shape)
        return x + b.reshape(b.shape + (1,) * (x.ndim - 1 - self.axis))


class Matmul(Operator):
    def forward(self, a, b):
        # under an active precision policy both operands enter the MXU
        # in the compute dtype (fp32 masters are cast at the use site;
        # the vjp casts the weight gradient back up automatically)
        a, b = _cast_compute(a, b)
        return jnp.matmul(a, b)


class Gemm(Operator):
    """alpha*A'@B' + beta*C (reference autograd.Gemm, onnx Gemm)."""

    def __init__(self, alpha=1.0, beta=1.0, transA=0, transB=0):
        super().__init__()
        self.alpha, self.beta = alpha, beta
        self.transA, self.transB = transA, transB

    def forward(self, A, B, C=None):
        A, B, C = _cast_compute(A, B, C)
        a = A.T if self.transA else A
        b = B.T if self.transB else B
        y = self.alpha * (a @ b)
        if C is not None:
            y = y + self.beta * C
        return y


class Sum(Operator):
    """Elementwise sum of N tensors (reference autograd.Sum)."""

    def forward(self, *xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out


# ---- unary math -----------------------------------------------------------

def _unary_op(name, fn):
    return type(name, (Operator,), {"forward": staticmethod(fn)})


Abs = _unary_op("Abs", jnp.abs)
Exp = _unary_op("Exp", jnp.exp)
Log = _unary_op("Log", jnp.log)
Sqrt = _unary_op("Sqrt", jnp.sqrt)
Sin = _unary_op("Sin", jnp.sin)
Cos = _unary_op("Cos", jnp.cos)
Tan = _unary_op("Tan", jnp.tan)
Sinh = _unary_op("Sinh", jnp.sinh)
Cosh = _unary_op("Cosh", jnp.cosh)
Asin = _unary_op("Asin", jnp.arcsin)
Acos = _unary_op("Acos", jnp.arccos)
Atan = _unary_op("Atan", jnp.arctan)
Asinh = _unary_op("Asinh", jnp.arcsinh)
Acosh = _unary_op("Acosh", jnp.arccosh)
Atanh = _unary_op("Atanh", jnp.arctanh)
Tanh = _unary_op("Tanh", jnp.tanh)
Erf = _unary_op("Erf", jax.scipy.special.erf)


class Ceil(Operator):
    differentiable = True

    def forward(self, x):
        return jnp.ceil(x)

    def backward(self, dy):
        return jnp.zeros_like(dy)


class Floor(Operator):
    def forward(self, x):
        return jnp.floor(x)

    def backward(self, dy):
        return jnp.zeros_like(dy)


class Round(Operator):
    def forward(self, x):
        return jnp.trunc(x + jnp.sign(x) * 0.5)  # round-half-away like ref

    def backward(self, dy):
        return jnp.zeros_like(dy)


class Rounde(Operator):
    """Round half to even (reference autograd.Rounde)."""

    def forward(self, x):
        return jnp.round(x)

    def backward(self, dy):
        return jnp.zeros_like(dy)


class Sign(Operator):
    def forward(self, x):
        return jnp.sign(x)

    def backward(self, dy):
        return jnp.zeros_like(dy)


# ---- activations ----------------------------------------------------------

class ReLU(Operator):
    def forward(self, x):
        return jnp.maximum(x, 0)


class LeakyRelu(Operator):
    def __init__(self, a=0.01):
        super().__init__()
        self.a = a

    def forward(self, x):
        return jnp.where(x >= 0, x, self.a * x)


class Elu(Operator):
    def __init__(self, alpha=1.0):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return jnp.where(x > 0, x, self.alpha * (jnp.exp(jnp.minimum(x, 0)) - 1))


class SeLU(Operator):
    def __init__(self, alpha=1.67326, gamma=1.0507):
        super().__init__()
        self.alpha, self.gamma = alpha, gamma

    def forward(self, x):
        return self.gamma * jnp.where(
            x > 0, x, self.alpha * (jnp.exp(jnp.minimum(x, 0)) - 1))


class Sigmoid(Operator):
    def forward(self, x):
        return jax.nn.sigmoid(x)


class SoftPlus(Operator):
    def forward(self, x):
        return jax.nn.softplus(x)


class SoftSign(Operator):
    def forward(self, x):
        return x / (1 + jnp.abs(x))


class HardSigmoid(Operator):
    def __init__(self, alpha=0.2, gamma=0.5):
        super().__init__()
        self.alpha, self.gamma = alpha, gamma

    def forward(self, x):
        return jnp.clip(self.alpha * x + self.gamma, 0.0, 1.0)


class PRelu(Operator):
    def forward(self, x, slope):
        return jnp.where(x >= 0, x, slope * x)


class SoftMax(Operator):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        # logsumexp accumulation stays f32 for 16-bit inputs (an 8-bit
        # mantissa sum over a wide axis loses the tail); the activation
        # keeps its precision class
        return jax.nn.softmax(_f32a(x), axis=self.axis).astype(x.dtype)


class GELU(Operator):
    """TPU extension (used by transformer models; not in reference op set)."""

    def forward(self, x):
        return jax.nn.gelu(x)


class LRN(Operator):
    """Across-channel local response normalisation on NCHW
    (reference src/model/layer/lrn.cc; AlexNet-era caffe semantics):
    y = x / (k + alpha/n * sum_{window n}(x^2))^beta."""

    def __init__(self, size=5, alpha=1e-4, beta=0.75, k=1.0):
        super().__init__()
        self.size = int(size)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.k = float(k)

    def forward(self, x):
        half = self.size // 2
        win = jax.lax.reduce_window(
            x * x, 0.0, jax.lax.add,
            window_dimensions=(1, self.size, 1, 1),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (half, self.size - 1 - half), (0, 0), (0, 0)))
        return x * jnp.power(self.k + self.alpha / self.size * win,
                             -self.beta)


def lrn(x, size=5, alpha=1e-4, beta=0.75, k=1.0):
    return LRN(size, alpha, beta, k)(x)


# ---- losses ---------------------------------------------------------------

class CrossEntropy(Operator):
    """-mean(sum(t * log(p))) with probabilities input
    (reference autograd.py cross_entropy:1212)."""

    def forward(self, x, t):
        t = jax.lax.stop_gradient(t)
        eps = 1e-10
        batch = x.shape[0]
        # loss reduction in f32 regardless of the net's compute dtype
        x, t = _f32a(x), _f32a(t)
        return -jnp.sum(t * jnp.log(x + eps)) / batch


class SoftMaxCrossEntropy(Operator):
    """Fused softmax + CE over logits (reference softmax_cross_entropy:1306).

    Targets may be one-hot (same shape) or integer class ids.
    """

    def forward(self, x, t):
        t = jax.lax.stop_gradient(t)
        # logsumexp + mean in f32: the fragile-op contract of 16-bit
        # policies (and of the plain bf16 input path)
        x = _f32a(x)
        logp = jax.nn.log_softmax(x, axis=-1)
        if t.shape == x.shape:
            ce = -jnp.sum(t * logp, axis=-1)
        else:
            tt = t.reshape(t.shape[0:1]) if t.ndim > 1 else t
            ce = -jnp.take_along_axis(
                logp, tt.astype(jnp.int32)[:, None], axis=-1)[:, 0]
        return jnp.mean(ce)


class MeanSquareError(Operator):
    """0.5 * mean over batch of ||x-t||^2 (reference mse_loss:1334)."""

    def forward(self, x, t):
        t = jax.lax.stop_gradient(t)
        batch = x.shape[0]
        return jnp.sum(jnp.square(_f32a(x) - _f32a(t))) / (2.0 * batch)


class BinaryCrossEntropy(Operator):
    def forward(self, x, t):
        t = jax.lax.stop_gradient(t)
        eps = 1e-10
        x, t = _f32a(x), _f32a(t)
        per = -(t * jnp.log(x + eps) + (1 - t) * jnp.log(1 - x + eps))
        return jnp.mean(jnp.sum(per.reshape(per.shape[0], -1), axis=-1))


class RankingLoss(Operator):
    """Margin ranking loss over (pos, neg) scores (reference
    ranking_loss:1266)."""

    def __init__(self, M=0.2):
        super().__init__()
        self.M = M

    def forward(self, pos, neg):
        return jnp.mean(jnp.maximum(self.M - (pos - neg), 0.0))


# ---- reductions / comparisons ---------------------------------------------

class ReduceSum(Operator):
    def __init__(self, axes=None, keepdims=1):
        super().__init__()
        self.axes = tuple(axes) if axes is not None else None
        self.keepdims = bool(keepdims)

    def forward(self, x):
        return jnp.sum(x, axis=self.axes, keepdims=self.keepdims)


class ReduceMean(Operator):
    def __init__(self, axes=None, keepdims=1):
        super().__init__()
        self.axes = tuple(axes) if axes is not None else None
        self.keepdims = bool(keepdims)

    def forward(self, x):
        return jnp.mean(x, axis=self.axes, keepdims=self.keepdims)


class ReduceMax(Operator):
    def __init__(self, axes=None, keepdims=1):
        super().__init__()
        self.axes = tuple(axes) if axes is not None else None
        self.keepdims = bool(keepdims)

    def forward(self, x):
        return jnp.max(x, axis=self.axes, keepdims=self.keepdims)


class ReduceProd(Operator):
    """Product reduction (ONNX ReduceProd — the reference reaches it only
    through its ONNX backend; no composition of sum/log covers negative
    or zero values, so it is a first-class op with a vjp backward)."""

    def __init__(self, axes=None, keepdims=1):
        super().__init__()
        self.axes = tuple(axes) if axes is not None else None
        self.keepdims = bool(keepdims)

    def forward(self, x):
        return jnp.prod(x, axis=self.axes, keepdims=self.keepdims)


class Mean(Operator):
    """Elementwise mean of N tensors (reference autograd.Mean)."""

    def forward(self, *xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out / len(xs)


class Max(Operator):
    def forward(self, *xs):
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out


class Min(Operator):
    def forward(self, *xs):
        out = xs[0]
        for x in xs[1:]:
            out = jnp.minimum(out, x)
        return out


class Clip(Operator):
    def __init__(self, min=None, max=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return jnp.clip(x, self.min, self.max)


def _cmp_op(name, fn):
    cls = type(name, (Operator,), {
        "forward": staticmethod(lambda *a, _f=fn: _f(*a).astype(jnp.float32))})
    cls.differentiable = False
    return cls


Less = _cmp_op("Less", jnp.less)
Greater = _cmp_op("Greater", jnp.greater)
Equal = _cmp_op("Equal", jnp.equal)
And = _cmp_op("And", lambda a, b: jnp.logical_and(a > 0, b > 0))
Or = _cmp_op("Or", lambda a, b: jnp.logical_or(a > 0, b > 0))
Xor = _cmp_op("Xor", lambda a, b: jnp.logical_xor(a > 0, b > 0))
Not = _cmp_op("Not", lambda a: jnp.logical_not(a > 0))


# ---- shape ops ------------------------------------------------------------

class Reshape(Operator):
    def __init__(self, shape):
        super().__init__()
        self.shape = tuple(int(s) for s in shape)

    def forward(self, x):
        return jnp.reshape(x, self.shape)


class Flatten(Operator):
    def __init__(self, axis=1):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        lead = int(np.prod(x.shape[:self.axis])) if self.axis else 1
        return jnp.reshape(x, (lead, -1))


class Transpose(Operator):
    def __init__(self, perm=None):
        super().__init__()
        self.perm = tuple(perm) if perm is not None else None

    def forward(self, x):
        return jnp.transpose(x, self.perm)


class Squeeze(Operator):
    def __init__(self, axis=None):
        super().__init__()
        self.axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def forward(self, x):
        return jnp.squeeze(x, self.axis)


class Unsqueeze(Operator):
    def __init__(self, axis):
        super().__init__()
        self.axis = axis if isinstance(axis, (list, tuple)) else [axis]

    def forward(self, x):
        for a in sorted(self.axis):
            x = jnp.expand_dims(x, a)
        return x


class Concat(Operator):
    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def forward(self, *xs):
        return jnp.concatenate(xs, axis=self.axis)


class Split(Operator):
    def __init__(self, axis, parts=None, num_output=None):
        super().__init__()
        self.axis = axis
        self.parts = parts
        self.num_output = num_output

    def forward(self, x):
        if self.parts is not None:
            idx = np.cumsum(self.parts)[:-1].tolist()
            return tuple(jnp.split(x, idx, axis=self.axis))
        return tuple(jnp.split(x, self.num_output, axis=self.axis))


class Slice(Operator):
    def __init__(self, starts, ends, axes=None, steps=None):
        super().__init__()
        self.starts, self.ends = list(starts), list(ends)
        self.axes = list(axes) if axes is not None else None
        self.steps = list(steps) if steps is not None else None

    def forward(self, x):
        axes = self.axes if self.axes is not None else list(range(len(self.starts)))
        steps = self.steps if self.steps is not None else [1] * len(self.starts)
        idx = [builtins_slice(None)] * x.ndim
        for s, e, a, st in zip(self.starts, self.ends, axes, steps):
            idx[a] = builtins_slice(s, e, st)
        return x[tuple(idx)]


builtins_slice = slice  # keep builtin reachable; `slice` fn below shadows it


class Gather(Operator):
    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def forward(self, x, indices):
        return jnp.take(x, indices.astype(jnp.int32), axis=self.axis)


class ScatterElements(Operator):
    def __init__(self, axis=0):
        super().__init__()
        self.axis = axis

    def forward(self, x, indices, updates):
        idx = indices.astype(jnp.int32)
        # build full index grids along every axis, replace on self.axis
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                             indexing="ij")
        grids[self.axis] = idx
        return x.at[tuple(grids)].set(updates)


class Tile(Operator):
    def __init__(self, repeats):
        super().__init__()
        self.repeats = repeats

    def forward(self, x):
        return jnp.tile(x, self.repeats)


class Expand(Operator):
    def __init__(self, shape):
        super().__init__()
        self.shape = tuple(int(s) for s in shape)

    def forward(self, x):
        return jnp.broadcast_to(x, jnp.broadcast_shapes(x.shape, self.shape))


class Pad(Operator):
    def __init__(self, mode, pads, constant=0.0):
        super().__init__()
        self.mode = mode
        self.pads = list(pads)
        self.constant = constant

    def forward(self, x):
        n = x.ndim
        width = [(self.pads[i], self.pads[i + n]) for i in range(n)]
        if self.mode == "constant":
            return jnp.pad(x, width, constant_values=self.constant)
        return jnp.pad(x, width, mode={"reflect": "reflect",
                                       "edge": "edge"}[self.mode])


class UpSample(Operator):
    """Nearest-neighbour upsample by integer scales (reference
    autograd.UpSample:5263)."""

    def __init__(self, mode="nearest", scales=None):
        super().__init__()
        assert mode.lower() == "nearest"
        self.scales = scales

    def forward(self, x):
        for axis, s in enumerate(self.scales):
            s = int(s)
            if s != 1:
                x = jnp.repeat(x, s, axis=axis)
        return x


class DepthToSpace(Operator):
    def __init__(self, blocksize, mode="DCR"):
        super().__init__()
        self.b = blocksize
        self.mode = mode

    def forward(self, x):
        N, C, H, W = x.shape
        b = self.b
        if self.mode == "DCR":
            y = x.reshape(N, b, b, C // (b * b), H, W)
            y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
        else:  # CRD
            y = x.reshape(N, C // (b * b), b, b, H, W)
            y = jnp.transpose(y, (0, 1, 4, 2, 5, 3))
        return y.reshape(N, C // (b * b), H * b, W * b)


class SpaceToDepth(Operator):
    def __init__(self, blocksize):
        super().__init__()
        self.b = blocksize

    def forward(self, x):
        N, C, H, W = x.shape
        b = self.b
        y = x.reshape(N, C, H // b, b, W // b, b)
        y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
        return y.reshape(N, C * b * b, H // b, W // b)


# ---- indexing / generation ------------------------------------------------

class Where(Operator):
    def forward(self, cond, a, b):
        return jnp.where(jax.lax.stop_gradient(cond) > 0, a, b)


class OneHot(Operator):
    def __init__(self, axis=-1, depth=None, values=(0.0, 1.0)):
        super().__init__()
        self.axis = axis
        self.depth = depth
        self.values = values

    differentiable = False

    def forward(self, indices):
        off, on = self.values
        oh = jax.nn.one_hot(indices.astype(jnp.int32), self.depth,
                            axis=self.axis)
        return oh * (on - off) + off


class Embedding(Operator):
    """Lookup rows of W by integer ids (reference autograd.Embedding:5648)."""

    def forward(self, x, W):
        y = jnp.take(W, jax.lax.stop_gradient(x).astype(jnp.int32), axis=0)
        # policy cast on the GATHERED rows, not the table: casting W
        # itself would materialise a full 16-bit copy of the (possibly
        # vocab-sized) table; ids are index-valued and never cast
        return _cast_compute(y)


class CosSim(Operator):
    def forward(self, a, b):
        num = jnp.sum(a * b, axis=-1)
        den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
        return num / (den + 1e-12)


class Shape(Operator):
    differentiable = False

    def forward(self, x):
        return jnp.asarray(x.shape, dtype=jnp.int32)


class ConstantOfShape(Operator):
    differentiable = False

    def __init__(self, value=0.0):
        super().__init__()
        self.value = value

    def forward(self, x):
        shape = tuple(int(v) for v in np.asarray(x))
        return jnp.full(shape, self.value, dtype=jnp.float32)


class NonZero(Operator):
    """Indices of nonzero entries. Dynamic-shaped ⇒ eager/host only (cannot
    run under jit; reference computes it on host too)."""

    differentiable = False

    def forward(self, x):
        idx = np.nonzero(np.asarray(jax.device_get(x)))
        return jnp.asarray(np.stack(idx), dtype=jnp.int64)


class Cast(Operator):
    differentiable = False

    def __init__(self, to):
        super().__init__()
        self.to = to

    def forward(self, x):
        return x.astype(self.to)


class Identity(Operator):
    def forward(self, x):
        return x


class AsType(Operator):
    """Differentiable dtype cast — the mixed-precision boundary op
    (bf16 activations below, f32 above). Unlike :class:`Cast` (which is
    for integer/config casts and blocks gradients), jax's vjp through
    ``astype`` casts the cotangent back to the source dtype, which is
    exactly the master-dtype accumulation semantics wanted here."""

    def __init__(self, to):
        super().__init__()
        self.to = to

    def forward(self, x):
        return x.astype(self.to)


class _LayerNorm(Operator):
    """Normalise over the trailing dim, then scale+shift (TPU extension
    used by the transformer family)."""

    def __init__(self, eps=1e-5):
        super().__init__()
        self.eps = eps

    def forward(self, x, scale, bias):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps) * scale + bias
        # norm math in f32; activations keep the input's precision class
        return y.astype(x.dtype)


def layernorm(x, scale, bias, eps=1e-5):
    return _LayerNorm(eps)(x, scale, bias)


class Dropout(Operator):
    def __init__(self, ratio=0.5):
        super().__init__()
        self.ratio = ratio

    def forward(self, x):
        if not is_training() or self.ratio <= 0.0:
            return x
        key = self.dev.rand_key()
        keep = 1.0 - self.ratio
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


# ===========================================================================
# functional wrappers (parity with reference snake_case API)
# ===========================================================================

def add(a, b):
    out = Add()(a, b)
    # residual-tail peephole tag (ops/fused_epilogue.py): a sum whose
    # operand is a tagged inference-BN output may fuse the whole
    # scale/shift + add + relu tail into one pass over the conv output
    # when a ReLU consumes it. One getattr per operand — the tag
    # itself costs one attribute; eligibility is decided at the ReLU.
    ta = getattr(a, "_bn_epilogue", None)
    tb = getattr(b, "_bn_epilogue", None)
    if ta is not None or tb is not None:
        # both-tagged (a downsample block adds two BN outputs): fuse
        # around ONE of them, the other's reference output is the
        # residual input
        tag, res = (ta, b) if ta is not None else (tb, a)
        out._bn_add_epilogue = (tag, res)
    return out


def sub(a, b):
    return Sub()(a, b)


def mul(a, b):
    return Mul()(a, b)


def div(a, b):
    return Div()(a, b)


def pow(a, b):  # noqa: A001
    return Pow()(a, b)


def negative(x):
    return Negative()(x)


def reciprocal(x):
    return Reciprocal()(x)


def add_bias(x, b, axis=0):
    return AddBias(axis)(x, b)


def matmul(a, b):
    return Matmul()(a, b)


def gemm(A, B, C=None, alpha=1.0, beta=1.0, transA=0, transB=0):
    if C is None:
        return Gemm(alpha, beta, transA, transB)(A, B)
    return Gemm(alpha, beta, transA, transB)(A, B, C)


def add_all(*xs):
    return Sum()(*xs)


def sum(*xs):  # noqa: A001  (reference autograd.sum = elementwise N-ary sum)
    return Sum()(*xs)


def abs(x):  # noqa: A001
    return Abs()(x)


def exp(x):
    return Exp()(x)


def log(x):
    return Log()(x)


def sqrt(x):
    return Sqrt()(x)


def sin(x):
    return Sin()(x)


def cos(x):
    return Cos()(x)


def tan(x):
    return Tan()(x)


def sinh(x):
    return Sinh()(x)


def cosh(x):
    return Cosh()(x)


def asin(x):
    return Asin()(x)


def acos(x):
    return Acos()(x)


def atan(x):
    return Atan()(x)


def asinh(x):
    return Asinh()(x)


def acosh(x):
    return Acosh()(x)


def atanh(x):
    return Atanh()(x)


def tanh(x):
    return Tanh()(x)


def erf(x):
    return Erf()(x)


def ceil(x):
    return Ceil()(x)


def floor(x):
    return Floor()(x)


def round(x):  # noqa: A001
    return Round()(x)


def rounde(x):
    return Rounde()(x)


def sign(x):
    return Sign()(x)


def relu(x):
    if getattr(x, "_bn_epilogue", None) is not None or \
            getattr(x, "_bn_add_epilogue", None) is not None:
        # a tagged inference-BN output (or a BN-output + residual sum)
        # may fuse scale/shift[+add]+relu into one pass over the conv
        # output (ops/fused_epilogue.py peephole; opt-in +
        # eligibility-gated — returns None to decline)
        from .ops import fused_epilogue
        fused = fused_epilogue.try_relu_epilogue(x)
        if fused is not None:
            return fused
    return ReLU()(x)


def leakyrelu(x, a=0.01):
    return LeakyRelu(a)(x)


def elu(x, alpha=1.0):
    return Elu(alpha)(x)


def selu(x, alpha=1.67326, gamma=1.0507):
    return SeLU(alpha, gamma)(x)


def sigmoid(x):
    return Sigmoid()(x)


def softplus(x):
    return SoftPlus()(x)


def softsign(x):
    return SoftSign()(x)


def hardsigmoid(x, alpha=0.2, gamma=0.5):
    return HardSigmoid(alpha, gamma)(x)


def prelu(x, slope):
    return PRelu()(x, slope)


def softmax(x, axis=1):
    return SoftMax(axis)(x)


def gelu(x):
    return GELU()(x)


def cross_entropy(y, t):
    return CrossEntropy()(y, t)


def softmax_cross_entropy(x, t):
    return SoftMaxCrossEntropy()(x, t)


def mse_loss(x, t):
    return MeanSquareError()(x, t)


def binary_cross_entropy(x, t):
    return BinaryCrossEntropy()(x, t)


def ranking_loss(pos, neg, M=0.2):
    return RankingLoss(M)(pos, neg)


def reduce_sum(x, axes=None, keepdims=1):
    return ReduceSum(axes, keepdims)(x)


def reduce_mean(x, axes=None, keepdims=1):
    return ReduceMean(axes, keepdims)(x)


def reduce_max(x, axes=None, keepdims=1):
    return ReduceMax(axes, keepdims)(x)


def reduce_prod(x, axes=None, keepdims=1):
    return ReduceProd(axes, keepdims)(x)


def mean(*xs):
    return Mean()(*xs)


def max(*xs):  # noqa: A001
    return Max()(*xs)


def min(*xs):  # noqa: A001
    return Min()(*xs)


def clip(x, min=None, max=None):  # noqa: A002
    return Clip(min, max)(x)


def less(a, b):
    return Less()(a, b)


def greater(a, b):
    return Greater()(a, b)


def equal(a, b):
    return Equal()(a, b)


def _and(a, b):
    return And()(a, b)


def _or(a, b):
    return Or()(a, b)


def _xor(a, b):
    return Xor()(a, b)


def _not(a):
    return Not()(a)


def reshape(x, shape):
    return Reshape(shape)(x)


def flatten(x, axis=1):
    return Flatten(axis)(x)


def transpose(x, shape=None):
    return Transpose(shape)(x)


def squeeze(x, axis=None):
    return Squeeze(axis)(x)


def unsqueeze(x, axis):
    return Unsqueeze(axis)(x)


def cat(xs, axis=0):
    return Concat(axis)(*xs)


def split(x, axis, parts=None, num_output=None):
    return Split(axis, parts, num_output)(x)


def slice(x, starts, ends, axes=None, steps=None):  # noqa: A001
    return Slice(starts, ends, axes, steps)(x)


def make_slice(x, axis, idx):
    """Take index ``idx`` along ``axis`` keeping dims (reference helper)."""
    return Slice([idx], [idx + 1], [axis])(x)


def gather(x, axis, indices):
    if isinstance(indices, (list, tuple, np.ndarray)):
        indices = Tensor(data=np.asarray(indices, dtype=np.int32),
                         requires_grad=False)
    return Gather(axis)(x, indices)


def scatter_elements(x, indices, updates, axis=0):
    return ScatterElements(axis)(x, indices, updates)


def tile(x, repeats):
    return Tile(repeats)(x)


def expand(x, shape):
    return Expand(shape)(x)


def pad(x, mode, pads, constant=0.0):
    return Pad(mode, pads, constant)(x)


def upsample(x, mode="nearest", scales=None):
    return UpSample(mode, scales)(x)


def depth_to_space(x, blocksize, mode="DCR"):
    return DepthToSpace(blocksize, mode)(x)


def space_to_depth(x, blocksize):
    return SpaceToDepth(blocksize)(x)


def where(cond, a, b):
    return Where()(cond, a, b)


def onehot(axis, indices, depth, values=(0.0, 1.0)):
    return OneHot(axis, depth, values)(indices)


def embedding(x, W):
    return Embedding()(x, W)


def cossim(a, b):
    return CosSim()(a, b)


def shape(x):
    return Shape()(x)


def constant_of_shape(x, value=0.0):
    return ConstantOfShape(value)(x)


def nonzero(x):
    return NonZero()(x)


def cast(x, to):
    return Cast(to)(x)


def astype(x, to):
    return AsType(to)(x)


def axis_helper(y_shape, x_shape):
    """Axes along which ``x_shape`` was broadcast to produce
    ``y_shape`` — the sum-reduction set for a broadcast backward
    (reference autograd.py:34)."""
    res = []
    j = len(x_shape) - 1
    for i in range(len(y_shape) - 1, -1, -1):
        if j < 0 or x_shape[j] != y_shape[i]:
            res.append(i)
        j -= 1
    return tuple(res[::-1])


def back_broadcast(y_shape, x_shape, x):
    """Reduce a broadcast result (cotangent) back to ``x_shape``: sum
    over the broadcast axes, then reshape (reference autograd.py:52).
    Accepts a Tensor or array; returns the same kind, preserving the
    Tensor's device and requires_grad metadata."""
    if tuple(y_shape) == tuple(x_shape):
        return x
    arr = x.data if isinstance(x, Tensor) else jnp.asarray(x)
    arr = jnp.sum(arr, axis=axis_helper(y_shape, x_shape)) \
        .reshape(tuple(x_shape))
    if isinstance(x, Tensor):
        return Tensor(data=arr, device=x.device,
                      requires_grad=x.requires_grad)
    return arr


def identity(x):
    return Identity()(x)


def dropout(x, ratio=0.5):
    return Dropout(ratio)(x)


def ctensor2numpy(x):
    return np.asarray(jax.device_get(_raw(x)))


class _Checkpointed(Operator):
    """Run a sub-network under ``jax.checkpoint``: its activations are NOT
    saved for backward — the block is recomputed from its inputs during the
    gradient pass. The TPU-first answer to activation memory on long
    sequences / deep stacks (trade FLOPs for HBM); no reference counterpart
    (SINGA recycles block buffers in its Graph scheduler instead,
    src/core/scheduler/scheduler.cc:671-688, which cannot help with
    autograd residuals).

    Params enter as explicit operator inputs so their gradients ride the
    ordinary tape; the device RNG is re-seeded from an input key inside the
    wrapped function so dropout masks agree between the forward and the
    recompute pass.
    """

    def __init__(self, run):
        super().__init__()
        self._run = run          # (x_arr, *param_arrs) -> out_arr, via ops
        self._ck = jax.checkpoint(self._pure)

    def _pure(self, key, x, *params):
        dev = self.dev
        saved = dev._get_rng_state()
        dev._set_rng_state(key)
        try:
            return self._run(x, *params)
        finally:
            dev._set_rng_state(saved)

    def forward(self, key, x, *params):
        return self._ck(key, x, *params)


def _aux_layers(block):
    """Layers in ``block``'s tree that stash an ``aux_loss`` Tensor during
    forward (MoE load-balance losses), in deterministic traversal order."""
    found = []

    def walk(l):
        if hasattr(l, "aux_loss"):
            found.append(l)
        for _name, sub in sorted(l._sublayers()):
            walk(sub)

    walk(block)
    return found


def checkpoint(block, x):
    """Apply ``block`` (a Layer) to Tensor ``x`` with rematerialized
    backward: ``y = checkpoint(blk, x)`` is numerically ``blk(x)`` but
    stores only the block's inputs, recomputing its inside during the
    gradient pass (``jax.checkpoint``).

    Auxiliary losses stashed by sublayers during forward (``aux_loss``
    attributes, e.g. MoE load-balance terms) are threaded out of the
    rematerialized region as extra op outputs and re-stashed, so
    ``blk.mlp.aux_loss`` stays usable in the surrounding loss.

    On the first call (shape-inferring initialization) the block runs
    un-checkpointed so its parameters materialize; every later call —
    including under jit/graph mode — is rematerialized.
    """
    from .layer import Layer
    if not isinstance(block, Layer):
        raise TypeError("checkpoint() wraps a Layer; for plain functions "
                        "use jax.checkpoint directly")
    if not block._initialized:
        return block(x)
    params = block.get_params()
    if len(block.get_states()) != len(params):
        # running statistics (BatchNorm) are updated in the forward pass;
        # under recompute they would be written from a closed-over inner
        # trace — unsound. LayerNorm-style blocks are the supported shape.
        raise ValueError(
            "checkpoint() cannot wrap blocks holding non-parameter state "
            "(e.g. BatchNorm running stats); use normalization without "
            "running statistics (LayerNorm) inside checkpointed blocks")
    names = sorted(params)
    tensors = [params[n] for n in names]
    aux_layers = _aux_layers(block)

    def run(x_arr, *param_arrs):
        backup = [t.data for t in tensors]
        for t, a in zip(tensors, param_arrs):
            t.data = a
        try:
            xin = Tensor(data=x_arr, device=x.device, requires_grad=False)
            out = block(xin)
            if not isinstance(out, Tensor):
                raise TypeError(
                    "checkpoint() supports single-Tensor-output blocks; "
                    f"{type(block).__name__}.forward returned "
                    f"{type(out).__name__}")
            auxs = tuple(l.aux_loss.data for l in aux_layers
                         if l.aux_loss is not None)
            if auxs:
                return (out.data,) + auxs
            return out.data
        finally:
            for t, a in zip(tensors, backup):
                t.data = a

    op = _Checkpointed(run)
    key = x.device.rand_key()
    kt = Tensor(data=key, device=x.device, requires_grad=False)
    res = op(kt, x, *tensors)
    if isinstance(res, (tuple, list)):
        y, auxs = res[0], list(res[1:])
        live = [l for l in aux_layers if l.aux_loss is not None]
        for l, a in zip(live, auxs):
            l.aux_loss = a
        return y
    return res


# ---- conv/bn/pool/rnn ops live in singa_tpu.ops; re-export here for parity
from .ops.conv import (ConvHandle, _Conv2d, conv2d)  # noqa: E402
from .ops.batchnorm import (BatchNormHandle, _BatchNorm2d,  # noqa: E402
                            batchnorm_2d)
from .ops.pooling import (PoolingHandle, _Pooling2d, pooling_2d,  # noqa: E402
                          globalaveragepool, GlobalAveragePool)
from .ops.rnn import (CudnnRNNHandle, _RNN, rnn_op)  # noqa: E402
