"""IMDB sentiment classification with an embedding + LSTM stack
(reference examples/rnn/imdb_model.py + imdb_train.py).

Data: pass ``--data imdb.npz`` with arrays ``x`` (N, seq) int token ids
and ``y`` (N,) 0/1 labels — the output of any standard IMDB
preprocessing (the reference's imdb_data.py builds exactly such padded
id sequences; no downloads happen here). Without ``--data`` a synthetic
separable token dataset is generated so the script always runs.

Usage: python examples/train_imdb.py [--data imdb.npz] [--bs 32]
           [--epochs 2] [--hidden 64] [--vocab 4000] [--seq 64]
           [--mode lstm|gru] [--bidirectional] [--cpu]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def synthetic(vocab, seq, n=512, seed=0):
    """Separable by construction: class 1 sequences oversample the top
    half of the vocabulary."""
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 2, n)
    lo = rng.randint(1, vocab // 2, (n, seq))
    hi = rng.randint(vocab // 2, vocab, (n, seq))
    mask = rng.rand(n, seq) < (0.25 + 0.5 * y[:, None])
    x = np.where(mask, hi, lo)
    return x.astype(np.float32), y.astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None)
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=4000)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--mode", default="lstm", choices=["lstm", "gru"])
    ap.add_argument("--bidirectional", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import autograd, device, layer, metric, model, opt, \
        tensor

    class IMDBModel(model.Model):
        def __init__(self):
            super().__init__()
            self.embed = layer.Embedding(args.vocab, args.embed)
            self.rnn = layer.CudnnRNN(hidden_size=args.hidden,
                                      rnn_mode=args.mode,
                                      batch_first=True,
                                      bidirectional=args.bidirectional,
                                      return_sequences=False)
            self.l1 = layer.Linear(64)
            self.l2 = layer.Linear(2)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            y, _hy, _cy = self.rnn(self.embed(x))
            y = autograd.reshape(y, (y.shape[0], -1))
            return self.l2(autograd.relu(self.l1(y)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)

    if args.data:
        blob = np.load(args.data)
        x_all = blob["x"].astype(np.float32)
        y_all = blob["y"].astype(np.int32)
        args.vocab = max(args.vocab, int(x_all.max()) + 1)
    else:
        x_all, y_all = synthetic(args.vocab, args.seq)
    n_val = max(args.bs, len(x_all) // 10)
    train_x, train_y = x_all[:-n_val], y_all[:-n_val]
    val_x, val_y = x_all[-n_val:], y_all[-n_val:]

    m = IMDBModel()
    m.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    tx = tensor.Tensor(data=train_x[:args.bs], device=dev,
                       requires_grad=False)
    m.compile([tx], is_train=True, use_graph=True)

    eye = np.eye(2, dtype=np.float32)
    acc = metric.Accuracy()
    rng = np.random.RandomState(1)
    for epoch in range(args.epochs):
        idx = rng.permutation(len(train_x))
        t0, losses, accs = time.time(), [], []
        m.train()
        for b in range(len(train_x) // args.bs):
            sel = idx[b * args.bs:(b + 1) * args.bs]
            bx = tensor.Tensor(data=train_x[sel], device=dev,
                               requires_grad=False)
            by = tensor.Tensor(data=eye[train_y[sel]], device=dev,
                               requires_grad=False)
            out, loss = m(bx, by)
            losses.append(float(loss.data))
            accs.append(acc.evaluate(out, train_y[sel]))
        m.eval()
        vaccs = []
        for b in range(max(1, len(val_x) // args.bs)):
            bx = val_x[b * args.bs:(b + 1) * args.bs]
            by = val_y[b * args.bs:(b + 1) * args.bs]
            out = m(tensor.Tensor(data=bx, device=dev,
                                  requires_grad=False))
            vaccs.append(acc.evaluate(out, by))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"train_acc {np.mean(accs):.4f} "
              f"val_acc {np.mean(vaccs):.4f} "
              f"({time.time() - t0:.1f}s)", flush=True)


if __name__ == "__main__":
    main()
