"""Long-context Transformer LM with composable dp/sp/tp parallelism —
the TPU-native flagship (no reference equivalent; SURVEY.md §5 notes the
reference has no sequence parallelism).

Usage: python examples/train_transformer.py [--seq 512] [--tp 2]
           [--sp 2] [--layers 4] [--d-model 256] [--cpu]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=8)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel degree (with --moe)")
    ap.add_argument("--moe", type=int, default=0,
                    help="experts per block (0 = dense FFN)")
    ap.add_argument("--fused-head-chunk", type=int, default=0,
                    help="train through the chunked fused CE head: the "
                         "(B,S,V) logits are never materialised; under "
                         "--tp the loss reduces across vocab shards "
                         "online (per-rank head memory V/tp)")
    ap.add_argument("--generate", type=int, default=0,
                    help="after training, decode N tokens greedily from "
                         "the first batch row (KV-cache scan)")
    ap.add_argument("--bf16", action="store_true",
                    help="compute_dtype=bfloat16: the whole transformer "
                         "stack (params + attention matmuls) in MXU-"
                         "native precision; embeddings / MoE router / "
                         "loss softmax stay f32")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    import jax
    from jax.sharding import PartitionSpec as P
    from singa_tpu import device, opt, tensor
    from singa_tpu.models import transformer
    from singa_tpu.parallel import mesh as mesh_mod
    from singa_tpu.parallel.communicator import set_mesh

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, args.vocab,
                      (args.bs, args.seq)).astype(np.float32)
    tgt = np.roll(ids, -1, axis=1)
    tx = tensor.Tensor(data=ids, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=tgt, device=dev, requires_grad=False)

    import jax.numpy as jnp
    model = transformer.TransformerLM(
        args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers,
        max_len=args.seq + args.generate,
        seq_axis="seq" if args.sp > 1 else None,
        moe=args.moe or None, tp=args.tp > 1,
        fused_head_chunk=args.fused_head_chunk or None,
        compute_dtype=jnp.bfloat16 if args.bf16 else None)
    dist = opt.DistOpt(opt.SGD(lr=0.1, momentum=0.9),
                       reduce_axes=("data", "expert", "seq"))
    msh = mesh_mod.make_mesh(
        jax.devices(), mesh_mod.MeshConfig(model=args.tp, seq=args.sp,
                                           expert=args.ep))
    print("mesh:", dict(msh.shape))
    dist.communicator.mesh = msh
    set_mesh(msh)
    model.set_optimizer(dist)
    # tokens shard over every batch-like axis in use: data, expert
    # (MoE peers hold distinct tokens), and seq on dim 1
    batch_ax = ("data", "expert") if args.ep > 1 else "data"
    if args.sp > 1:
        model.input_specs = [P(batch_ax, "seq"), P(batch_ax, "seq")]
        model.output_specs = [P(batch_ax, "seq"), P()]
    elif args.ep > 1:
        model.input_specs = [P(batch_ax), P(batch_ax)]
        model.output_specs = [P(batch_ax), P()]
    model.compile([tx], is_train=True, use_graph=True)

    model(tx, ty)  # eager warm-up
    t0 = time.time()
    for step in range(args.steps):
        _, loss = model(tx, ty)
        if step % 5 == 0:
            print(f"step {step}: loss {float(loss.data):.4f}")
    toks = args.bs * args.seq * args.steps / (time.time() - t0)
    print(f"throughput {toks:.0f} tokens/s")

    if args.generate:
        out = model.generate(ids[:1], max_new_tokens=args.generate,
                             temperature=0)   # first row only
        print("generated:", out[0, -args.generate:].tolist())


if __name__ == "__main__":
    main()
