"""ONNX interchange example: train a CNN, export to ONNX, reimport it as
a SONNXModel, and fine-tune the imported graph (the reference's
examples/onnx/*.py fine-tune pretrained zoo models fetched from the
network; this environment has no egress, so the same user flow is shown
on a locally-trained model — the interchange mechanics are identical).

Usage: python examples/onnx_finetune.py [--cpu] [--steps 10]
"""

import argparse
import sys
import tempfile
import os

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, layer, model, opt, sonnx, tensor

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.conv = layer.Conv2d(8, 3, padding=1)
            self.relu = layer.ReLU()
            self.pool = layer.MaxPool2d(2, 2)
            self.flat = layer.Flatten()
            self.fc = layer.Linear(10)

        def forward(self, x):
            return self.fc(self.flat(self.pool(self.relu(self.conv(x)))))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            from singa_tpu import autograd
            loss = autograd.softmax_cross_entropy(out, y)
            self.optimizer(loss)
            return out, loss

    rng = np.random.RandomState(0)
    x = rng.randn(args.bs, 3, 16, 16).astype(np.float32)
    labels = rng.randint(0, 10, args.bs)
    y = np.eye(10)[labels].astype(np.float32)
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=y, device=dev, requires_grad=False)

    # 1) pre-train briefly
    m = Net()
    m.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    m.compile([tx], is_train=True, use_graph=True)
    for i in range(args.steps):
        out, loss = m(tx, ty)
    print(f"pretrained: loss {float(np.asarray(loss.data)):.4f}")

    # 2) export to an .onnx file
    ex = tensor.Tensor(data=x, device=dev, requires_grad=True)
    onnx_model = sonnx.to_onnx(m, [ex], "cnn")
    path = os.path.join(tempfile.gettempdir(), "cnn.onnx")
    sonnx.save(onnx_model, path)
    print(f"exported {len(onnx_model.graph.node)} nodes -> {path}")

    # 3) reimport and fine-tune the IMPORTED graph
    loaded = sonnx.load(path)

    class FineTune(sonnx.SONNXModel):
        def train_one_batch(self, x, y):
            from singa_tpu import autograd
            out = self.forward(x)
            loss = autograd.softmax_cross_entropy(out, y)
            self.optimizer(loss)
            return out, loss

    ft = FineTune(loaded)
    ft.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    ft.train(True)   # enable the tape (don't rely on ambient mode)
    for i in range(args.steps):
        out, loss = ft.train_one_batch(tx, ty)
    acc = float((np.argmax(np.asarray(out.data), 1) == labels).mean())
    print(f"fine-tuned imported model: loss "
          f"{float(np.asarray(loss.data)):.4f}, train acc {acc:.2f}")


if __name__ == "__main__":
    main()
