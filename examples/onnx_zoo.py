"""Run (and optionally fine-tune) a LOCAL ONNX model file.

The reference ships one script per downloaded zoo model
(examples/onnx/{resnet18,vgg16,vgg19,mobilenet,squeezenet,shufflenetv1,
shufflenetv2,densenet121,arcface,fer_emotion,tiny_yolov2,
superresolution,bert,gpt2,ro_bert_a}.py), each doing: download →
``sonnx.prepare(model)`` → run. This environment has no egress, so this
single script covers the same capability for ANY ``.onnx`` file already
on disk — including models exported from this framework's own zoo
(``--export`` writes one to try the loop end-to-end).

Usage:
  python examples/onnx_zoo.py model.onnx [--input data.npz]
      [--batch 1] [--finetune N_STEPS] [--lr 0.05] [--cpu]
  python examples/onnx_zoo.py --export model.onnx [--arch mlp|cnn]
"""

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


def export(path, arch, dev):
    from singa_tpu import models, sonnx, tensor

    shapes = {"mlp": (2, 64), "cnn": (2, 1, 28, 28)}
    factory = getattr(models, arch)
    kwargs = {"data_size": 64} if arch == "mlp" else {}
    m = factory.create_model(num_classes=10, **kwargs)
    x = np.zeros(shapes[arch], np.float32)
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    m.compile([tx], is_train=False, use_graph=False)
    mp = sonnx.to_onnx(m, [tx])
    with open(path, "wb") as f:
        f.write(mp.SerializeToString())
    print(f"exported {arch} -> {path}")


def load_model(path):
    from singa_tpu.onnx_proto import ModelProto

    mp = ModelProto()
    with open(path, "rb") as f:
        mp.ParseFromString(f.read())
    return mp


def input_arrays(rep, args):
    if args.input:
        blob = np.load(args.input)
        return [blob[k] for k in blob.files]
    out = []
    rng = np.random.RandomState(0)
    for vi in rep.inputs:
        dims = [d.dim_value or args.batch
                for d in vi.type.tensor_type.shape.dim]
        dims[0] = args.batch
        out.append(rng.randn(*dims).astype(np.float32))
        print(f"  input {vi.name}: random {tuple(dims)}")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?", help="path to a .onnx file")
    ap.add_argument("--export", default=None,
                    help="write a model exported from our zoo here")
    ap.add_argument("--arch", default="mlp", choices=["mlp", "cnn"])
    ap.add_argument("--input", default=None,
                    help="npz whose arrays are the graph inputs in order")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--finetune", type=int, default=0,
                    help="SONNXModel fine-tune steps on synthetic labels")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, sonnx

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)

    if args.export:
        export(args.export, args.arch, dev)
        if not args.model:
            return

    if not args.model:
        sys.exit("no model path given (or use --export)")

    mp = load_model(args.model)
    rep = sonnx.SingaBackend.prepare(
        mp, device="CPU" if args.cpu else "TPU")
    print(f"loaded {args.model}: {len(rep.nodes)} nodes, "
          f"{len(rep.states)} initializers")

    ins = input_arrays(rep, args)
    outs = rep.run(ins)
    for o, vi in zip(outs, rep.outputs):
        arr = np.asarray(o.numpy())
        print(f"  output {vi.name}: {arr.shape} "
              f"mean={arr.mean():.4f} std={arr.std():.4f}")

    if args.finetune:
        from singa_tpu import opt, tensor

        class Tuned(sonnx.SONNXModel):
            def __init__(self, model_proto):
                super().__init__(model_proto)
                from singa_tpu import layer
                self.loss_fn = layer.SoftMaxCrossEntropy()

            def forward(self, *x):
                out = super().forward(*x)
                return out[0] if isinstance(out, (list, tuple)) else out

            def train_one_batch(self, x, y):
                out = self.forward(x)
                loss = self.loss_fn(out, y)
                self.optimizer(loss)
                return out, loss

        m = Tuned(mp)
        m.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
        tx = tensor.Tensor(data=ins[0], device=dev, requires_grad=False)
        m.compile([tx], is_train=True, use_graph=True)
        classes = np.asarray(outs[0].numpy()).shape[-1]
        rng = np.random.RandomState(1)
        y = np.eye(classes, dtype=np.float32)[
            rng.randint(0, classes, len(ins[0]))]
        ty = tensor.Tensor(data=y, device=dev, requires_grad=False)
        for i in range(args.finetune):
            out, loss = m(tx, ty)
            print(f"  finetune step {i}: loss {float(loss.data):.4f}",
                  flush=True)


if __name__ == "__main__":
    main()
