"""ResNet-50 synthetic-data training throughput benchmark
(reference examples/cnn/benchmark.py:40-90, same metric:
``throughput = niters * batch * world / (end - start)``).

This is the interactive form of the harness; the repo-root ``bench.py``
wraps the same measurement with probing/fallback orchestration for the
scored one-line JSON.

Usage: python examples/benchmark.py [--bs 32] [--iters 100]
           [--warmup 8] [--depth 50] [--size 224] [-p float32|bfloat16]
           [--dist] [--verbosity 0] [--cpu]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--warmup", type=int, default=8)
    ap.add_argument("--depth", type=int, default=50,
                    choices=[18, 34, 50, 101, 152])
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("-p", "--precision", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--dist", action="store_true")
    ap.add_argument("--verbosity", "-v", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--layout", default="NCHW",
                    choices=["NCHW", "NHWC"])
    ap.add_argument("--stem", default="conv7",
                    choices=["conv7", "space_to_depth"])
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    from singa_tpu import device, opt, tensor
    from singa_tpu.models import resnet

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)
    dev.SetVerbosity(args.verbosity)
    dev.SetSkipIteration(5)

    world = 1
    m = resnet.create_model(depth=args.depth, num_classes=1000,
                            num_channels=3, layout=args.layout,
                            stem=args.stem)
    sgd = opt.SGD(lr=0.1, momentum=0.9, weight_decay=1e-5)
    if args.dist:
        d = opt.DistOpt(sgd)
        world = d.world_size
        m.set_optimizer(d)
    else:
        m.set_optimizer(sgd)

    rng = np.random.RandomState(0)
    x = rng.randn(args.bs, 3, args.size, args.size).astype(np.float32)
    y = np.eye(1000, dtype=np.float32)[rng.randint(0, 1000, args.bs)]
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    if args.precision == "bfloat16":
        tx = tx.as_type(jnp.bfloat16)
    ty = tensor.Tensor(data=y, device=dev, requires_grad=False)

    m.compile([tx], is_train=True, use_graph=True)

    # completion barrier that holds on proxied backends too — the one
    # canonical recipe, shipped in the package (block_until_ready can
    # resolve on enqueue-ACK through a network tunnel; see
    # docs/performance.md)
    from singa_tpu.utils import force_completion

    def sync(t):
        return force_completion(t.data)

    # always at least one untimed step: it includes trace+compile, which
    # must not land inside the timed region
    for _ in range(max(1, args.warmup)):
        out, loss = m(tx, ty)
    sync(loss)

    start = time.time()
    for _ in range(args.iters):
        out, loss = m(tx, ty)
    sync(loss)
    end = time.time()

    titer = (end - start) / args.iters
    throughput = args.iters * args.bs * world / (end - start)
    print(f"\nThroughput = {throughput:.2f} per second", flush=True)
    print(f"TotalTime={end - start:.4f}", flush=True)
    print(f"Total={titer:.6f}", flush=True)
    dev.PrintTimeProfiling()


if __name__ == "__main__":
    main()
