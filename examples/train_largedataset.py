"""Training from an on-disk record dataset that does not fit the model's
input pipeline in memory (reference examples/largedataset_cnn: data is
pre-encoded into record shards, then streamed through the prefetching
reader during training).

Phase 1 writes CIFAR-like samples into BinFile shards (the native
``SGTPREC0`` record runtime, native/singa_native.cc); phase 2 streams
them back with the C++ prefetch thread, batches, and trains a CNN —
multi-epoch, exercising reader rewind with prefetch intact.
"""

import argparse
import os
import struct
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def write_shards(root, n, shards, shape, rng):
    from singa_tpu.io import BinFileWriter
    c, h, w = shape
    paths = []
    per = n // shards
    for s in range(shards):
        path = os.path.join(root, f"shard-{s:03d}.bin")
        with BinFileWriter(path) as wtr:
            for i in range(per):
                label = rng.randint(0, 10)
                img = (rng.rand(c, h, w) * 255).astype(np.uint8)
                # record: 1 label byte + raw CHW bytes
                wtr.Write(f"s{s}-{i}",
                          struct.pack("B", label) + img.tobytes())
        paths.append(path)
    return paths


def stream_batches(paths, bs, shape, epochs):
    """Generator over (x, y) batches, streaming every shard per epoch
    through the native prefetching reader."""
    from singa_tpu.io import BinFileReader
    c, h, w = shape
    readers = [BinFileReader(p, prefetch=64) for p in paths]
    try:
        for _ in range(epochs):
            xs, ys = [], []
            for r in readers:
                r.SeekToFirst()
                while True:
                    rec = r.Read()
                    if rec is None:
                        break
                    _, value = rec
                    ys.append(value[0])
                    xs.append(np.frombuffer(value[1:], np.uint8)
                              .reshape(c, h, w))
                    if len(xs) == bs:
                        x = np.stack(xs).astype(np.float32) / 255.0 - 0.5
                        y = np.eye(10, dtype=np.float32)[ys]
                        xs, ys = [], []
                        yield x, y
            yield None, None          # epoch boundary
    finally:
        for r in readers:
            r.Close()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (hermetic runs)")
    args = ap.parse_args()

    # the config update matters even with the env var set: an
    # environment sitecustomize may pin another backend over it
    if args.cpu or os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, opt, tensor
    from singa_tpu.models import cnn

    shape = (3, args.size, args.size)
    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as root:
        paths = write_shards(root, args.n, args.shards, shape, rng)
        total = sum(os.path.getsize(p) for p in paths)
        print(f"wrote {args.shards} shards, {total / 1e6:.2f} MB")

        dev = device.create_tpu_device()
        dev.SetRandSeed(7)
        model = cnn.create_model(num_channels=3)
        model.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
        x0 = np.zeros((args.bs, *shape), np.float32)
        tx0 = tensor.Tensor(data=x0, device=dev, requires_grad=False)
        model.compile([tx0], is_train=True, use_graph=True)

        epoch, losses, t0 = 0, [], time.time()
        for x, y in stream_batches(paths, args.bs, shape, args.epochs):
            if x is None:
                dt = time.time() - t0
                print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
                      f"({len(losses) * args.bs / dt:.1f} img/s)")
                epoch, losses, t0 = epoch + 1, [], time.time()
                continue
            tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
            ty = tensor.Tensor(data=y, device=dev, requires_grad=False)
            out, loss = model(tx, ty)
            losses.append(float(loss.data))


if __name__ == "__main__":
    main()
