"""RBM with CD-1 (reference examples/rbm/train.py). Synthetic binary
patterns unless --data npz with array x is given."""

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=100)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--hdim", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.0005)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--data", default=None)
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, opt
    from singa_tpu.models import rbm

    rng = np.random.RandomState(0)
    if args.data:
        x = np.load(args.data)["x"].astype(np.float32)
        x = x.reshape(len(x), -1) / x.max()
    else:
        protos = (rng.rand(10, 784) > 0.6).astype(np.float32)
        x = np.repeat(protos, 200, axis=0)
        rng.shuffle(x)
    vdim = x.shape[1]

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    model = rbm.create_model(vdim=vdim, hdim=args.hdim, device=dev)
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=2e-4)

    nb = len(x) // args.bs
    for epoch in range(args.epochs):
        err = 0.0
        for b in range(nb):
            err += model.train_on_batch(
                sgd, x[b * args.bs:(b + 1) * args.bs])
        print(f"epoch {epoch}: reconstruction error/sample "
              f"{err / (nb * args.bs):.4f}")


if __name__ == "__main__":
    main()
