"""Multi-process data-parallel training — one OS process per host,
bootstrapped with ``jax.distributed`` (the reference launches one process
per GPU with python multiprocessing + an NcclIdHolder,
examples/cnn/train_multiprocess.py, or mpirun, examples/cnn/train_mpi.py;
here the coordinator address plays the NCCL-id role and XLA collectives
replace the NCCL ring).

Run standalone (spawns the workers itself):

    python examples/train_multiprocess.py --procs 2 --steps 5

or launch one rank per host, SPMD-style:

    python examples/train_multiprocess.py --rank 0 --procs 2 \
        --coordinator host0:29500 &
    python examples/train_multiprocess.py --rank 1 --procs 2 \
        --coordinator host0:29500

On machines without accelerators each process simulates a host with
``--devices-per-proc`` CPU devices, so the full multi-host code path —
coordination service, global mesh, cross-process psum — runs anywhere.
"""

import argparse
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_rank(args):
    if args.platform == "cpu":
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count="
            f"{args.devices_per_proc}")
        import jax
        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    import numpy as np
    from jax.sharding import PartitionSpec as P
    from singa_tpu import device, layer, model as model_mod, opt, tensor
    from singa_tpu.models import cnn
    from singa_tpu.parallel import communicator, mesh as mesh_mod

    # rank exchange / process bootstrap (reference communicator.cc:73-103)
    communicator.init_process(
        communicator.NcclIdHolder(args.coordinator),
        rank=args.rank, world=args.procs)
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    print(f"rank {args.rank}/{args.procs}: {n_local} local / "
          f"{n_global} global devices", flush=True)

    rng = np.random.RandomState(0)
    gb = args.bs * n_global
    if args.moe:
        # expert-parallel across HOSTS: the 'expert' axis is made the
        # OUTERMOST mesh axis so (with process-major device order) each
        # process owns one expert group — expert weights genuinely shard
        # cross-process, and save_states gathers them over the process
        # group
        from singa_tpu.parallel.moe import MoEFFN

        class MoENet(model_mod.Model):
            def __init__(self):
                super().__init__()
                self.ffn = MoEFFN(args.moe, 32, top_k=2,
                                  capacity_factor=4.0)
                self.loss_fn = layer.MeanSquareError()

            def forward(self, xx):
                return self.ffn(xx)

            def train_one_batch(self, xx, yy):
                o = self.forward(xx)
                ls = self.loss_fn(o, yy)
                self.optimizer(ls)
                return o, ls

        mesh_cfg = mesh_mod.MeshConfig(
            expert=args.procs,
            axis_order=("expert", "data", "seq", "pipe", "model"))
        dist_kw = {"reduce_axes": ("data", "expert")}
        make_model = MoENet
        x = rng.randn(gb, 16).astype(np.float32)
        y = rng.randn(gb, 16).astype(np.float32)
    else:
        mesh_cfg = mesh_mod.MeshConfig()
        dist_kw = {"world_size": n_global}
        make_model = lambda: cnn.create_model(num_channels=1)  # noqa: E731
        x = rng.randn(gb, 1, 28, 28).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, gb)]

    mesh = mesh_mod.make_mesh(jax.devices(), mesh_cfg)
    communicator.set_mesh(mesh)
    dev = device.Device(jax.local_devices()[0])
    dev.SetRandSeed(7)
    model = make_model()
    dist = opt.DistOpt(opt.SGD(lr=args.lr, momentum=0.9), **dist_kw)
    dist.communicator.mesh = mesh
    model.set_optimizer(dist)
    if args.moe:
        model.input_specs = [P(("data", "expert")),
                             P(("data", "expert"))]

    # SPMD convention: every process feeds the same GLOBAL batch; the
    # placement inside the compiled step keeps only the local shard
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=y, device=dev, requires_grad=False)

    model.compile([tx], is_train=True, use_graph=True)
    model(tx, ty)                       # materialise + compile
    t0 = time.time()
    loss = None
    for _ in range(args.steps):
        out, loss = model(tx, ty)
    lv = float(np.asarray(jax.device_get(loss.data)))
    dt = time.time() - t0
    print(f"rank {args.rank}: {args.steps} steps, loss {lv:.4f}, "
          f"{args.steps * gb / dt:.1f} img/s global", flush=True)

    if args.save:
        # collective: every rank participates in the cross-process gather
        # of host-sharded state; each writes its own (identical) copy
        path = f"{args.save}.rank{args.rank}.zip"
        model.save_states(path)
        print(f"rank {args.rank}: saved {path}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rank", type=int, default=None,
                    help="this process's rank; omit to spawn all ranks")
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of rank 0's coordination service; "
                         "launcher mode defaults to an ephemeral free port")
    ap.add_argument("--devices-per-proc", type=int, default=2)
    ap.add_argument("--platform", default="cpu",
                    choices=["cpu", "tpu"])
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--bs", type=int, default=8,
                    help="per-device batch size")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--moe", type=int, default=0,
                    help="experts for a cross-host expert-parallel MoE "
                         "run (0 = data-parallel CNN)")
    ap.add_argument("--save", default="",
                    help="checkpoint path prefix written after "
                         "training (collective across ranks)")
    args = ap.parse_args()

    if args.rank is not None:
        if args.coordinator is None:
            args.coordinator = "127.0.0.1:29512"
        run_rank(args)
        return

    if args.coordinator is None:
        # ephemeral free port so concurrent runs / stale workers on the
        # default port can't collide
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        args.coordinator = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()

    # launcher mode: one subprocess per rank (the reference's
    # multiprocessing.Process loop, train_multiprocess.py)
    procs = []
    for r in range(args.procs):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--rank", str(r)]
        for k in ("procs", "coordinator", "devices_per_proc", "platform",
                  "steps", "bs", "lr", "moe", "save"):
            cmd += [f"--{k.replace('_', '-')}", str(getattr(args, k))]
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(cmd, env=env))
    rcs = [p.wait() for p in procs]
    if any(rcs):
        raise SystemExit(f"worker failure: rcs={rcs}")


if __name__ == "__main__":
    main()
