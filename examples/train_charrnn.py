"""Character-level LM on a text file (reference examples/rnn/char_rnn.py).

Pass --text yourfile.txt; without one, a small synthetic corpus with
learnable structure is generated.
"""

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


class Data:
    """(reference char_rnn.py Data:92-123)"""

    def __init__(self, text, batch_size=32, seq_length=50,
                 train_ratio=0.8):
        self.raw = text
        self.vocab = sorted(set(text))
        self.char_to_idx = {c: i for i, c in enumerate(self.vocab)}
        self.idx_to_char = dict(enumerate(self.vocab))
        self.vocab_size = len(self.vocab)
        data = np.asarray([self.char_to_idx[c] for c in text],
                          np.int32)
        n = len(data) // (batch_size * seq_length)
        data = data[:n * batch_size * seq_length].reshape(
            batch_size, -1)
        split = int(data.shape[1] * train_ratio) // seq_length * seq_length
        self.train_dat = data[:, :split]
        self.val_dat = data[:, split:]
        self.batch_size = batch_size
        self.seq_length = seq_length
        self.num_train_batch = split // seq_length


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", default=None)
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--seq", type=int, default=25)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import opt, tensor, device
    from singa_tpu.models import char_rnn

    if args.text:
        text = open(args.text, errors="ignore").read()
    else:
        rng = np.random.RandomState(0)
        words = ["the ", "quick ", "brown ", "fox ", "jumps "]
        text = "".join(rng.choice(words) for _ in range(4000))

    data = Data(text, args.bs, args.seq)
    print(f"vocab {data.vocab_size}, {data.num_train_batch} batches/epoch")

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    model = char_rnn.CharRNN(data.vocab_size, args.hidden)
    model.set_optimizer(opt.SGD(lr=0.5, momentum=0.9))
    model.train()

    eye = np.eye(data.vocab_size, dtype=np.float32)
    for epoch in range(args.epochs):
        losses = []
        model.reset_states() if model._states_ready else None
        for b in range(data.num_train_batch):
            s = b * args.seq
            chunk = data.train_dat[:, s:s + args.seq + 1]
            if chunk.shape[1] < args.seq + 1:
                break
            inputs = [tensor.Tensor(data=eye[chunk[:, i]], device=dev,
                                    requires_grad=True)
                      for i in range(args.seq)]
            labels = [tensor.Tensor(
                data=chunk[:, i + 1].astype(np.float32), device=dev,
                requires_grad=False) for i in range(args.seq)]
            _, loss = model.train_one_batch(inputs, labels)
            losses.append(float(loss.data))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f}")

    ids = char_rnn.sample(model, [data.char_to_idx[text[0]]],
                          data.vocab_size, nsamples=60)
    print("sample:", "".join(data.idx_to_char[i] for i in ids))


if __name__ == "__main__":
    main()
