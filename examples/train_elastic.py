"""Fault-tolerant (checkpoint-restart) training loop.

The reference's failure handling is fail-fast only: NCCL/MPI errors
print and exit (include/singa/io/communicator.h:40-67), with no resume.
This example exceeds that cheaply with the rotated async checkpoint
manager: every run resumes from the newest checkpoint, so a crashed or
preempted job continues exactly where it stopped (optimizer momentum
included — the trajectory is identical to an uninterrupted run).

This is the MINIMAL form — the raw CheckpointManager loop. The full
production driver (preemption signal handling with a supervisor
exit-code contract, NaN/divergence guards, transient-failure retry,
corrupt-checkpoint fallback) lives in ``singa_tpu/resilience``; see
``examples/train_cnn.py --resilient`` and the README's Fault tolerance
section.

Try it:
    python examples/train_elastic.py --cpu --steps 40 --crash-at 17
    python examples/train_elastic.py --cpu --steps 40
    # resumes at 16: the newest committed checkpoint is step 15
    # (--save-every 5), and resume = latest saved step + 1

Usage: python examples/train_elastic.py [--dir ckpts] [--steps 100]
           [--save-every 5] [--keep 3] [--bs 32] [--lr 0.1]
           [--crash-at -1] [--cpu]
"""

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="ckpts")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--crash-at", type=int, default=-1,
                    help="simulate a failure after this step")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, layer, model, opt, tensor
    from singa_tpu.checkpoint import CheckpointManager

    class MLP(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(64)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(10)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(args.bs, 32).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, args.bs)]
    tx = tensor.Tensor(data=x, device=dev, requires_grad=False)
    ty = tensor.Tensor(data=y, device=dev, requires_grad=False)

    m = MLP()
    m.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    m.compile([tx], is_train=True, use_graph=True)

    mgr = CheckpointManager(args.dir, max_to_keep=args.keep,
                            save_interval_steps=args.save_every)
    try:
        start = mgr.restore_latest(m)
        if start:
            print(f"resumed from checkpoint; continuing at step {start}",
                  flush=True)
        for step in range(start, args.steps):
            out, loss = m(tx, ty)
            mgr.save(step, m)
            if step % 10 == 0 or step == args.steps - 1:
                print(f"step {step}: loss {float(loss.data):.4f}",
                      flush=True)
            if step == args.crash_at:
                mgr.wait()
                print(f"simulated crash at step {step}", flush=True)
                sys.exit(42)
        mgr.wait()
        print("training complete", flush=True)
    finally:
        mgr.close()


if __name__ == "__main__":
    main()
