"""Elastic multi-host training: cluster health + two-phase checkpoints +
world-size-elastic resume.

The reference's failure handling is print-and-exit
(include/singa/io/communicator.h:40-67). This example runs the full
elastic contract instead:

- every rank joins a control-plane cluster (heartbeats, failing-fast
  barriers — ``singa_tpu/resilience/cluster.py``);
- checkpoints are TWO-PHASE: each rank writes its shard, ACKs, and only
  after every ACK does the coordinator publish the commit marker — a
  rank that dies mid-save can never leave a checkpoint that only looks
  committed;
- a lost rank exits the survivors with code 75 (the supervisor
  contract); relaunching with a SMALLER ``--world`` resumes from the
  last *committed* step, optimizer momentum included, with the batch
  accounting rescaled from the manifest (per-replica batch kept).

Try it (single host — world of one, same code path)::

    python examples/train_elastic.py --cpu --steps 40 --crash-at 17
    python examples/train_elastic.py --cpu --steps 40      # resumes

Two hosts, then lose one and restart smaller::

    python examples/train_elastic.py --cpu --world 2 --steps 40 \
        --die-at 11 --die-rank 1            # rank 1 hard-dies at step 11
    # survivors exit 75; restart at the surviving size:
    python examples/train_elastic.py --cpu --world 1 --steps 40

``tools/chaos_smoke.py`` drives these scenarios end-to-end under a
wall-clock budget.
"""

import argparse
import json
import os
import subprocess
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_model(lr):
    from singa_tpu import layer, model, opt

    class MLP(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(64)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(10)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    m = MLP()
    m.set_optimizer(opt.SGD(lr=lr, momentum=0.9))
    return m


def dump_state(model, path):
    """Host-copy every model + optimizer state to one npz — the
    bit-identity probe the chaos suite compares across restarts."""
    states = {f"model/{k}": np.asarray(getattr(v, "data", v))
              for k, v in model.get_states().items()}
    for k, v in model.optimizer.get_states().items():
        states[f"optimizer/{k}"] = np.asarray(getattr(v, "data", v))
    np.savez(path, **states)


def run_rank(args):
    from singa_tpu import device, tensor
    from singa_tpu.checkpoint import latest_manifest
    from singa_tpu.data import NumpyBatchIter
    from singa_tpu.parallel import communicator, mesh as mesh_mod
    from singa_tpu.resilience import ClusterConfig, FaultPlan, make_cluster
    from singa_tpu.resilience.runtime import ResilientTrainer

    # -- elastic accounting: manifest first, shapes second ---------------
    manifest = latest_manifest(args.dir)
    per_bs, global_bs = args.bs, args.bs * args.world
    if manifest is not None:
        per, gb = communicator.rescale_batch(manifest, args.world)
        if per is not None:
            per_bs, global_bs = per, gb
        if int(manifest.get("world", args.world)) != args.world:
            print(f"rank {args.rank}: elastic restart — checkpoint world "
                  f"{manifest.get('world')} -> {args.world}, global "
                  f"batch {manifest.get('global_batch')} -> {global_bs}",
                  flush=True)

    # the data axis absorbs any device-count change; axis NAMES stay
    # fixed so checkpointed shardings re-land on the new degrees. The
    # CLUSTER world change is reported above — elastic_mesh's
    # saved_world compares per-process DEVICE degrees, a different
    # quantity (1 per process here), so it is not passed.
    mesh = mesh_mod.elastic_mesh()
    if args.mesh:
        # explicit GSPMD train mesh (data x model): same axis names as
        # the elastic mesh, so checkpoint shardings re-land unchanged
        from singa_tpu.parallel import gspmd
        d_, m_ = (int(v) for v in args.mesh.lower().split("x"))
        mesh = gspmd.train_mesh(data=d_, model=m_)
    communicator.set_mesh(mesh)
    use_gspmd = bool(args.mesh or args.fsdp)

    faults = FaultPlan()
    if args.die_at >= 0 and args.rank == args.die_rank:
        faults.kill_rank(args.die_at)
    if args.kill_before_ack >= 0 and args.rank == args.die_rank:
        faults.kill_before_ack(args.kill_before_ack)
    if args.diverge_at >= 0 and args.rank == args.diverge_rank:
        # silent SDC on this rank: state forks with no exception — only
        # the cross-replica fingerprint can see it
        faults.diverge_at(args.diverge_at, times=args.diverge_times)

    cluster = make_cluster(
        args.rank, args.world, args.coordinator,
        ClusterConfig(heartbeat_interval=args.hb_interval,
                      straggler_after=3 * args.hb_interval,
                      dead_after=args.dead_after),
        faults=faults)

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)
    rng = np.random.RandomState(0)
    n = max(global_bs * 4, 64)
    x = rng.randn(n, 32).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    tx = tensor.Tensor(data=x[:global_bs], device=dev,
                       requires_grad=False)

    m = build_model(args.lr)
    m.compile([tx], is_train=True, use_graph=True,
              mesh=mesh if use_gspmd else None,
              fsdp_axis="data" if args.fsdp else None)
    if use_gspmd:
        print(f"rank {args.rank}: GSPMD train "
              f"mesh=data{mesh.shape['data']}xmodel{mesh.shape['model']}"
              f"{' fsdp=data' if args.fsdp else ''}", flush=True)

    trainer = ResilientTrainer(
        m, args.dir, max_to_keep=args.keep,
        save_interval_steps=args.save_every, cluster=cluster,
        faults=faults, commit_timeout=args.commit_timeout,
        start_barrier_timeout=args.start_timeout,
        fingerprint_every=args.fingerprint_every,
        max_divergence_rollbacks=args.max_divergence_rollbacks,
        manifest_extra={"per_replica_batch": per_bs,
                        "global_batch": global_bs},
        aot=args.aot_dir or None)

    if args.dump_restored:
        # bit-identity probe: what does the last COMMITTED checkpoint
        # restore to? (run() restores again itself — deterministic)
        start = trainer.mgr.restore_latest(m)
        dump_state(m, args.dump_restored)
        print(f"rank {args.rank}: dumped restored state of step "
              f"{start - 1} to {args.dump_restored}", flush=True)

    if args.dump_sample_ids:
        os.makedirs(args.dump_sample_ids, exist_ok=True)

    def on_step(step, out):
        if args.dump_on_save and trainer.mgr.latest_step() == step:
            dump_state(m, os.path.join(args.dump_on_save,
                                       f"state_step{step}.npz"))
        if args.dump_sample_ids and batches.last_batch_ids is not None:
            # one file per step, overwritten on a re-run: the dir holds
            # the FINAL timeline's per-step sample ids — what the
            # data-resume chaos scenario asserts bit-identical to a
            # fault-free run's
            np.save(os.path.join(args.dump_sample_ids,
                                 f"ids_step{step}.npy"),
                    batches.last_batch_ids)
        if step == args.crash_at:
            trainer.mgr.wait()
            print(f"simulated crash at step {step}", flush=True)
            sys.exit(42)

    # checkpointable stream: state ({epoch, position}) rides every
    # checkpoint, so kills/rollbacks/elastic restarts rewind it in
    # lockstep with the tensors (exactly-once sample consumption)
    batches = NumpyBatchIter(x, y, batch_size=global_bs, seed=0)
    try:
        summary = trainer.run(batches, num_steps=args.steps,
                              step_callback=on_step)
    finally:
        cluster.close()
    print(f"rank {args.rank}: summary "
          f"{json.dumps({k: v for k, v in summary.items() if k != 'cluster'})}",
          flush=True)
    print("training complete", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", default="ckpts")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--save-every", type=int, default=5)
    ap.add_argument("--keep", type=int, default=3)
    ap.add_argument("--bs", type=int, default=32,
                    help="PER-REPLICA batch size (the elastic invariant)")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--mesh", default=None, metavar="DxM",
                    help="GSPMD train mesh 'DxM' (data x model): compile "
                         "the step as ONE jitted NamedSharding program "
                         "instead of the shard_map driver")
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO/FSDP over 'data' on the GSPMD path "
                         "(optimizer state + masters sharded, gathered "
                         "just-in-time)")
    ap.add_argument("--world", type=int, default=1)
    ap.add_argument("--rank", type=int, default=None,
                    help="this process's rank; omit to spawn all ranks")
    ap.add_argument("--coordinator", default=None,
                    help="host:port of rank 0's cluster listener")
    ap.add_argument("--aot-dir", default="",
                    help="cold-start elimination (singa_tpu.aot): "
                         "persistent compile cache + exported train-"
                         "step executable under this dir; a restart "
                         "deserializes instead of retracing")
    ap.add_argument("--hb-interval", type=float, default=0.25)
    ap.add_argument("--dead-after", type=float, default=2.5)
    ap.add_argument("--commit-timeout", type=float, default=30.0)
    ap.add_argument("--start-timeout", type=float, default=30.0)
    ap.add_argument("--crash-at", type=int, default=-1,
                    help="soft crash (exit 42) after this step commits")
    ap.add_argument("--die-at", type=int, default=-1,
                    help="hard-kill --die-rank just before this step")
    ap.add_argument("--die-rank", type=int, default=1)
    ap.add_argument("--kill-before-ack", type=int, default=-1,
                    help="hard-kill --die-rank after this step's shard "
                         "is written but before its commit ACK")
    ap.add_argument("--fingerprint-every", type=int, default=0,
                    help="cross-replica state fingerprint cadence "
                         "(0 = off, the zero-overhead default)")
    ap.add_argument("--max-divergence-rollbacks", type=int, default=2,
                    help="quarantine-rollbacks before exit 76")
    ap.add_argument("--diverge-at", type=int, default=-1,
                    help="silently perturb --diverge-rank's params at "
                         "this step's fingerprint check (SDC injection)")
    ap.add_argument("--diverge-rank", type=int, default=1)
    ap.add_argument("--diverge-times", type=int, default=1,
                    help="how many times the divergence re-fires "
                         "(>max-divergence-rollbacks forces exit 76)")
    ap.add_argument("--dump-on-save", default="",
                    help="dir for per-committed-step state npz dumps")
    ap.add_argument("--dump-restored", default="",
                    help="npz path for the state right after restore")
    ap.add_argument("--dump-sample-ids", default="",
                    help="dir for per-step consumed-sample-id npy dumps "
                         "(the exactly-once probe)")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    if args.world > 1 and args.coordinator is None:
        import socket
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        args.coordinator = f"127.0.0.1:{s.getsockname()[1]}"
        s.close()

    if args.rank is not None or args.world <= 1:
        args.rank = args.rank or 0
        run_rank(args)
        return

    # launcher mode: one subprocess per rank; exit code is rank 0's
    # (the supervisor contract — 75 means "restart me, maybe smaller")
    procs = []
    for r in range(args.world):
        cmd = [sys.executable, os.path.abspath(__file__), "--rank",
               str(r)]
        for k, v in vars(args).items():
            if k == "rank" or isinstance(v, bool) or v is None:
                continue
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        if args.cpu:
            cmd.append("--cpu")
        if args.fsdp:   # bools are skipped above; forward explicitly
            cmd.append("--fsdp")
        procs.append(subprocess.Popen(cmd))
    rcs = [p.wait() for p in procs]
    print(f"launcher: rank exit codes {rcs}", flush=True)
    sys.exit(rcs[0])


if __name__ == "__main__":
    main()
