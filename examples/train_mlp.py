"""Train the MLP on a synthetic two-moon-ish dataset
(reference examples/mlp/train.py — reference generates synthetic data
from a line boundary the same way)."""

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=64)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, metric, opt, tensor
    from singa_tpu.models import mlp

    # reference data: points above/below the line y = 5x + 1
    # (examples/mlp/train.py)
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, (1024, 2)).astype(np.float32)
    y = (x[:, 1] > 5 * x[:, 0] + 1).astype(np.int64)

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    model = mlp.create_model(num_classes=2)
    model.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    tx = tensor.Tensor(data=x[:args.bs], device=dev, requires_grad=False)
    model.compile([tx], is_train=True, use_graph=True)

    acc = metric.Accuracy()
    for epoch in range(args.epochs):
        idx = rng.permutation(len(x))
        losses, accs = [], []
        for b in range(len(x) // args.bs):
            sel = idx[b * args.bs:(b + 1) * args.bs]
            bx = tensor.Tensor(data=x[sel], device=dev,
                               requires_grad=False)
            by = tensor.Tensor(data=np.eye(2, dtype=np.float32)[y[sel]],
                               device=dev, requires_grad=False)
            out, loss = model(bx, by)
            losses.append(float(loss.data))
            accs.append(acc.evaluate(out, y[sel]))
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"acc {np.mean(accs):.4f}")


if __name__ == "__main__":
    main()
