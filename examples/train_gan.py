"""Adversarial training, vanilla or LSGAN
(reference examples/gan/vanilla.py, lsgan.py). Synthetic 'MNIST-like'
data unless --data npz with array x is given."""

import argparse
import sys

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("kind", nargs="?", default="vanilla",
                    choices=["vanilla", "lsgan"])
    ap.add_argument("--bs", type=int, default=64)
    ap.add_argument("--iters", type=int, default=100)
    ap.add_argument("--noise", type=int, default=100)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--data", default=None)
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import autograd, device, opt, tensor
    from singa_tpu.models import gan

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    rng = np.random.RandomState(0)
    feature = 784
    if args.data:
        real_all = np.load(args.data)["x"].reshape(-1, feature)
        real_all = real_all.astype(np.float32) / real_all.max()
    else:
        # blobby fake digits: low-rank structure the G can chase
        basis = rng.rand(16, feature).astype(np.float32)
        codes = rng.rand(4096, 16).astype(np.float32)
        real_all = np.clip(codes @ basis / 4.0, 0, 1)

    model = gan.create_model(args.kind, noise_size=args.noise,
                             feature_size=feature)
    model.set_optimizer(opt.SGD(lr=0.01, momentum=0.5))
    noise0 = tensor.Tensor(data=rng.randn(args.bs, args.noise)
                           .astype(np.float32), device=dev,
                           requires_grad=False)
    real0 = tensor.Tensor(data=real_all[:args.bs], device=dev,
                          requires_grad=False)
    model.compile_gan(noise0, real0)
    model.train()

    ones = np.ones((args.bs, 1), np.float32)
    zeros = np.zeros((args.bs, 1), np.float32)
    d_y = tensor.Tensor(data=np.concatenate([ones, zeros]), device=dev,
                        requires_grad=False)
    g_y = tensor.Tensor(data=ones, device=dev, requires_grad=False)

    for it in range(args.iters):
        sel = rng.randint(0, len(real_all), args.bs)
        real = tensor.Tensor(data=real_all[sel], device=dev,
                             requires_grad=False)
        noise = tensor.Tensor(
            data=rng.randn(args.bs, args.noise).astype(np.float32),
            device=dev, requires_grad=False)
        fake = model.forward_gen(noise)
        d_in = autograd.cat([real, fake], axis=0)
        _, d_loss = model.train_one_batch_dis(d_in, d_y)
        _, g_loss = model.train_one_batch(noise, g_y)
        if it % 20 == 0:
            print(f"iter {it}: d_loss {float(d_loss.data):.4f} "
                  f"g_loss {float(g_loss.data):.4f}")


if __name__ == "__main__":
    main()
