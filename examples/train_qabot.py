"""QA answer-selection training (reference examples/qabot/qabot_train.py):
encode question and candidate answers with biLSTMs, score by cosine
similarity, train with margin ranking loss over (positive, negative)
pairs, evaluate by top-1 accuracy over a candidate pool.

Runs on synthetic embedded data (the reference downloads the InsuranceQA
corpus + GloVe vectors; the model/training machinery is identical).
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def synthetic_qa(rng, n, seq_len, embed, n_topics=10):
    """Questions and answers about the same 'topic' share a direction in
    embedding space; the positive answer matches the question's topic."""
    topics = rng.randn(n_topics, embed).astype(np.float32)
    t = rng.randint(0, n_topics, n)
    t_neg = (t + 1 + rng.randint(0, n_topics - 1, n)) % n_topics
    q = topics[t][:, None, :] + 0.3 * rng.randn(n, seq_len, embed)
    a_pos = topics[t][:, None, :] + 0.3 * rng.randn(n, seq_len, embed)
    a_neg = topics[t_neg][:, None, :] + 0.3 * rng.randn(n, seq_len, embed)
    return (q.astype(np.float32), a_pos.astype(np.float32),
            a_neg.astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kind", default="max",
                    choices=["lstm", "mean", "max", "mlp"])
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--bs", type=int, default=16)
    ap.add_argument("--n", type=int, default=128)
    ap.add_argument("--seq-len", type=int, default=10)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (hermetic runs)")
    args = ap.parse_args()

    if args.cpu or os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, opt, tensor
    from singa_tpu.models import qabot

    dev = device.create_tpu_device()
    dev.SetRandSeed(7)
    rng = np.random.RandomState(0)
    q, a_pos, a_neg = synthetic_qa(rng, args.n, args.seq_len, args.embed)

    m = qabot.create_model(args.kind, hidden_size=args.hidden)
    m.set_optimizer(opt.SGD(lr=args.lr, momentum=0.9))
    m.train()

    for epoch in range(args.epochs):
        idx = rng.permutation(args.n)
        t0, losses, correct = time.time(), [], 0
        for b in range(args.n // args.bs):
            sel = idx[b * args.bs:(b + 1) * args.bs]
            tq = tensor.Tensor(data=q[sel], device=dev,
                               requires_grad=False)
            ta = tensor.Tensor(
                data=np.concatenate([a_pos[sel], a_neg[sel]]),
                device=dev, requires_grad=False)
            sp, sn, loss = m.train_one_batch(tq, ta)
            losses.append(float(loss.data))
            correct += int((np.asarray(sp.data) >
                            np.asarray(sn.data)).sum())
        seen = (args.n // args.bs) * args.bs
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"top1 {correct / seen:.3f} "
              f"({seen / (time.time() - t0):.1f} pairs/s)")


if __name__ == "__main__":
    main()
