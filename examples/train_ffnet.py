"""Sequential FeedForwardNet training — the convenience-trainer path
(reference examples/cpp/cifar10/alexnet.cc drives
FeedForwardNet::Train/Evaluate, include/singa/model/feed_forward_net.h:
63-116; here the same capability through singa_tpu.net on synthetic
CIFAR-shaped data: add layers, compile with loss+metric, fit/evaluate).
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (hermetic runs)")
    args = ap.parse_args()

    if args.cpu or os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, layer, metric, net, opt, tensor

    dev = device.create_tpu_device()
    dev.SetRandSeed(7)

    # synthetic separable data: class = argmax of a fixed projection
    rng = np.random.RandomState(0)
    x = rng.randn(args.n, 3, args.size, args.size).astype(np.float32)
    w = rng.randn(3 * args.size * args.size, 10)
    yi = np.argmax(x.reshape(args.n, -1) @ w, axis=1)
    y = np.eye(10, dtype=np.float32)[yi]

    model = net.FeedForwardNet()
    model.add(layer.Conv2d(16, 3, padding=1))
    model.add(layer.ReLU())
    model.add(layer.MaxPool2d(2, 2))
    model.add(layer.Conv2d(32, 3, padding=1))
    model.add(layer.ReLU())
    model.add(layer.MaxPool2d(2, 2))
    model.add(layer.Flatten())
    model.add(layer.Linear(10))

    tx = tensor.Tensor(data=x[:args.bs], device=dev, requires_grad=False)
    model.compile_net(opt.SGD(lr=args.lr, momentum=0.9), [tx],
                      loss=layer.SoftMaxCrossEntropy(),
                      metric=metric.Accuracy())
    model.fit(x, y, batch_size=args.bs, epochs=args.epochs, dev=dev)
    loss, acc = model.evaluate(x, y, batch_size=args.bs, dev=dev)
    print(f"final eval: loss {loss:.4f} accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
