"""Serve a TransformerLM behind the continuous-batching engine and the
stdlib HTTP gateway — the serving counterpart of train_elastic.py: real
enough to chaos-test, small enough to read.

Modes:

- default: start the engine + gateway, print ``READY port=N``, then
  block until SIGTERM/SIGINT. The signal triggers a **graceful drain**
  (in-flight and queued requests all finish, new ones get 503) and the
  process exits 0 (``serving.EXIT_DRAINED``) — kill -TERM is how a
  supervisor rolls a replica, and exit 0 tells it the drain completed.
- ``--selftest N``: additionally fire N generation requests at the own
  gateway from client threads, assert every one returns exactly once
  with the requested token count and that the decode program traced
  exactly once, print ``SELFTEST OK`` and exit 0 (the CI smoke).
- ``--pool-role prefill --decode-peers P1,P2``: disaggregated pools
  across processes. This replica admits and chunk-prefills only; each
  finished prefill is sealed (CRC-framed KV snapshot) and transferred
  to a decode gateway chosen by prefix affinity (rendezvous hash of
  the prompt's block-aligned chain key over the peer list, so a
  repeated prefix keeps landing where its KV already lives). Failure
  ladder per transfer: typed 409 refusal (corrupt frame) or a dead
  peer → next-best peer → recompute via ``/v1/generate`` on any live
  peer → typed error; no live peers at seal time → colocate (this
  replica decodes it after all). ``--pool-role decode`` marks the
  receiving side (it serves ``/v1/inject`` continuations and plain
  generates). Both sides must share KV geometry.
- ``--autoscale MIN``: fleet mode. MIN in-process replicas (each its
  own engine + metrics registry) behind a ``FleetRouter``, an
  ``Autoscaler`` supervising the population against SLO targets
  (scale-up on sustained breach, drain+handoff retirement on calm,
  crash/stale replacement, flap quarantine), ONE gateway fronting the
  router. With ``--aot-dir`` every spawned replica must pass the
  warm-admission gate (zero fresh compiles); backpressure 503s carry
  a ``Retry-After`` from the scaler's observed spawn-to-ready median.
  ``--selftest`` prints ``AUTOSCALE OK`` instead of ``SELFTEST OK``.

Usage::

    python examples/serve_transformer.py --cpu --port 8901
    curl -d '{"prompt": [1,2,3], "max_new_tokens": 8}' \
        http://127.0.0.1:8901/v1/generate
    curl -X POST http://127.0.0.1:8901/drain     # or: kill -TERM <pid>
"""

import argparse
import base64
import json
import sys
import threading
import time

import numpy as np

sys.path.insert(0, ".")


def _post(port, path, doc, timeout=120.0):
    import http.client
    c = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        c.request("POST", path, json.dumps(doc),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        return r.status, json.loads(r.read().decode() or "{}")
    finally:
        c.close()


def _make_handoff(peers, timeout):
    """The deadline drain's migration callable: offer each unfinished
    request to the peer gateways — sealed-snapshot inject first (the
    continuation is bitwise-identical, zero recomputed prefill), typed
    409 refusal → recompute via /v1/generate on the same peer, dead
    peer → next peer. Ownership moves to a relay thread (the drain
    must not block on a peer's decode); the thread resolves the
    request's future exactly once, typed on total failure."""

    def handoff(req, snapshot, budget):
        if not peers:
            return False

        def run():
            from singa_tpu.serving import EngineDraining
            doc = None
            for p in peers:
                try:
                    if snapshot is not None:
                        st, d = _post(p, "/v1/inject", {
                            "meta": base64.b64encode(
                                snapshot["meta"]).decode(),
                            "frame": base64.b64encode(
                                snapshot["frame"]).decode(),
                            "timeout": timeout}, timeout=timeout)
                        if st == 200:
                            doc = d
                            break
                        if st != 409:
                            continue    # peer trouble: next peer
                        # 409 = typed refusal: recompute, same peer
                    body = {"prompt": [int(t) for t in req.prompt],
                            "max_new_tokens": req.max_new_tokens,
                            "temperature": req.temperature,
                            "request_id": req.trace_id,
                            "timeout": timeout}
                    if req.top_k is not None:
                        body["top_k"] = req.top_k
                    if req.eos_id is not None:
                        body["eos_id"] = req.eos_id
                    st, d = _post(p, "/v1/generate", body,
                                  timeout=timeout)
                    if st == 200:
                        doc = d
                        break
                except OSError:
                    continue
            if req.future.done():
                return
            if doc is None:
                req.future.set_error(EngineDraining(
                    "handoff failed: no peer accepted the request"))
            else:
                req.future.set_result(doc)

        threading.Thread(target=run, daemon=True,
                         name="handoff-relay").start()
        return True

    return handoff


def _make_pool_transfer(peers, timeout, reg, affinity, block_size):
    """The prefill pool's transfer callable (``engine.set_transfer``):
    route each sealed slot to a decode gateway by prefix affinity and
    walk the failure ladder across processes. Rungs: typed 409
    refusal or a dead socket → next-best peer; all injects refused →
    recompute via ``/v1/generate`` on a live peer (greedy makes the
    recompute bitwise-identical, it just pays prefill again); nothing
    live at seal time → return False, which is the colocate rung (the
    prefill engine keeps the slot and decodes it itself). A relay
    thread owns the request once we return True — the engine tick
    must never block on a peer's decode — and resolves the future
    exactly once, typed on total failure."""
    from singa_tpu.serving import affinity_hash, prefix_chain_key

    dead = set()
    owner = {}              # prefix chain key → port that served it
    hits = reg.counter("serve_pool_affinity_hit_total",
                       "transfers landing on the decode peer that "
                       "already served this prefix chain")
    misses = reg.counter("serve_pool_affinity_miss_total",
                         "transfers landing on a decode peer cold "
                         "for this prefix chain")
    retries = reg.counter("serve_pool_transfer_retry_total",
                          "transfer attempts that moved to the "
                          "next-best decode peer (refused frame or "
                          "dead socket)")

    def transfer(req, snapshot, _resnap):
        live = [p for p in peers if p not in dead]
        if not live:
            return False                    # colocate rung
        key = prefix_chain_key([int(t) for t in req.prompt],
                               block_size)
        if affinity and key is not None:
            order = sorted(live, key=lambda p: affinity_hash(
                key, salt=str(p)), reverse=True)
        else:
            order = live[hash(req.trace_id) % len(live):] + \
                live[:hash(req.trace_id) % len(live)]

        def run():
            import http.client as _hc

            from singa_tpu.serving import ReplicaCrashed
            doc, served_by = None, None
            # a peer SIGKILLed mid-response surfaces as any of these
            wire_dead = (OSError, _hc.HTTPException, ValueError)
            for p in order:
                try:
                    st, d = _post(p, "/v1/inject", {
                        "meta": base64.b64encode(
                            snapshot["meta"]).decode(),
                        "frame": base64.b64encode(
                            snapshot["frame"]).decode(),
                        "timeout": timeout}, timeout=timeout)
                except wire_dead:
                    dead.add(p)
                    retries.inc()
                    continue
                if st == 200:
                    doc, served_by = d, p
                    break
                retries.inc()   # 409: refused typed; the frame is
                                # bad everywhere, the recompute rung
                                # below picks it up
            if doc is None:
                for p in order:
                    if p in dead:
                        continue
                    try:
                        st, d = _post(
                            p, "/v1/generate",
                            {"prompt": [int(t) for t in req.prompt],
                             "max_new_tokens": req.max_new_tokens,
                             "temperature": req.temperature,
                             "request_id": req.trace_id,
                             "timeout": timeout}, timeout=timeout)
                    except wire_dead:
                        dead.add(p)
                        continue
                    if st == 200:
                        doc, served_by = d, p
                        break
            if req.future.done():
                return
            if doc is None:
                req.future.set_error(ReplicaCrashed(
                    "pool transfer failed: no decode peer took the "
                    "request"))
                return
            if key is not None:
                (hits if owner.get(key) == served_by
                 else misses).inc()
                owner[key] = served_by
            req.future.set_result(doc)

        threading.Thread(target=run, daemon=True,
                         name="pool-transfer-relay").start()
        return True

    return transfer


def _selftest(port, n, vocab, new_tokens=8, temperature=0.5):
    rng = np.random.RandomState(0)
    results = [None] * n

    def one(i):
        prompt = rng.randint(1, vocab, (int(rng.randint(1, 8)),)).tolist()
        results[i] = _post(port, "/v1/generate",
                           {"prompt": prompt,
                            "max_new_tokens": new_tokens,
                            "temperature": temperature, "seed": i})

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    bad = [(i, r) for i, r in enumerate(results)
           if r is None or r[0] != 200
           or len(r[1].get("tokens", [])) != new_tokens]
    if bad:
        raise SystemExit(f"SELFTEST FAILED: {bad[:3]}")


def _run_autoscale(args, model, serve_kw):
    """Fleet mode: ``--autoscale MIN`` replicas behind a FleetRouter
    with an Autoscaler driving the population (see module docstring).
    Single-device engines only — the sharded flags don't compose with
    in-process fleet replicas."""
    import itertools
    import signal as _signal

    from singa_tpu.observability import metrics as obs_metrics
    from singa_tpu.serving import (Autoscaler, AutoscaleTargets,
                                   FleetRouter, ServingReplica,
                                   ShedPolicy, serve_gateway)

    seq = itertools.count()

    def spawn():
        i = next(seq)
        reg = obs_metrics.MetricsRegistry()
        eng = model.compile_serving(
            slots=args.slots, max_len=args.max_len,
            prefill_len=args.prefill_len, policy=args.policy,
            registry=reg, **serve_kw)
        if args.aot_dir:
            src = dict(eng.compiled_step_info()["aot"] or {})
            if not src or any(v != "loaded" for v in src.values()):
                # cold spin-up exports back: the NEXT spawn (the one
                # the warm-admission gate judges) deserializes
                eng.export_aot()
        return ServingReplica(eng, name=f"r{i}").start()

    fleet_reg = obs_metrics.MetricsRegistry()
    router = FleetRouter([spawn() for _ in range(args.autoscale)],
                         registry=fleet_reg,
                         shed_policy=ShedPolicy(window_s=1.0))
    scaler = Autoscaler(
        router, spawn,
        targets=AutoscaleTargets(min_replicas=args.autoscale,
                                 max_replicas=args.max_replicas),
        registry=fleet_reg, interval=args.autoscale_interval,
        require_warm=bool(args.aot_dir),
        probe_timeout=args.default_timeout)
    scaler.start()
    server, port = serve_gateway(
        router, port=args.port,
        default_timeout=args.default_timeout,
        max_body_bytes=args.max_body_bytes,
        retry_after=scaler.retry_after_hint)
    print(f"READY port={port} replicas={router.population()}",
          flush=True)

    def shutdown():
        scaler.stop()
        ok = router.drain(timeout=args.drain_timeout)
        server.shutdown()
        server.server_close()
        return 0 if ok else 1

    if args.selftest:
        _selftest(port, args.selftest, args.vocab, temperature=0.5)
        st = scaler.status()
        code = shutdown()
        print(f"AUTOSCALE OK n={args.selftest} "
              f"population={st['population']} "
              f"quarantined={st['quarantined_seats']} "
              f"drain_exit={code}", flush=True)
        return code

    stop = threading.Event()
    for s in (_signal.SIGTERM, _signal.SIGINT):
        _signal.signal(s, lambda *_: stop.set())
    stop.wait()
    code = shutdown()
    print(f"DRAINED exit={code}", flush=True)
    return code


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0,
                    help="gateway port (0 = ephemeral, printed as READY)")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=32)
    ap.add_argument("--heads", type=int, default=2)
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--prefill-len", type=int, default=16)
    ap.add_argument("--policy", default=None,
                    help="mixed-precision policy name (e.g. bf16_mixed)")
    ap.add_argument("--kv-layout", default="ring",
                    choices=("ring", "paged"),
                    help="KV cache layout: the ring (default) or the "
                         "paged block pool with prefix sharing "
                         "(docs/serving.md)")
    ap.add_argument("--kv-block-size", type=int, default=16,
                    help="paged layout: tokens per KV block")
    ap.add_argument("--kv-blocks", type=int, default=None,
                    help="paged layout: pool size in blocks (default "
                         "slots x ceil(max_len/block_size))")
    ap.add_argument("--speculative-k", type=int, default=0,
                    help="speculative decoding: verify-program width "
                         "(up to K tokens per tick, greedy requests "
                         "only; needs --kv-layout paged)")
    ap.add_argument("--model-shards", type=int, default=0,
                    help="GSPMD sharded serving: tensor-parallel "
                         "degree over a (batch × model) device mesh "
                         "(heads/MLP/vocab sharded, XLA inserts the "
                         "collectives; greedy-only — "
                         "docs/serving.md). 0 = single-device")
    ap.add_argument("--mesh", default=None, metavar="BxM",
                    help="explicit serving mesh shape, e.g. 2x2 "
                         "(batch × model axes over the first B*M "
                         "devices); overrides --model-shards")
    ap.add_argument("--aot-dir", default=None, metavar="DIR",
                    help="cold-start elimination (singa_tpu.aot): "
                         "deserialize matching prefill/decode "
                         "executables from DIR instead of tracing "
                         "(persistent compile cache under "
                         "DIR/xla-cache); programs compiled fresh are "
                         "exported back so the NEXT spin-up is warm")
    ap.add_argument("--autoscale", type=int, default=0, metavar="MIN",
                    help="fleet mode: MIN in-process replicas behind "
                         "a FleetRouter with an SLO-driven Autoscaler "
                         "supervising the population (scale-up on "
                         "sustained breach, drain+handoff retirement, "
                         "crash replacement, flap quarantine); with "
                         "--aot-dir spawns must pass the "
                         "warm-admission gate (0 = single-replica "
                         "mode)")
    ap.add_argument("--max-replicas", type=int, default=3,
                    help="autoscale population ceiling")
    ap.add_argument("--autoscale-interval", type=float, default=0.25,
                    help="supervision tick period (seconds)")
    ap.add_argument("--selftest", type=int, default=0, metavar="N",
                    help="fire N requests at the own gateway, verify, "
                         "exit 0")
    ap.add_argument("--drain-timeout", type=float, default=60.0)
    ap.add_argument("--drain-deadline", type=float, default=None,
                    help="preemption budget (seconds) armed on "
                         "SIGTERM/SIGINT: finish what fits, hand off "
                         "(--handoff-peers) or fail-typed the rest by "
                         "the deadline instead of waiting out "
                         "--drain-timeout")
    ap.add_argument("--handoff-peers", default=None, metavar="PORTS",
                    help="comma-separated peer gateway ports: a "
                         "deadline drain migrates unfinished requests "
                         "there (POST /v1/inject with the sealed KV "
                         "snapshot; recompute via /v1/generate when "
                         "the peer refuses typed)")
    ap.add_argument("--pool-role", default=None,
                    choices=("prefill", "decode"),
                    help="disaggregated pools: tag this replica's "
                         "role (prefill seals+transfers finished "
                         "slots to --decode-peers; decode receives "
                         "/v1/inject continuations). Both sides must "
                         "share KV geometry")
    ap.add_argument("--decode-peers", default=None, metavar="PORTS",
                    help="comma-separated decode gateway ports the "
                         "prefill pool transfers sealed KV to "
                         "(prefix-affinity ordered; failure ladder "
                         "in the module docstring)")
    ap.add_argument("--no-affinity", action="store_true",
                    help="order decode peers round-robin instead of "
                         "by prefix affinity (the A/B measurement "
                         "baseline for the affinity hit counters)")
    ap.add_argument("--fault-corrupt-transfer", type=int, default=0,
                    metavar="SEQ",
                    help="chaos: arm FaultPlan.corrupt_handoff(SEQ) — "
                         "flip a bit in the SEQ-th sealed KV frame so "
                         "the receiving decode peer refuses it typed "
                         "(0 = off)")
    ap.add_argument("--spill-bytes", type=int, default=0,
                    help="host-RAM spill tier byte budget for evicted "
                         "cached-prefix KV blocks (paged layout; 0 = "
                         "off)")
    ap.add_argument("--snapshot-every", type=int, default=0,
                    help="checkpoint in-flight KV snapshots every N "
                         "ticks so a crash re-dispatch resumes from "
                         "the last snapshot instead of token zero "
                         "(0 = off)")
    ap.add_argument("--default-timeout", type=float, default=120.0,
                    help="per-request deadline budget (seconds) when "
                         "the body carries no timeout; the engine SLO "
                         "timeout and the gateway's own wait are both "
                         "derived from this ONE clock")
    ap.add_argument("--max-body-bytes", type=int, default=8 << 20,
                    help="refuse request bodies over this size with "
                         "413 before reading them")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from singa_tpu import device, tensor
    from singa_tpu.models import transformer
    from singa_tpu.serving import ServingReplica, serve_gateway

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)
    model = transformer.TransformerLM(
        args.vocab, d_model=args.d_model, n_heads=args.heads,
        n_layers=args.layers, max_len=args.max_len, tp=False)
    model.eval()
    # one eager forward materialises the lazily-initialised params the
    # serving adapter host-gathers
    model(tensor.Tensor(
        data=np.zeros((1, args.prefill_len), np.float32), device=dev,
        requires_grad=False))

    serve_kw = {}
    if args.aot_dir:
        from singa_tpu.aot import cache as aot_cache
        serve_kw["aot_store"] = args.aot_dir
        serve_kw["compile_cache"] = aot_cache.cache_dir_for(
            args.aot_dir)
    if args.kv_layout != "ring":
        serve_kw.update(kv_layout=args.kv_layout,
                        kv_block_size=args.kv_block_size,
                        kv_blocks=args.kv_blocks)
    if args.speculative_k:
        serve_kw["speculative_k"] = args.speculative_k
    if args.spill_bytes:
        serve_kw["spill_bytes"] = args.spill_bytes
    if args.snapshot_every:
        serve_kw["snapshot_every"] = args.snapshot_every
    if args.pool_role:
        serve_kw["pool_role"] = args.pool_role
    if args.fault_corrupt_transfer:
        from singa_tpu.resilience.faults import FaultPlan
        plan = FaultPlan()
        plan.corrupt_handoff(args.fault_corrupt_transfer, times=1)
        serve_kw["faults"] = plan
    if args.autoscale:
        return _run_autoscale(args, model, serve_kw)
    sharded = bool(args.model_shards or args.mesh)
    if args.mesh:
        import jax
        from singa_tpu.parallel import gspmd
        b, m_ = (int(x) for x in args.mesh.lower().split("x"))
        serve_kw["mesh"] = gspmd.serving_mesh(
            jax.devices()[:b * m_], model_shards=m_, batch_shards=b)
    elif args.model_shards:
        serve_kw["model_shards"] = args.model_shards
    engine = model.compile_serving(
        slots=args.slots, max_len=args.max_len,
        prefill_len=args.prefill_len, policy=args.policy, **serve_kw)
    if sharded:
        info = engine.compiled_step_info()
        print(f"SHARDED mesh=batch{info['mesh']['batch']}x"
              f"model{info['mesh']['model']} "
              f"kv_per_device_bytes={info['kv_per_device_bytes']}",
              flush=True)
    if args.aot_dir:
        src = dict(engine.compiled_step_info()["aot"] or {})
        if not src or any(v != "loaded" for v in src.values()):
            # cold spin-up: leave warm artifacts behind for the next
            # replica (the chaos warm-restart scenario's populate
            # leg); export_aot refreshes the engine's audit state, so
            # /healthz and /aot.json report "exported" too
            engine.export_aot()
            src = dict(engine.compiled_step_info()["aot"] or {})
        print("AOT " + " ".join(
            f"{p.split('serve_', 1)[-1]}={v}"
            for p, v in sorted(src.items())), flush=True)
    if args.decode_peers:
        peers = [int(p) for p in args.decode_peers.split(",") if p]
        engine.set_transfer(_make_pool_transfer(
            peers, args.default_timeout, engine._reg,
            affinity=not args.no_affinity,
            block_size=args.kv_block_size))
    replica = ServingReplica(engine, name=f"serve-{args.port}")
    replica.install_signal_handlers(deadline=args.drain_deadline)
    replica.start()
    server, port = serve_gateway(engine, port=args.port,
                                 replica=replica,
                                 default_timeout=args.default_timeout,
                                 max_body_bytes=args.max_body_bytes)
    print(f"READY port={port}", flush=True)

    if args.selftest:
        # sharded serving is greedy-only (in-graph argmax over the
        # vocab shards): the smoke drives it at temperature 0
        _selftest(port, args.selftest, args.vocab,
                  temperature=0.0 if sharded else 0.5)
        info = engine.compiled_step_info()
        assert info["n_traces"] == 1, \
            f"decode retraced: {info['n_traces']}"
        replica.request_drain()
        code = replica.drain(timeout=args.drain_timeout)
        server.shutdown()
        server.server_close()
        print(f"SELFTEST OK n={args.selftest} n_traces=1 "
              f"drain_exit={code}", flush=True)
        return code

    handoff = None
    if args.handoff_peers:
        peers = [int(p) for p in args.handoff_peers.split(",") if p]
        handoff = _make_handoff(peers, args.default_timeout)
    drain_started = {}

    def _watch():
        replica._drain_evt.wait()
        drain_started["t"] = time.monotonic()

    threading.Thread(target=_watch, daemon=True,
                     name="drain-watch").start()
    # poll=0.05: a preemption deadline is seconds — the gap between
    # the signal and the blocking drain must not eat half the budget
    code = replica.run_until_drained(poll=0.05,
                                     timeout=args.drain_timeout,
                                     handoff=handoff)
    # DRAIN_DONE times the ENGINE drain (the preemption-deadline
    # contract) — printed before server_close(), whose handler-thread
    # join legitimately extends past the deadline while migrated
    # responses relay back from the peers
    if "t" in drain_started:
        print(f"DRAIN_DONE in={time.monotonic() - drain_started['t']:.2f}s",
              flush=True)
    # stop accepting, then join in-flight handler threads: every
    # admitted request's HTTP response is written before exit
    server.shutdown()
    server.server_close()
    print(f"DRAINED exit={code}", flush=True)
    return code


if __name__ == "__main__":
    raise SystemExit(main())
