"""Train a CNN-family model (reference examples/cnn/train_cnn.py).

Synthetic data by default (the reference downloads CIFAR-10/MNIST; this
environment has no egress) — pass --data path/to/npz with arrays x,y to
train on real data. Supports the reference's distributed options:
plain | half | partialUpdate | sparseTopK | sparseThreshold.

Usage: python examples/train_cnn.py [cnn|alexnet|resnet|xceptionnet]
           [--bs 32] [--epochs 2] [--lr 0.05] [--dist]
           [--dist-option plain] [--spars 0.05] [--cpu]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?", default="cnn",
                    choices=["cnn", "alexnet", "resnet", "xceptionnet"])
    ap.add_argument("--bs", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--dist", action="store_true")
    ap.add_argument("--dist-option", default="plain")
    ap.add_argument("--spars", type=float, default=0.05)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--data", default=None)
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import device, metric, opt, tensor
    from singa_tpu import models

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)

    size = {"cnn": 28, "alexnet": 224, "resnet": 224,
            "xceptionnet": 299}[args.model]
    chans = 1 if args.model == "cnn" else 3
    if args.data:
        blob = np.load(args.data)
        x_all, y_all = blob["x"].astype(np.float32), blob["y"]
    else:
        rng = np.random.RandomState(0)
        n = args.bs * args.iters
        x_all = rng.randn(n, chans, size, size).astype(np.float32)
        y_all = rng.randint(0, 10, n)

    factory = getattr(models, args.model)
    model = factory.create_model(num_channels=chans, num_classes=10)
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-5)
    model.set_optimizer(opt.DistOpt(sgd) if args.dist else sgd)

    tx = tensor.Tensor(data=x_all[:args.bs], device=dev,
                       requires_grad=False)
    model.compile([tx], is_train=True, use_graph=True)

    acc = metric.Accuracy()
    for epoch in range(args.epochs):
        idx = np.random.permutation(len(x_all))
        t0, seen, losses, accs = time.time(), 0, [], []
        for b in range(len(x_all) // args.bs):
            sel = idx[b * args.bs:(b + 1) * args.bs]
            bx = tensor.Tensor(data=x_all[sel], device=dev,
                               requires_grad=False)
            by = tensor.Tensor(
                data=np.eye(10, dtype=np.float32)[y_all[sel]],
                device=dev, requires_grad=False)
            if args.dist and args.dist_option != "plain":
                out, loss = model(bx, by, args.dist_option, args.spars)
            else:
                out, loss = model(bx, by)
            losses.append(float(loss.data))
            accs.append(acc.evaluate(out, y_all[sel]))
            seen += args.bs
        dt = time.time() - t0
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"acc {np.mean(accs):.4f} "
              f"throughput {seen / dt:.1f} img/s")


if __name__ == "__main__":
    main()
