"""Train a CNN-family model on CIFAR-10/100, MNIST, or synthetic data.

Parity with the reference's north-star command (examples/cnn/
train_cnn.py:97-263): ``python examples/train_cnn.py resnet cifar10``
trains with shuffling + batched random-crop/flip augmentation and prints
training loss/accuracy and evaluation accuracy per epoch. Differences
are TPU-idiomatic: augmentation and resize are vectorized over the batch
(no per-sample PIL loops), and training runs the traced/compiled graph
path.

Datasets are read from local files (no egress): see singa_tpu/datasets.py
for the accepted locations/formats. ``synthetic`` needs no files.

Usage: python examples/train_cnn.py [cnn|alexnet|resnet|xceptionnet|mlp]
           [cifar10|cifar100|mnist|synthetic] [--data-dir DIR]
           [--bs 64] [--epochs 10] [--lr 0.05]
           [-p float32|bfloat16|bf16_mixed] [--layout auto|NCHW|NHWC]
           [--dist] [--dist-option plain|half|partialUpdate|
            sparseTopK|sparseThreshold] [--spars 0.05] [--cpu]
           [--mesh DxM] [--fsdp]
           [--bucket-mb 0] [--no-overlap] [--fused-optim]
           [--verbosity 0] [--npz path.npz]
           [--resilient] [--ckpt-dir ckpts_cnn] [--save-every 50]
           [--profile-every 0] [--anomaly-factor F]

``-p bf16_mixed`` trains under the mixed-precision compile policy
(``Model.compile(policy="bf16_mixed")``): fp32 master weights (what
checkpoints store) with bf16 conv/matmul compute and dynamic loss
scaling — the TPU production setting. ``--layout auto`` (resnet) uses
the banked ``resnet_layout_ab`` hardware A/B winner so the example runs
the measured-fastest conv layout, falling back to NCHW when unmeasured.

``--resilient`` runs the fault-tolerant driver instead of the bare
epoch loop: NaN/divergence guards (singa_tpu/resilience/guards.py)
skip bad steps on-device, training checkpoints every ``--save-every``
steps, SIGTERM/SIGINT preemption checkpoints synchronously and exits
75 for the restart supervisor, and a relaunched command resumes from
the newest restorable checkpoint automatically.
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def _measured_layout():
    """Conv-trunk layout for --layout auto: the banked
    ``resnet_layout_ab`` hardware A/B winner via bench._conv_layout
    (env pin > fresh banked measurement > NCHW default), so the example
    — not just the benchmark — runs the measured-fastest form. Falls
    back to NCHW when bench.py or its observations are unreachable
    (e.g. the example is run outside the repo root)."""
    try:
        import bench
        return bench._conv_layout()
    except Exception as e:  # noqa: BLE001 — the example must still run
        return "NCHW", f"unmeasured-fallback ({type(e).__name__})"


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("model", nargs="?", default="cnn",
                    choices=["cnn", "alexnet", "resnet", "xceptionnet",
                             "mlp"])
    ap.add_argument("data", nargs="?", default="synthetic",
                    choices=["cifar10", "cifar100", "mnist", "synthetic"])
    ap.add_argument("--data-dir", default=None,
                    help="directory holding the standard dataset files")
    ap.add_argument("--bs", "-b", type=int, default=64)
    ap.add_argument("--epochs", "-m", type=int, default=10)
    ap.add_argument("--iters", type=int, default=20,
                    help="synthetic-data batches per epoch")
    ap.add_argument("--max-batches", type=int, default=0,
                    help="cap train batches per epoch (0 = all); "
                         "lets CI run a real epoch quickly")
    ap.add_argument("--lr", "-l", type=float, default=0.05)
    ap.add_argument("-p", "--precision", default="float32",
                    choices=["float32", "bfloat16", "bf16_mixed"],
                    help="bf16_mixed compiles the model under the "
                         "mixed-precision policy (fp32 masters + loss "
                         "scaling, bf16 compute); bfloat16 is the "
                         "legacy pure-bf16 input cast")
    ap.add_argument("--dist", action="store_true")
    ap.add_argument("--dist-option", default="plain")
    ap.add_argument("--spars", type=float, default=0.05)
    ap.add_argument("--mesh", default=None, metavar="DxM",
                    help="GSPMD train mesh 'DxM' (data x model degrees, "
                         "e.g. 8x1) — the train step compiles as ONE "
                         "jitted program with NamedSharding in/out "
                         "(Model.compile(mesh=...)); XLA inserts the "
                         "grad collectives. Mirrors serve_transformer's "
                         "--mesh")
    ap.add_argument("--fsdp", action="store_true",
                    help="ZeRO/FSDP on the GSPMD path: optimizer state "
                         "+ fp32 masters sharded over 'data', gathered "
                         "just-in-time inside the step (~Nx per-chip "
                         "optimizer-state headroom). Implies a default "
                         "data mesh when --mesh is not given")
    ap.add_argument("--bucket-mb", default="0",
                    help="with --dist: gradient-psum bucket size target "
                         "in MiB (DistOpt bucket_mb) — gradients "
                         "coalesce into size-targeted buckets, one "
                         "collective each, issued as backward produces "
                         "them so XLA hides them under remaining "
                         "backward compute; 0 = per-gradient streaming "
                         "psums (default); 'auto' resolves the banked "
                         "grad_bucket_ab winner via "
                         "bench._grad_bucket_mb (BENCH_BUCKET_MB pin "
                         "> measured winner > 0). Read the win off "
                         "timeline_exposed_collective_seconds")
    ap.add_argument("--no-overlap", action="store_true",
                    help="with --dist: pin every gradient collective "
                         "behind the FULL backward (the measured "
                         "no-overlap baseline an A/B compares against)")
    ap.add_argument("--fused-optim", action="store_true",
                    help="route eligible optimizer updates through the "
                         "one-HBM-pass Pallas kernels "
                         "(ops/fused_optim.py; declines to the "
                         "reference path off-TPU)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--no-augment", action="store_true")
    ap.add_argument("--verbosity", "-v", type=int, default=0)
    ap.add_argument("--layout", default="auto",
                    choices=["auto", "NCHW", "NHWC"],
                    help="conv-trunk activation layout (resnet only; "
                         "NHWC is the TPU lane-friendly form, applied "
                         "via ops.layout.use_layout inside the model). "
                         "'auto' runs the banked resnet_layout_ab "
                         "hardware A/B winner (bench._conv_layout) and "
                         "falls back to NCHW when unmeasured")
    ap.add_argument("--stem", default="conv7",
                    choices=["conv7", "space_to_depth"],
                    help="resnet stem: plain 7x7/s2 conv or its exact "
                         "space-to-depth reformulation")
    ap.add_argument("--npz", default=None,
                    help="npz with arrays x,y (overrides the data arg)")
    ap.add_argument("--telemetry", default=None, metavar="DIR",
                    help="write run telemetry into DIR: spans.jsonl "
                         "(live trace spans), metrics.json (registry "
                         "snapshot; feed to tools/metrics_dump.py) and "
                         "metrics.prom (Prometheus text)")
    ap.add_argument("--resilient", action="store_true",
                    help="train through the fault-tolerant driver "
                         "(checkpoint-restart + NaN guards + retry)")
    ap.add_argument("--ckpt-dir", default="ckpts_cnn",
                    help="checkpoint directory for --resilient")
    ap.add_argument("--save-every", type=int, default=50,
                    help="checkpoint interval (steps) for --resilient")
    ap.add_argument("--profile-every", type=int, default=0,
                    help="with --resilient: run every Nth step under a "
                         "profiler trace and refresh the "
                         "profile_fusion_* gauges (0 = off)")
    ap.add_argument("--anomaly-factor", type=float, default=None,
                    help="with --resilient: arm the step-time anomaly "
                         "sentinel at this spike factor (e.g. 3.0)")
    return ap


def _dump_telemetry(args, model):
    """End-of-run telemetry dump for --telemetry DIR: the metrics
    snapshot as JSON (the form tools/metrics_dump.py validates and
    converts) plus its Prometheus rendering; spans.jsonl has been
    streaming live since startup."""
    if not args.telemetry:
        return
    import json

    from singa_tpu.observability import export, metrics
    try:
        # enrich the snapshot with the step's XLA flop count (one AOT
        # re-lower, end of run — never on the step path)
        flops = model.step_flops(compute=True)
        if flops:
            metrics.default_registry().gauge(
                "train_step_flops",
                "XLA-counted FLOPs of one compiled step").set(flops)
    except Exception:
        pass
    snap = metrics.default_registry().snapshot()
    with open(f"{args.telemetry}/metrics.json", "w") as f:
        json.dump(snap, f)
    with open(f"{args.telemetry}/metrics.prom", "w") as f:
        f.write(export.render_prometheus(snap))
    print(f"telemetry written to {args.telemetry} "
          "(spans.jsonl, metrics.json, metrics.prom)", flush=True)


def main():
    args = build_parser().parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from singa_tpu import datasets, device, metric, opt, tensor
    from singa_tpu import models

    if args.telemetry:
        import os

        from singa_tpu.observability import spans as obs_spans
        os.makedirs(args.telemetry, exist_ok=True)
        obs_spans.configure(jsonl_path=f"{args.telemetry}/spans.jsonl")

    dev = device.create_cpu_device() if args.cpu \
        else device.create_tpu_device()
    dev.SetRandSeed(0)
    dev.SetVerbosity(args.verbosity)

    # ---- data -----------------------------------------------------------
    num_classes = 10
    augment = False
    if args.npz:  # npz escape hatch
        blob = np.load(args.npz)
        x, y = blob["x"].astype(np.float32), blob["y"].astype(np.int32)
        n_val = max(1, len(x) // 10)
        train_x, train_y = x[:-n_val], y[:-n_val]
        val_x, val_y = x[-n_val:], y[-n_val:]
        num_classes = int(y.max()) + 1
    elif args.data == "synthetic":
        chans = 1 if args.model in ("cnn", "mlp") else 3
        size = {"cnn": 28, "mlp": 28, "alexnet": 224, "resnet": 224,
                "xceptionnet": 299}[args.model]
        rng = np.random.RandomState(0)
        n = args.bs * args.iters
        train_x = rng.randn(n, chans, size, size).astype(np.float32)
        train_y = rng.randint(0, 10, n).astype(np.int32)
        val_x, val_y = train_x[:args.bs], train_y[:args.bs]
    else:
        train_x, train_y, val_x, val_y = datasets.load(args.data,
                                                       args.data_dir)
        if args.data.startswith("cifar"):
            train_x, val_x = datasets.normalize_cifar(train_x, val_x)
            num_classes = 100 if args.data == "cifar100" else 10
            augment = not args.no_augment
        else:  # mnist
            train_x = np.asarray(train_x, np.float32) / 255.0
            val_x = np.asarray(val_x, np.float32) / 255.0

    chans = train_x.shape[1]

    # ---- model ----------------------------------------------------------
    factory = getattr(models, args.model)
    if args.model == "mlp":
        train_x = train_x.reshape(len(train_x), -1)
        val_x = val_x.reshape(len(val_x), -1)
        model = factory.create_model(data_size=train_x.shape[1],
                                     num_classes=num_classes)
        augment = False
    else:
        kw = {}
        if args.model == "resnet":
            layout = args.layout
            if layout == "auto":
                layout, layout_src = _measured_layout()
                print(f"conv layout: {layout} ({layout_src})", flush=True)
            kw = {"layout": layout, "stem": args.stem}
        model = factory.create_model(num_channels=chans,
                                     num_classes=num_classes, **kw)
    if args.bucket_mb == "auto":
        # same mechanism as --layout auto: the banked hardware A/B
        # winner through bench's measured-choice plumbing
        try:
            import bench
            bucket_mb, bucket_src = bench._grad_bucket_mb()
        except Exception as e:  # noqa: BLE001 — the example must run
            bucket_mb, bucket_src = 0.0, \
                f"unmeasured-fallback ({type(e).__name__})"
        if args.dist:
            print(f"grad bucket: {bucket_mb} MiB ({bucket_src})",
                  flush=True)
    else:
        bucket_mb = float(args.bucket_mb)
    sgd = opt.SGD(lr=args.lr, momentum=0.9, weight_decay=1e-5,
                  fused=args.fused_optim)
    opt_obj = opt.DistOpt(sgd, bucket_mb=bucket_mb,
                          overlap=not args.no_overlap) \
        if args.dist else sgd
    if not args.dist and (bucket_mb or args.no_overlap):
        print("note: --bucket-mb/--no-overlap shape the gradient "
              "collectives and need --dist; ignored on a single "
              "replica", flush=True)
    if args.resilient:
        from singa_tpu.resilience import GuardedOptimizer
        # (Without --resilient, compile(policy="bf16_mixed") wraps a
        # GuardedOptimizer itself — this explicit wrap keeps the
        # trainer's rollback hooks on the same object.)
        if args.precision == "bf16_mixed":
            # same configuration compile() would pick for the policy
            from singa_tpu.mixed_precision import Policy
            opt_obj = GuardedOptimizer.for_policy(opt_obj,
                                                  Policy("bf16_mixed"))
        else:
            # legacy pure-bf16 keeps its underflow shield; f32 runs
            # pure-guard
            opt_obj = GuardedOptimizer(
                opt_obj, init_scale=2.0 ** 15
                if args.precision == "bfloat16" else 1.0)
    model.set_optimizer(opt_obj)

    # Under --dist every process feeds the FULL global batch and the
    # mesh shards it (shard_map splits dim 0; multi-process placement
    # assumes an SPMD-identical host copy) — so unlike the reference's
    # NCCL ranks (train_cnn.py:58-72) the dataset is NOT partitioned
    # per rank here. datasets.partition remains for host-local loaders.
    rank = model.optimizer.global_rank if args.dist else 0

    input_size = getattr(model, "input_size", None)
    need_resize = (getattr(model, "dimension", 4) == 4
                   and input_size is not None
                   and train_x.shape[-1] != input_size)

    def stage(x):
        if need_resize:
            # stays a device array: no host roundtrip before the step
            x = datasets.resize_batch(x, input_size)
        else:
            x = np.ascontiguousarray(x, np.float32)
        t = tensor.Tensor(data=x, device=dev, requires_grad=False)
        if args.precision == "bfloat16":
            # legacy pure-bf16: params follow the input dtype. Under
            # bf16_mixed the input stays f32 — the policy casts at the
            # op boundary inside the compiled step.
            import jax.numpy as jnp
            t = t.as_type(jnp.bfloat16)
        return t

    mesh_obj = None
    if args.mesh or args.fsdp:
        from singa_tpu.parallel import gspmd
        if args.mesh:
            d_, m_ = (int(v) for v in args.mesh.lower().split("x"))
        else:
            import jax
            d_, m_ = len(jax.devices()), 1
        mesh_obj = gspmd.train_mesh(data=d_, model=m_)
        print(f"GSPMD train mesh=data{d_}xmodel{m_}"
              f"{' fsdp=data' if args.fsdp else ''}", flush=True)

    tx = stage(train_x[:args.bs])
    model.compile([tx], is_train=True, use_graph=True,
                  policy="bf16_mixed" if args.precision == "bf16_mixed"
                  else None,
                  mesh=mesh_obj,
                  fsdp_axis="data" if args.fsdp else None)

    eye = np.eye(num_classes, dtype=np.float32)
    acc = metric.Accuracy()
    n_train = len(train_x) // args.bs
    if n_train == 0:
        sys.exit(f"dataset too small: {len(train_x)} train samples "
                 f"(per rank) < batch size {args.bs}")
    if args.max_batches:
        n_train = min(n_train, args.max_batches)
    n_val = len(val_x) // args.bs or 1

    if args.resilient:
        from singa_tpu.data import NumpyBatchIter
        from singa_tpu.resilience import ResilientTrainer

        class StagedBatches:
            """Checkpointable CNN input pipeline: sample selection via
            the stateless-shuffle NumpyBatchIter (its ``{epoch,
            position}`` state rides every --resilient checkpoint, so a
            preempted/rolled-back run resumes the EXACT sample stream),
            augmentation seeded by that state (the resumed stream
            reproduces the exact augmented batches too), device staging
            last."""

            def __init__(self, inner):
                self.inner = inner

            def state_dict(self):
                return self.inner.state_dict()

            def load_state_dict(self, state):
                self.inner.load_state_dict(state)

            def __iter__(self):
                for bx, by in self.inner:
                    if augment:
                        st = self.inner.state_dict()
                        arng = np.random.RandomState(
                            (st["epoch"] * 1_000_003 + st["position"])
                            % (2 ** 31))
                        bx = datasets.augment_crop_flip(bx, rng=arng)
                    yield (stage(bx),
                           tensor.Tensor(data=eye[by], device=dev,
                                         requires_grad=False))

        # --max-batches caps the EPOCH by slicing the sample set, so
        # the deterministic permutation stays over a fixed population
        pipeline = StagedBatches(NumpyBatchIter(
            train_x[:n_train * args.bs], train_y[:n_train * args.bs],
            args.bs, seed=1))
        model.train()
        trainer = ResilientTrainer(model, args.ckpt_dir,
                                   save_interval_steps=args.save_every,
                                   verbose=(rank == 0),
                                   profile_every=args.profile_every,
                                   anomaly_factor=args.anomaly_factor)
        summary = trainer.run(pipeline,
                              num_steps=args.epochs * n_train)
        if rank == 0:
            print(f"resilient run summary: {summary}", flush=True)
        model.eval()
        vaccs = [acc.evaluate(model(stage(val_x[b*args.bs:(b+1)*args.bs])),
                              val_y[b*args.bs:(b+1)*args.bs])
                 for b in range(n_val)]
        if rank == 0:
            print(f"Evaluation accuracy = {np.mean(vaccs):.6f}",
                  flush=True)
        dev.PrintTimeProfiling()
        _dump_telemetry(args, model)
        return

    from singa_tpu.observability import metrics as obs_metrics
    from singa_tpu.observability import spans as obs_spans
    m_step = obs_metrics.default_registry().histogram(
        "train_step_seconds", "wall-clock duration of one step")
    rng = np.random.RandomState(1)
    for epoch in range(args.epochs):
        if rank == 0:
            print(f"Starting Epoch {epoch}:", flush=True)
        idx = rng.permutation(len(train_x))
        t0, losses, accs = time.time(), [], []
        model.train()
        for b in range(n_train):
            sel = idx[b * args.bs:(b + 1) * args.bs]
            bx = train_x[sel]
            if augment:
                bx = datasets.augment_crop_flip(bx, rng=rng)
            tbx = stage(bx)
            tby = tensor.Tensor(data=eye[train_y[sel]], device=dev,
                                requires_grad=False)
            ts = time.perf_counter()
            with obs_spans.span("step", step=epoch * n_train + b):
                if args.dist and args.dist_option != "plain":
                    out, loss = model(tbx, tby, args.dist_option,
                                      args.spars)
                else:
                    out, loss = model(tbx, tby)
            m_step.observe(time.perf_counter() - ts)
            losses.append(float(loss.data))
            accs.append(acc.evaluate(out, train_y[sel]))
        if rank == 0:
            print(f"Training loss = {np.mean(losses):.6f}, "
                  f"training accuracy = {np.mean(accs):.6f}", flush=True)

        model.eval()
        vaccs = []
        for b in range(n_val):
            bx = val_x[b * args.bs:(b + 1) * args.bs]
            by = val_y[b * args.bs:(b + 1) * args.bs]
            out = model(stage(bx))
            vaccs.append(acc.evaluate(out, by))
        if rank == 0:
            print(f"Evaluation accuracy = {np.mean(vaccs):.6f}, "
                  f"Elapsed Time = {time.time() - t0:.3f}s", flush=True)

    dev.PrintTimeProfiling()
    _dump_telemetry(args, model)


if __name__ == "__main__":
    main()
