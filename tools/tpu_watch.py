"""Round-long TPU tunnel watcher.

The tunnel to the real chip has been observed down for entire rounds and
flaky within rounds. This loop probes liveness on a low duty cycle and —
the moment a window opens — immediately banks layered evidence using
bench.py's smoke and full-benchmark children, appending every observation
to ``tpu_observations.jsonl``. The end-of-round ``python bench.py`` folds
that file into its one-line JSON, so a transient tunnel-up window earlier
in the round still produces a reported hardware number.

Run detached:  nohup python tools/tpu_watch.py > tpu_watch.log 2>&1 &
Stop early:    touch tpu_watch.stop   (checked once per cycle)
"""

import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench  # noqa: E402  (stdlib-only at import time)

MAX_HOURS = float(os.environ.get("TPU_WATCH_HOURS", "11.5"))
IDLE_SLEEP = 8 * 60       # between probes while the tunnel is down
BANKED_SLEEP = 45 * 60    # once a full benchmark is banked, just refresh
STOP_FILE = os.path.join(ROOT, "tpu_watch.stop")


def log(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def _complete_bench(o):
    """True only for a COMPLETE honest benchmark: a salvaged partial
    (fp32 leg only) must keep the watcher on the fast probe cadence so
    the missing legs still get measured in the next window."""
    return (o.get("event") == "bench"
            and o.get("platform") not in (None, "cpu")
            and o.get("timing") == "slope-readback"
            and bench._is_complete(o))


# per-leg SUCCESS markers in the banked observations (error records use
# different names on purpose, so a failed leg is retried). The single
# source lives in bench.EXTRA_SUCCESS_MARKERS so the report's
# extras-folding and this retry logic can never diverge; its dict order
# is the information-value order the probe child runs legs in —
# never-banked diagnostics first (fusion profile explains the MFU gap,
# layout A/B steers the full benchmark), re-confirmations last.
_EXTRA_LEG_MARKERS = bench.EXTRA_SUCCESS_MARKERS

# run BEFORE the full benchmark in a fresh window (their results steer it)
PRIORITY_LEGS = ("resnet_fusion_profile", "resnet_layout_ab")


def _extras_missing():
    """Extra-probe legs with any success marker not yet banked this
    round — already-banked heavy legs are never re-run on a retry (a
    multi-marker leg like hbm_footprint retries until EVERY marker is
    banked; the probe skips its already-banked children)."""
    obs = [o for o in bench._load_obs() if o.get("event") == "extra"]
    seen = {str(o.get("extra", "")) for o in obs}
    missing = [leg for leg, markers in _EXTRA_LEG_MARKERS.items()
               if any(m not in seen for m in markers)]
    # the sweep banks each config's record as it completes; enough of
    # them IS the measurement even if the child died before printing
    # the final flash_block_best summary — don't redo the whole sweep
    if "flash_block_sweep" in missing:
        cfgs = {(o.get("block_q"), o.get("block_k")) for o in obs
                if o.get("extra") == "flash_block_probe"
                and o.get("ms") is not None}
        if len(cfgs) >= 3:
            missing.remove("flash_block_sweep")
    return missing


def _n_banked_successes():
    """Banked extra records that represent real measurements — the
    device marker and per-leg error records don't count as work."""
    return sum(1 for o in bench._load_obs()
               if o.get("event") == "extra"
               and o.get("extra") not in (None, "device")
               and o.get("error") is None)


def _run_extras(legs, timeout=1500):
    """One bounded child of tools/tpu_probe_extra.py, restricted to the
    still-missing legs (it takes the TPU lock itself — call AFTER
    releasing ours). Returns the number of records the child banked —
    0 means it provably did no work (lock busy / tunnel already gone)."""
    import subprocess
    script = os.path.join(ROOT, "tools", "tpu_probe_extra.py")
    env = dict(os.environ, TPU_EXTRA_LEGS=",".join(legs))
    before = _n_banked_successes()
    try:
        proc = subprocess.run([sys.executable, script],
                              capture_output=True, text=True,
                              timeout=timeout, env=env)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        rc = "timeout"
    banked_new = _n_banked_successes() - before
    log(f"extras({','.join(legs)}): {banked_new} new measurements "
        f"(rc={rc})")
    return banked_new


def main():
    deadline = time.time() + MAX_HOURS * 3600
    banked = False
    extras_tries = 0      # attempts that actually banked something
    extras_calls = 0      # hard cap on child launches per round
    n = 0
    # round boundary: bench.py only trusts observations after this
    # marker. A RESTART mid-round keeps the existing window (and its
    # banked evidence) instead of discarding it.
    last_heavy = 0.0
    if bench._record_round_start(MAX_HOURS):
        log("opened a new round window")
    else:
        log("recent round window found; resuming it")
        complete = [o for o in bench._load_obs() if _complete_bench(o)]
        banked = bool(complete)
        if complete:
            last_heavy = time.time() - bench._obs_age_s(complete[-1])
    log(f"watching for TPU windows (max {MAX_HOURS}h, "
        f"idle interval {IDLE_SLEEP}s)")
    while time.time() < deadline:
        if os.path.exists(STOP_FILE):
            log("stop file present; exiting")
            return
        n += 1
        # try-lock: if a live `python bench.py` holds the chip, just
        # skip this cycle — interfering would corrupt its measurement
        with bench._TpuLock(wait_s=0) as lock:
            if not lock.acquired:
                log(f"cycle#{n}: bench.py holds the tpu lock; skipping")
                time.sleep(IDLE_SLEEP)
                continue
            # dead-tunnel fast-fail (BENCH_r05: 73 consecutive probe
            # timeouts burned the round at full probe cost): once the
            # streak trips the cooldown, probe SHORT and SLOW — still
            # probing, so a tunnel that revives breaks the streak and
            # restores full cadence, but a dead one costs 30s per
            # half-hour instead of 120s per 8 minutes. Every 4th
            # cooldown cycle keeps the FULL budget: a revived backend
            # whose cold start exceeds 30s must still be recoverable
            # without human intervention (BENCH_FORCE_PROBE).
            cooldown = bench._probe_cooldown()
            full_probe = not cooldown or n % 4 == 0
            if cooldown:
                log(f"cycle#{n}: probe cooldown active "
                    f"({cooldown} consecutive timeouts) — "
                    f"{'full' if full_probe else 'short'} probe, "
                    "slow cadence")
            status, err = bench._probe_tpu(120 if full_probe else 30)
            bench._record_obs("probe", {
                "status": status, "err": err, "src": "watch",
                # bench._probe_timeout_kind classifies the round's
                # timeout streak from this stamp (warm cache => the
                # round's full attempts can't be compile-bound)
                "compile_cache": bench._compile_cache_state()})
            log(f"probe#{n}: {status}{' (' + err + ')' if err else ''}")
        if status != "ok":
            time.sleep(IDLE_SLEEP * (4 if cooldown else 1))
            continue
        # probes are cheap (one 120s child) — keep the fast cadence
        # even after a complete bench is banked, or short windows go
        # unseen. Only the EXPENSIVE heavy sequence is throttled to
        # once per BANKED_SLEEP after a complete bank — gated on when
        # the heavy work last RAN (not last succeeded), so a failed
        # refresh doesn't put the expensive path on every probe.
        if not banked or time.time() - last_heavy >= BANKED_SLEEP:
            ran_heavy = False   # heavy work actually attempted this cycle
            # 1. cheap layered evidence first: a window that dies in
            #    3 minutes still leaves device + matmul-peak + flash
            #    records behind
            with bench._TpuLock(wait_s=60) as lock:
                if lock.acquired:
                    ran_heavy = True
                    smoke = bench._attempt_smoke(300)
                    for rec in smoke:
                        bench._record_obs("smoke", rec)
                    log(f"smoke: {len(smoke)} sub-results banked")
                else:
                    log(f"cycle#{n}: smoke skipped (tpu lock busy)")
            # 2. the never-banked diagnostics BEFORE the known bench
            #    (VERDICT r4 #1): the fusion profile explains the MFU
            #    gap; the layout A/B's banked winner steers the conv
            #    layout of the full benchmark that follows
            if extras_calls < 10:
                pri = [leg for leg in PRIORITY_LEGS
                       if leg in _extras_missing()]
                if pri:
                    extras_calls += 1
                    log(f"window live: PRIORITY diagnostics first {pri}")
                    # generous budget: the layout A/B's NHWC variant is
                    # a cold compile the cache has never seen
                    if _run_extras(pri, timeout=2100) > 0:
                        extras_tries += 1
            # 3. the scored 4-leg benchmark (fp32/bf16/lm/lm_bf16 —
            #    banks lm_mfu and lm_bf16_mfu)
            with bench._TpuLock(wait_s=60) as lock:
                if lock.acquired:
                    ran_heavy = True
                    res, aerr = bench._attempt("tpu", 1500)
                    if res is not None:
                        bench._record_obs("bench", res)
                        thr = res.get("throughput")
                        log(f"BENCH BANKED: {thr} img/s on "
                            f"{res.get('device_kind')} "
                            f"(layout={res.get('conv_layout')}, "
                            f"partial={bool(res.get('partial_timeout') or res.get('partial_crash') or res.get('partial'))})")
                        if _complete_bench(dict(res, event="bench",
                                                platform=res.get("platform"))):
                            banked = True
                    else:
                        log(f"full bench attempt failed: {aerr}")
                else:
                    log(f"cycle#{n}: bench re-run skipped (tpu lock busy)")
            # the refresh throttle starts only when heavy work actually
            # RAN — a busy lock must not silence the re-attempt for a
            # whole BANKED_SLEEP
            if banked and ran_heavy:
                last_heavy = time.time()
        else:
            log(f"cycle#{n}: window live, bench recently banked — "
                f"next re-run in "
                f"{int(BANKED_SLEEP - (time.time() - last_heavy))}s")
        # 4. window still live: spend it on the remaining extra
        # measurements, retrying ONLY the legs whose success marker
        # isn't banked yet (outside our lock — the child serializes
        # itself). A try only counts when the child banked something —
        # a no-work exit (lock busy, tunnel already gone) must not burn
        # the budget; extras_calls hard-caps the loop.
        if extras_tries < 5 and extras_calls < 10:
            missing = _extras_missing()
            if missing:
                extras_calls += 1
                log(f"window live: extras run for {missing} "
                    f"(productive tries so far: {extras_tries}/5)")
                if _run_extras(missing) > 0:
                    extras_tries += 1
        time.sleep(IDLE_SLEEP)
    log("watch window closed")


if __name__ == "__main__":
    main()
