"""Generate the hermetic ONNX node-conformance fixtures.

The official ONNX backend node suite (what the reference runs via
test/python/test_onnx_backend.py) ships inside the `onnx` wheel, which
this environment does not have. This script freezes an equivalent
subset — single-node ModelProtos plus input/output TensorProtos in the
official on-disk layout (model.onnx + test_data_set_0/{input,output}_N
.pb) — built from the ONNX operator-spec semantics implemented in plain
numpy, serialized with the vendored wire-compatible protos
(singa_tpu/onnx_proto). The committed fixtures make
tests/test_onnx_nodes.py a conformance suite that runs with zero
optional dependencies; tests/test_onnx_backend.py still runs the real
upstream suite whenever the onnx wheel is importable.

Regenerate (deterministic, seed-pinned):
    python tools/gen_onnx_node_fixtures.py
"""

import os
import shutil
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from singa_tpu.onnx_compat import TensorProto, helper, numpy_helper  # noqa

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data", "onnx_nodes")

F = TensorProto.FLOAT


def _vi(name, arr):
    dt = helper.np_dtype_to_tensor_dtype(np.asarray(arr).dtype)
    return helper.make_tensor_value_info(name, dt, list(np.shape(arr)))


def case(name, op_type, inputs, outputs, attrs=None, opset=11):
    """inputs/outputs: list of (name, ndarray). Returns (name, model,
    input arrays, output arrays)."""
    node = helper.make_node(op_type, [n for n, _ in inputs],
                            [n for n, _ in outputs], **(attrs or {}))
    graph = helper.make_graph(
        [node], name,
        [_vi(n, a) for n, a in inputs],
        [_vi(n, a) for n, a in outputs])
    model = helper.make_model(
        graph, opset_imports=[helper.make_operatorsetid("", opset)])
    return (name, model, [a for _, a in inputs], [a for _, a in outputs])


# ---------------------------------------------------------------------------
# numpy reference implementations of the ONNX operator spec
# ---------------------------------------------------------------------------

def ref_softmax(x, axis):
    # opset-11 semantics: coerce to 2D at `axis`, softmax the rows
    shape = x.shape
    flat = x.reshape(int(np.prod(shape[:axis])) if axis > 0 else 1, -1)
    e = np.exp(flat - flat.max(axis=1, keepdims=True))
    return (e / e.sum(axis=1, keepdims=True)).reshape(shape)


def ref_conv2d_general(x, w, strides=(1, 1), pads=(0, 0, 0, 0),
                       dilations=(1, 1), group=1):
    """ONNX Conv reference with dilation and groups."""
    N, C, H, W = x.shape
    M, Cg, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                    (pads[1], pads[3])))
    ekh = (kh - 1) * dilations[0] + 1
    ekw = (kw - 1) * dilations[1] + 1
    oh = (xp.shape[2] - ekh) // strides[0] + 1
    ow = (xp.shape[3] - ekw) // strides[1] + 1
    out = np.zeros((N, M, oh, ow), np.float32)
    mg = M // group
    for n in range(N):
        for m in range(M):
            g = m // mg
            for i in range(oh):
                for j in range(ow):
                    patch = xp[n, g * Cg:(g + 1) * Cg,
                               i * strides[0]:i * strides[0] + ekh:
                               dilations[0],
                               j * strides[1]:j * strides[1] + ekw:
                               dilations[1]]
                    out[n, m, i, j] = np.sum(patch * w[m])
    return out


def ref_conv2d(x, w, strides=(1, 1), pads=(0, 0, 0, 0)):
    return ref_conv2d_general(x, w, strides, pads)


def ref_pool2d(x, k, strides, is_max):
    N, C, H, W = x.shape
    oh = (H - k[0]) // strides[0] + 1
    ow = (W - k[1]) // strides[1] + 1
    out = np.zeros((N, C, oh, ow), np.float32)
    red = np.max if is_max else np.mean
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = red(
                x[:, :, i * strides[0]:i * strides[0] + k[0],
                  j * strides[1]:j * strides[1] + k[1]], axis=(2, 3))
    return out


def ref_gemm(a, b, c=None, alpha=1.0, beta=1.0, transA=0, transB=0):
    aa = a.T if transA else a
    bb = b.T if transB else b
    y = alpha * (aa @ bb)
    if c is not None:
        y = y + beta * c
    return y.astype(np.float32)


def ref_batchnorm(x, s, bias, mean, var, eps=1e-5):
    shp = (1, -1, 1, 1)
    return ((x - mean.reshape(shp)) / np.sqrt(var.reshape(shp) + eps)
            * s.reshape(shp) + bias.reshape(shp)).astype(np.float32)


def ref_conv_transpose2d(x, w, strides=(1, 1)):
    """ONNX ConvTranspose, no pads/dilation; w is (C, M, kh, kw)."""
    N, C, H, W = x.shape
    _, M, kh, kw = w.shape
    oh = (H - 1) * strides[0] + kh
    ow = (W - 1) * strides[1] + kw
    out = np.zeros((N, M, oh, ow), np.float32)
    for n in range(N):
        for c in range(C):
            for i in range(H):
                for j in range(W):
                    out[n, :, i * strides[0]:i * strides[0] + kh,
                        j * strides[1]:j * strides[1] + kw] += \
                        x[n, c, i, j] * w[c]
    return out


def ref_lrn(x, size, alpha, beta, bias):
    C = x.shape[1]
    half_lo = (size - 1) // 2
    half_hi = size // 2
    sq = np.zeros_like(x)
    for c in range(C):
        lo, hi = max(0, c - half_lo), min(C - 1, c + half_hi)
        sq[:, c] = (x[:, lo:hi + 1] ** 2).sum(axis=1)
    return (x / (bias + (alpha / size) * sq) ** beta).astype(np.float32)


def ref_depth_to_space(x, bs):
    b, c, h, w = x.shape
    t = x.reshape(b, bs, bs, c // (bs * bs), h, w)
    return t.transpose(0, 3, 4, 1, 5, 2).reshape(
        b, c // (bs * bs), h * bs, w * bs).copy()


def ref_space_to_depth(x, bs):
    b, c, h, w = x.shape
    t = x.reshape(b, c, h // bs, bs, w // bs, bs)
    return t.transpose(0, 3, 5, 1, 2, 4).reshape(
        b, c * bs * bs, h // bs, w // bs).copy()


def ref_scatter_elements(data, indices, updates, axis):
    out = data.copy()
    for idx in np.ndindex(*indices.shape):
        tgt = list(idx)
        tgt[axis] = indices[idx]
        out[tuple(tgt)] = updates[idx]
    return out


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def ref_rnn_bidir(X, W, R, B, H):
    """ONNX bidirectional RNN: dir 0 forward, dir 1 runs on the
    time-reversed input and its outputs are stored back at original
    positions. Returns Y (T,2,Bz,H), Y_h (2,Bz,H)."""
    yf, hf = ref_rnn(X, W[0:1], R[0:1], B[0:1], H)
    yr, hr = ref_rnn(X[::-1], W[1:2], R[1:2], B[1:2], H)
    T, Bz = X.shape[0], X.shape[1]
    Y = np.zeros((T, 2, Bz, H), np.float32)
    Y[:, 0] = yf[:, 0]
    Y[:, 1] = yr[::-1, 0]
    return Y, np.concatenate([hf, hr], 0)


def ref_rnn(X, W, R, B, H):
    """ONNX RNN (tanh, forward). X (T,Bz,I); W (1,H,I); R (1,H,H);
    B (1,2H). Returns Y (T,1,Bz,H), Y_h (1,Bz,H)."""
    T, Bz, _ = X.shape
    Wb, Rb = B[0, :H], B[0, H:]
    h = np.zeros((Bz, H), np.float32)
    Y = np.zeros((T, 1, Bz, H), np.float32)
    for t in range(T):
        h = np.tanh(X[t] @ W[0].T + h @ R[0].T + Wb + Rb)
        Y[t, 0] = h
    return Y.astype(np.float32), h[None].astype(np.float32)


def ref_gru(X, W, R, B, H):
    """ONNX GRU (forward, linear_before_reset=0, zrh gate order)."""
    T, Bz, _ = X.shape
    Wz, Wr, Wh = W[0, :H], W[0, H:2 * H], W[0, 2 * H:]
    Rz, Rr, Rh = R[0, :H], R[0, H:2 * H], R[0, 2 * H:]
    Wbz, Wbr, Wbh = B[0, :H], B[0, H:2 * H], B[0, 2 * H:3 * H]
    Rbz, Rbr, Rbh = (B[0, 3 * H:4 * H], B[0, 4 * H:5 * H],
                     B[0, 5 * H:6 * H])
    h = np.zeros((Bz, H), np.float32)
    Y = np.zeros((T, 1, Bz, H), np.float32)
    for t in range(T):
        z = _sig(X[t] @ Wz.T + h @ Rz.T + Wbz + Rbz)
        r = _sig(X[t] @ Wr.T + h @ Rr.T + Wbr + Rbr)
        htil = np.tanh(X[t] @ Wh.T + (r * h) @ Rh.T + Wbh + Rbh)
        h = (1 - z) * htil + z * h
        Y[t, 0] = h
    return Y.astype(np.float32), h[None].astype(np.float32)


def ref_lstm(X, W, R, B, H):
    """ONNX LSTM (forward, iofc gate order, no peepholes)."""
    T, Bz, _ = X.shape
    Wi, Wo, Wf, Wc = (W[0, :H], W[0, H:2 * H], W[0, 2 * H:3 * H],
                      W[0, 3 * H:])
    Ri, Ro, Rf, Rc = (R[0, :H], R[0, H:2 * H], R[0, 2 * H:3 * H],
                      R[0, 3 * H:])
    bi = B[0, 0 * H:1 * H] + B[0, 4 * H:5 * H]
    bo = B[0, 1 * H:2 * H] + B[0, 5 * H:6 * H]
    bf = B[0, 2 * H:3 * H] + B[0, 6 * H:7 * H]
    bc = B[0, 3 * H:4 * H] + B[0, 7 * H:8 * H]
    h = np.zeros((Bz, H), np.float32)
    c = np.zeros((Bz, H), np.float32)
    Y = np.zeros((T, 1, Bz, H), np.float32)
    for t in range(T):
        i = _sig(X[t] @ Wi.T + h @ Ri.T + bi)
        o = _sig(X[t] @ Wo.T + h @ Ro.T + bo)
        f = _sig(X[t] @ Wf.T + h @ Rf.T + bf)
        ct = np.tanh(X[t] @ Wc.T + h @ Rc.T + bc)
        c = f * c + i * ct
        h = o * np.tanh(c)
        Y[t, 0] = h
    return (Y.astype(np.float32), h[None].astype(np.float32),
            c[None].astype(np.float32))


def build_cases():
    rng = np.random.RandomState(0)

    def r(*shape):
        return rng.randn(*shape).astype(np.float32)

    cases = []

    # -- simple activations / unary ------------------------------------
    x = r(3, 4, 5)
    xpos = np.abs(r(3, 4, 5)) + 0.1
    for name, op, inp, out in [
        ("test_relu", "Relu", x, np.maximum(x, 0)),
        ("test_sigmoid", "Sigmoid", x, 1 / (1 + np.exp(-x))),
        ("test_tanh", "Tanh", x, np.tanh(x)),
        ("test_softplus", "Softplus", x, np.log1p(np.exp(x))),
        ("test_neg", "Neg", x, -x),
        ("test_abs", "Abs", x, np.abs(x)),
        ("test_exp", "Exp", x, np.exp(x)),
        ("test_log", "Log", xpos, np.log(xpos)),
        ("test_sqrt", "Sqrt", xpos, np.sqrt(xpos)),
        ("test_ceil", "Ceil", x, np.ceil(x)),
        ("test_floor", "Floor", x, np.floor(x)),
        ("test_reciprocal", "Reciprocal", xpos, 1.0 / xpos),
        ("test_sign", "Sign", x, np.sign(x)),
        ("test_erf", "Erf", x, np.vectorize(__import__("math").erf)(x)
         .astype(np.float32)),
    ]:
        cases.append(case(name, op, [("x", inp)],
                          [("y", out.astype(np.float32))]))

    cases.append(case("test_elu", "Elu", [("x", x)],
                      [("y", np.where(x > 0, x, 2.0 * (np.exp(x) - 1))
                        .astype(np.float32))], {"alpha": 2.0}))
    cases.append(case("test_leakyrelu", "LeakyRelu", [("x", x)],
                      [("y", np.where(x > 0, x, 0.1 * x)
                        .astype(np.float32))], {"alpha": 0.1}))
    a_selu, g_selu = 1.6732632, 1.0507009
    cases.append(case(
        "test_selu_default", "Selu", [("x", x)],
        [("y", (g_selu * np.where(x > 0, x, a_selu * (np.exp(x) - 1)))
          .astype(np.float32))]))

    # -- binary elementwise (with broadcasting rows) --------------------
    a, b = r(3, 4, 5), r(3, 4, 5)
    bc = r(5)                                   # numpy-style broadcast
    bpos = np.abs(r(3, 4, 5)) + 0.5
    for name, op, (i1, i2), out in [
        ("test_add", "Add", (a, b), a + b),
        ("test_add_bcast", "Add", (a, bc), a + bc),
        ("test_sub", "Sub", (a, b), a - b),
        ("test_mul", "Mul", (a, b), a * b),
        ("test_div", "Div", (a, bpos), a / bpos),
        ("test_pow", "Pow", (np.abs(a) + 0.1, b), (np.abs(a) + 0.1) ** b),
    ]:
        cases.append(case(name, op, [("a", i1), ("b", i2)],
                          [("y", out.astype(np.float32))]))

    # -- matmul / gemm --------------------------------------------------
    m2a, m2b = r(4, 6), r(6, 3)
    cases.append(case("test_matmul_2d", "MatMul",
                      [("a", m2a), ("b", m2b)], [("y", m2a @ m2b)]))
    m3a, m3b = r(2, 4, 6), r(2, 6, 3)
    cases.append(case("test_matmul_3d", "MatMul",
                      [("a", m3a), ("b", m3b)],
                      [("y", (m3a @ m3b).astype(np.float32))]))
    ga, gb, gc = r(3, 5), r(5, 4), r(3, 4)
    gat, gbt = r(5, 3), r(4, 5)
    cases.append(case("test_gemm_all_attributes", "Gemm",
                      [("a", gat), ("b", gbt), ("c", gc)],
                      [("y", ref_gemm(gat, gbt, gc, 0.25, 0.35, 1, 1))],
                      {"alpha": 0.25, "beta": 0.35,
                       "transA": 1, "transB": 1}))
    cases.append(case("test_gemm_default", "Gemm",
                      [("a", ga), ("b", gb), ("c", gc)],
                      [("y", ref_gemm(ga, gb, gc))]))

    # -- softmax --------------------------------------------------------
    sm = r(3, 7)
    cases.append(case("test_softmax_axis_1", "Softmax", [("x", sm)],
                      [("y", ref_softmax(sm, 1))], {"axis": 1}))
    cases.append(case("test_softmax_default_axis", "Softmax",
                      [("x", sm)], [("y", ref_softmax(sm, 1))]))

    # -- shape ops ------------------------------------------------------
    c1, c2 = r(2, 3), r(2, 3)
    cases.append(case("test_concat_2d_axis_0", "Concat",
                      [("a", c1), ("b", c2)],
                      [("y", np.concatenate([c1, c2], 0))], {"axis": 0}))
    cases.append(case("test_concat_2d_axis_1", "Concat",
                      [("a", c1), ("b", c2)],
                      [("y", np.concatenate([c1, c2], 1))], {"axis": 1}))
    fl = r(2, 3, 4)
    cases.append(case("test_flatten_axis1", "Flatten", [("x", fl)],
                      [("y", fl.reshape(2, 12))], {"axis": 1}))
    tr = r(2, 3, 4)
    cases.append(case("test_transpose_default", "Transpose", [("x", tr)],
                      [("y", tr.transpose(2, 1, 0).copy())]))
    rs = r(2, 3, 4)
    tgt = np.array([4, 2, 3], np.int64)
    cases.append(case("test_reshape_reordered_all_dims", "Reshape",
                      [("x", rs), ("shape", tgt)],
                      [("y", rs.reshape(4, 2, 3))]))
    sq = r(1, 3, 4, 1)
    cases.append(case("test_squeeze", "Squeeze", [("x", sq)],
                      [("y", sq.reshape(3, 4))], {"axes": [0, 3]}))
    us = r(3, 4)
    cases.append(case("test_unsqueeze_axis_0", "Unsqueeze", [("x", us)],
                      [("y", us.reshape(1, 3, 4))], {"axes": [0]}))
    gt = r(5, 4)
    gi0 = np.array([0, 1, 3], np.int64)
    cases.append(case("test_gather_0", "Gather",
                      [("x", gt), ("i", gi0)],
                      [("y", np.take(gt, gi0, 0))], {"axis": 0}))
    cases.append(case("test_gather_1", "Gather",
                      [("x", gt), ("i", np.array([0, 2], np.int64))],
                      [("y", np.take(gt, [0, 2], 1))], {"axis": 1}))

    # -- reductions / clip ---------------------------------------------
    rd = r(3, 2, 2)
    cases.append(case(
        "test_reduce_mean_default_axes_keepdims_example", "ReduceMean",
        [("x", rd)], [("y", rd.mean(keepdims=True).astype(np.float32)
                       .reshape(1, 1, 1))]))
    cases.append(case(
        "test_reduce_sum_default_axes_keepdims_example", "ReduceSum",
        [("x", rd)], [("y", rd.sum(keepdims=True).astype(np.float32)
                       .reshape(1, 1, 1))]))
    cl = np.array([-2.0, -1.0, 0.0, 1.0, 2.0], np.float32)
    cases.append(case("test_clip_example", "Clip",
                      [("x", cl), ("min", np.float32(-1.0)),
                       ("max", np.float32(1.0))],
                      [("y", np.clip(cl, -1, 1))]))

    # -- conv / pool / bn ----------------------------------------------
    cx, cw = r(1, 1, 7, 5), r(1, 1, 3, 3)
    cases.append(case(
        "test_conv_with_strides_no_padding", "Conv",
        [("x", cx), ("w", cw)],
        [("y", ref_conv2d(cx, cw, (2, 2)))],
        {"kernel_shape": [3, 3], "strides": [2, 2], "pads": [0, 0, 0, 0]}))
    cases.append(case(
        "test_conv_with_strides_padding", "Conv",
        [("x", cx), ("w", cw)],
        [("y", ref_conv2d(cx, cw, (2, 2), (1, 1, 1, 1)))],
        {"kernel_shape": [3, 3], "strides": [2, 2], "pads": [1, 1, 1, 1]}))
    px = r(1, 3, 8, 8)
    cases.append(case(
        "test_maxpool_2d_default", "MaxPool", [("x", px)],
        [("y", ref_pool2d(px, (2, 2), (1, 1), True))],
        {"kernel_shape": [2, 2]}))
    cases.append(case(
        "test_averagepool_2d_strides", "AveragePool", [("x", px)],
        [("y", ref_pool2d(px, (3, 3), (2, 2), False))],
        {"kernel_shape": [3, 3], "strides": [2, 2]}))
    cases.append(case(
        "test_globalaveragepool", "GlobalAveragePool", [("x", px)],
        [("y", px.mean(axis=(2, 3), keepdims=True).astype(np.float32))]))
    bx = r(2, 3, 4, 4)
    bs, bb = np.abs(r(3)) + 0.5, r(3)
    bm, bv = r(3), np.abs(r(3)) + 0.5
    cases.append(case(
        "test_batchnorm_epsilon", "BatchNormalization",
        [("x", bx), ("s", bs), ("bias", bb), ("mean", bm), ("var", bv)],
        [("y", ref_batchnorm(bx, bs, bb, bm, bv, 1e-2))],
        {"epsilon": 1e-2}))
    cases.append(case(
        "test_batchnorm_example", "BatchNormalization",
        [("x", bx), ("s", bs), ("bias", bb), ("mean", bm), ("var", bv)],
        [("y", ref_batchnorm(bx, bs, bb, bm, bv))]))

    # -- trig / inverse-trig / hyperbolic -------------------------------
    # |x| < 1 for asin/acos/atanh (uniform draw — randn is unbounded)
    xu = (rng.rand(3, 4) * 1.8 - 0.9).astype(np.float32)
    xg1 = np.abs(r(3, 4)) + 1.1             # x > 1 for acosh
    for name, op, inp, fn in [
        ("test_cos", "Cos", x, np.cos), ("test_sin", "Sin", x, np.sin),
        ("test_tan", "Tan", xu, np.tan),
        ("test_cosh", "Cosh", x, np.cosh),
        ("test_sinh", "Sinh", x, np.sinh),
        ("test_acos", "Acos", xu, np.arccos),
        ("test_asin", "Asin", xu, np.arcsin),
        ("test_atan", "Atan", x, np.arctan),
        ("test_acosh", "Acosh", xg1, np.arccosh),
        ("test_asinh", "Asinh", x, np.arcsinh),
        ("test_atanh", "Atanh", xu, np.arctanh),
        ("test_softsign", "Softsign", x, lambda v: v / (1 + np.abs(v))),
    ]:
        cases.append(case(name, op, [("x", inp)],
                          [("y", fn(inp).astype(np.float32))]))
    cases.append(case(
        "test_hardsigmoid", "HardSigmoid", [("x", x)],
        [("y", np.clip(0.5 * x + 0.6, 0, 1).astype(np.float32))],
        {"alpha": 0.5, "beta": 0.6}))
    cases.append(case("test_identity", "Identity", [("x", x)],
                      [("y", x)]))
    pr_s = np.abs(r(5)).astype(np.float32)
    cases.append(case(
        "test_prelu_broadcast", "PRelu", [("x", x), ("slope", pr_s)],
        [("y", np.where(x > 0, x, pr_s * x).astype(np.float32))]))

    # -- logical / comparison ------------------------------------------
    ba = rng.rand(3, 4) > 0.5
    bb = rng.rand(3, 4) > 0.5
    for name, op, fn in [("test_and2d", "And", np.logical_and),
                         ("test_or2d", "Or", np.logical_or),
                         ("test_xor2d", "Xor", np.logical_xor)]:
        cases.append(case(name, op, [("a", ba), ("b", bb)],
                          [("y", fn(ba, bb))]))
    cases.append(case("test_not_2d", "Not", [("x", ba)],
                      [("y", np.logical_not(ba))]))
    ia = np.round(r(3, 4) * 2).astype(np.float32)
    ib = np.round(r(3, 4) * 2).astype(np.float32)
    cases.append(case("test_equal", "Equal", [("a", ia), ("b", ib)],
                      [("y", ia == ib)]))
    cases.append(case("test_greater", "Greater", [("a", a), ("b", b)],
                      [("y", a > b)]))
    cases.append(case("test_less", "Less", [("a", a), ("b", b)],
                      [("y", a < b)]))

    # -- variadic math --------------------------------------------------
    v1, v2, v3 = r(3, 4), r(3, 4), r(3, 4)
    for name, op, out in [
        ("test_max_example", "Max", np.maximum(np.maximum(v1, v2), v3)),
        ("test_min_example", "Min", np.minimum(np.minimum(v1, v2), v3)),
        ("test_sum_example", "Sum", v1 + v2 + v3),
        ("test_mean_example", "Mean", (v1 + v2 + v3) / 3.0),
    ]:
        cases.append(case(name, op,
                          [("a", v1), ("b", v2), ("c", v3)],
                          [("y", out.astype(np.float32))]))

    # -- tensor introspection / selection ------------------------------
    cases.append(case("test_shape", "Shape", [("x", r(3, 4, 5))],
                      [("y", np.array([3, 4, 5], np.int64))]))
    wc = r(3, 4) > 0
    wa, wb = r(3, 4), r(3, 4)
    cases.append(case("test_where_example", "Where",
                      [("c", wc), ("a", wa), ("b", wb)],
                      [("y", np.where(wc, wa, wb).astype(np.float32))]))
    nz = np.array([[1.0, 0.0, 2.0], [0.0, 3.0, 0.0]], np.float32)
    cases.append(case("test_nonzero_example", "NonZero", [("x", nz)],
                      [("y", np.array(np.nonzero(nz), np.int64))]))
    cst = r(2, 3) * 3
    cases.append(case("test_cast_float_to_int32", "Cast", [("x", cst)],
                      [("y", cst.astype(np.int32))],
                      {"to": int(TensorProto.INT32)}))
    cval = r(2, 3)
    cases.append(case(
        "test_constant", "Constant", [],
        [("y", cval)],
        {"value": numpy_helper.from_array(cval, "const_value")}))
    cases.append(case(
        "test_constantofshape_float_ones", "ConstantOfShape",
        [("shape", np.array([3, 2], np.int64))],
        [("y", np.full((3, 2), 1.0, np.float32))],
        {"value": helper.make_tensor("value", TensorProto.FLOAT, [1],
                                     [1.0])}))
    oh_idx = np.array([0, 2, 1, 1], np.int64)
    oh_out = np.full((4, 3), 0.5, np.float32)
    oh_out[np.arange(4), oh_idx] = 2.0
    cases.append(case(
        "test_onehot_with_axis", "OneHot",
        [("idx", oh_idx), ("depth", np.array([3], np.int64)),
         ("values", np.array([0.5, 2.0], np.float32))],
        [("y", oh_out)], {"axis": -1}))

    # -- shape manipulation (attribute-as-input ops) --------------------
    sl = r(5, 6)
    cases.append(case(
        "test_slice_with_steps", "Slice",
        [("x", sl), ("starts", np.array([1, 0], np.int64)),
         ("ends", np.array([4, 5], np.int64)),
         ("axes", np.array([0, 1], np.int64)),
         ("steps", np.array([2, 2], np.int64))],
        [("y", sl[1:4:2, 0:5:2].copy())]))
    sp = r(2, 6)
    cases.append(case(
        "test_split_variable_parts_1d", "Split", [("x", sp)],
        [("y0", sp[:, :2].copy()), ("y1", sp[:, 2:].copy())],
        {"axis": 1, "split": [2, 4]}))
    ex = r(3, 1)
    cases.append(case(
        "test_expand_dim_changed", "Expand",
        [("x", ex), ("shape", np.array([2, 3, 4], np.int64))],
        [("y", np.broadcast_to(ex, (2, 3, 4)).astype(np.float32)
          .copy())]))
    tl = r(2, 3)
    cases.append(case(
        "test_tile", "Tile",
        [("x", tl), ("repeats", np.array([2, 2], np.int64))],
        [("y", np.tile(tl, (2, 2)))]))
    pd = r(2, 3)
    cases.append(case(
        "test_pad_constant", "Pad",
        [("x", pd), ("pads", np.array([0, 1, 0, 2], np.int64)),
         ("cval", np.float32(0.5))],
        [("y", np.pad(pd, ((0, 0), (1, 2)), constant_values=0.5))],
        {"mode": "constant"}))
    up = r(1, 1, 2, 2)
    cases.append(case(
        "test_upsample_nearest", "Upsample",
        [("x", up), ("scales", np.array([1, 1, 2, 3], np.float32))],
        [("y", up.repeat(2, axis=2).repeat(3, axis=3))], opset=9))
    rz = r(1, 1, 2, 2)
    cases.append(case(
        "test_resize_upsample_scales_nearest", "Resize",
        [("x", rz), ("roi", np.array([], np.float32)),
         ("scales", np.array([1, 1, 2, 2], np.float32))],
        [("y", rz.repeat(2, axis=2).repeat(2, axis=3))],
        {"mode": "nearest"}))
    d2s = r(1, 8, 2, 3)
    cases.append(case(
        "test_depthtospace_dcr", "DepthToSpace", [("x", d2s)],
        [("y", ref_depth_to_space(d2s, 2))], {"blocksize": 2}))
    s2d = r(1, 2, 4, 6)
    cases.append(case(
        "test_spacetodepth", "SpaceToDepth", [("x", s2d)],
        [("y", ref_space_to_depth(s2d, 2))], {"blocksize": 2}))
    sc_d = r(3, 3)
    sc_i = np.array([[1, 0, 2], [0, 2, 1]], np.int64)
    sc_u = r(2, 3)
    cases.append(case(
        "test_scatter_elements_axis0", "ScatterElements",
        [("data", sc_d), ("indices", sc_i), ("updates", sc_u)],
        [("y", ref_scatter_elements(sc_d, sc_i, sc_u, 0))], {"axis": 0}))

    # -- reductions with explicit axes ----------------------------------
    rda = r(3, 2, 4)
    cases.append(case(
        "test_reduce_mean_keepdims0", "ReduceMean", [("x", rda)],
        [("y", rda.mean(axis=1).astype(np.float32))],
        {"axes": [1], "keepdims": 0}))
    cases.append(case(
        "test_reduce_sum_axes02", "ReduceSum", [("x", rda)],
        [("y", rda.sum(axis=(0, 2), keepdims=True).astype(np.float32))],
        {"axes": [0, 2], "keepdims": 1}))
    trp = r(2, 3, 4)
    cases.append(case(
        "test_transpose_perm", "Transpose", [("x", trp)],
        [("y", trp.transpose(1, 0, 2).copy())], {"perm": [1, 0, 2]}))

    # -- dropout (inference = identity) ---------------------------------
    dr = r(3, 4)
    cases.append(case("test_dropout_default_ratio", "Dropout",
                      [("x", dr)], [("y", dr)], {"ratio": 0.3}))

    # -- LRN / ConvTranspose -------------------------------------------
    lx = r(1, 5, 3, 3)
    cases.append(case(
        "test_lrn", "LRN", [("x", lx)],
        [("y", ref_lrn(lx, 3, 0.0002, 0.75, 2.0))],
        {"size": 3, "alpha": 0.0002, "beta": 0.75, "bias": 2.0}))
    ctx_, ctw = r(1, 1, 3, 3), r(1, 2, 3, 3)
    cases.append(case(
        "test_convtranspose", "ConvTranspose",
        [("x", ctx_), ("w", ctw)],
        [("y", ref_conv_transpose2d(ctx_, ctw))],
        {"kernel_shape": [3, 3]}))
    cases.append(case(
        "test_convtranspose_strides", "ConvTranspose",
        [("x", ctx_), ("w", ctw)],
        [("y", ref_conv_transpose2d(ctx_, ctw, (2, 2)))],
        {"kernel_shape": [3, 3], "strides": [2, 2]}))

    # -- RNN family (forward, default activations, zero init states) ----
    T, Bz, I, H = 3, 2, 4, 5
    rx = r(T, Bz, I)
    rw, rr = r(1, H, I) * 0.4, r(1, H, H) * 0.4
    rb = r(1, 2 * H) * 0.4
    ry, ryh = ref_rnn(rx, rw, rr, rb, H)
    cases.append(case(
        "test_simple_rnn_with_bias", "RNN",
        [("x", rx), ("w", rw), ("r", rr), ("b", rb)],
        [("y", ry), ("y_h", ryh)], {"hidden_size": H}))
    gw, gr = r(1, 3 * H, I) * 0.4, r(1, 3 * H, H) * 0.4
    gb = r(1, 6 * H) * 0.4
    gy, gyh = ref_gru(rx, gw, gr, gb, H)
    cases.append(case(
        "test_gru_with_bias", "GRU",
        [("x", rx), ("w", gw), ("r", gr), ("b", gb)],
        [("y", gy), ("y_h", gyh)], {"hidden_size": H}))
    lw, lr = r(1, 4 * H, I) * 0.4, r(1, 4 * H, H) * 0.4
    lb = r(1, 8 * H) * 0.4
    ly, lyh, lyc = ref_lstm(rx, lw, lr, lb, H)
    cases.append(case(
        "test_lstm_with_bias", "LSTM",
        [("x", rx), ("w", lw), ("r", lr), ("b", lb)],
        [("y", ly), ("y_h", lyh), ("y_c", lyc)], {"hidden_size": H}))
    bw, br = r(2, H, I) * 0.4, r(2, H, H) * 0.4
    bb = r(2, 2 * H) * 0.4
    by, byh = ref_rnn_bidir(rx, bw, br, bb, H)
    cases.append(case(
        "test_simple_rnn_bidirectional", "RNN",
        [("x", rx), ("w", bw), ("r", br), ("b", bb)],
        [("y", by), ("y_h", byh)],
        {"hidden_size": H, "direction": "bidirectional"}))

    # -- conv variants: dilation / groups -------------------------------
    dx, dw = r(1, 1, 9, 9), r(1, 1, 3, 3)
    cases.append(case(
        "test_conv_dilations", "Conv", [("x", dx), ("w", dw)],
        [("y", ref_conv2d_general(dx, dw, dilations=(2, 2)))],
        {"kernel_shape": [3, 3], "dilations": [2, 2]}))
    gx, gw = r(1, 4, 5, 5), r(4, 2, 3, 3)
    cases.append(case(
        "test_conv_groups", "Conv", [("x", gx), ("w", gw)],
        [("y", ref_conv2d_general(gx, gw, group=2))],
        {"kernel_shape": [3, 3], "group": 2}))
    # pool with pads
    ppx = r(1, 2, 5, 5)
    padded = np.pad(ppx, ((0, 0), (0, 0), (1, 1), (1, 1)),
                    constant_values=-np.inf)
    mp = np.zeros((1, 2, 5, 5), np.float32)
    for i in range(5):
        for j in range(5):
            mp[:, :, i, j] = padded[:, :, i:i + 3, j:j + 3].max((2, 3))
    cases.append(case(
        "test_maxpool_2d_pads", "MaxPool", [("x", ppx)], [("y", mp)],
        {"kernel_shape": [3, 3], "pads": [1, 1, 1, 1]}))

    # -- more edge-case variants ----------------------------------------
    sm3 = r(2, 3, 4)
    cases.append(case("test_softmax_axis_2", "Softmax", [("x", sm3)],
                      [("y", ref_softmax(sm3, 2))], {"axis": 2}))
    ga2, gb2 = r(3, 5), r(5, 4)
    cases.append(case("test_gemm_alpha_no_c", "Gemm",
                      [("a", ga2), ("b", gb2)],
                      [("y", ref_gemm(ga2, gb2, None, 0.5))],
                      {"alpha": 0.5}))
    cl2 = r(3, 4)
    cases.append(case("test_clip_min_only", "Clip",
                      [("x", cl2), ("min", np.float32(0.0))],
                      [("y", np.clip(cl2, 0.0, None))]))
    eqb = np.round(r(3, 1) * 2).astype(np.float32)
    eqc = np.round(r(1, 4) * 2).astype(np.float32)
    cases.append(case("test_equal_bcast", "Equal",
                      [("a", eqb), ("b", eqc)], [("y", eqb == eqc)]))
    spd = r(6, 4)
    cases.append(case(
        "test_split_equal_parts_default", "Split", [("x", spd)],
        [("y0", spd[:2].copy()), ("y1", spd[2:4].copy()),
         ("y2", spd[4:].copy())], {"axis": 0}))
    sln = r(6, 7)
    cases.append(case(
        "test_slice_negative", "Slice",
        [("x", sln), ("starts", np.array([0, -4], np.int64)),
         ("ends", np.array([6, -1], np.int64)),
         ("axes", np.array([0, 1], np.int64))],
        [("y", sln[0:6, -4:-1].copy())]))
    c2f = (np.round(r(2, 3) * 5)).astype(np.int32)
    cases.append(case("test_cast_int32_to_float", "Cast", [("x", c2f)],
                      [("y", c2f.astype(np.float32))],
                      {"to": int(TensorProto.FLOAT)}))
    rneg = r(2, 3, 4)
    cases.append(case(
        "test_reduce_mean_negative_axes", "ReduceMean", [("x", rneg)],
        [("y", rneg.mean(axis=-1, keepdims=True).astype(np.float32))],
        {"axes": [-1], "keepdims": 1}))
    prs = r(3, 4)
    slope_full = np.abs(r(3, 4)).astype(np.float32)
    cases.append(case(
        "test_prelu_example", "PRelu",
        [("x", prs), ("slope", slope_full)],
        [("y", np.where(prs > 0, prs, slope_full * prs)
          .astype(np.float32))]))

    # -- full Reduce* family (r5: reduce-op axes-form variants) ----------
    rd = r(2, 3, 4)
    rdp = np.abs(r(2, 3, 4)) + 0.2       # positive: L*/LogSum-safe
    reduce_refs = {
        "ReduceMax": lambda x, ax, k: x.max(axis=ax, keepdims=k),
        "ReduceMin": lambda x, ax, k: x.min(axis=ax, keepdims=k),
        "ReduceProd": lambda x, ax, k: x.prod(axis=ax, keepdims=k),
        "ReduceL1": lambda x, ax, k: np.abs(x).sum(axis=ax, keepdims=k),
        "ReduceL2": lambda x, ax, k: np.sqrt(
            (x * x).sum(axis=ax, keepdims=k)),
        "ReduceLogSum": lambda x, ax, k: np.log(
            x.sum(axis=ax, keepdims=k)),
        "ReduceLogSumExp": lambda x, ax, k: np.log(
            np.exp(x).sum(axis=ax, keepdims=k)),
    }
    for op, ref in reduce_refs.items():
        lx = rdp if "Log" in op or op == "ReduceL2" else rd
        low = op.lower()
        for suffix, axes, keep in [("_axes12_keepdims", (1, 2), 1),
                                   ("_axes1_nokeep", (1,), 0),
                                   ("_default_axes", None, 1),
                                   ("_negative_axes", (-1,), 1)]:
            attrs = {"keepdims": keep}
            if axes is not None:
                attrs["axes"] = list(axes)
            cases.append(case(
                f"test_{low}{suffix}", op, [("x", lx)],
                [("y", ref(lx, axes, bool(keep)).astype(np.float32))],
                attrs))
    # opset-13 ReduceSum: axes arrive as an input tensor
    cases.append(case(
        "test_reduce_sum_axes_input_opset13", "ReduceSum",
        [("x", rd), ("axes", np.array([0, 2], np.int64))],
        [("y", rd.sum(axis=(0, 2), keepdims=True).astype(np.float32))],
        {"keepdims": 1}, opset=13))
    # empty axes input = reduce over ALL axes (spec default)...
    cases.append(case(
        "test_reduce_sum_empty_axes_input_opset13", "ReduceSum",
        [("x", rd), ("axes", np.zeros(0, np.int64))],
        [("y", rd.sum(keepdims=True).reshape(1, 1, 1)
          .astype(np.float32))],
        {"keepdims": 1}, opset=13))
    # ...unless noop_with_empty_axes=1 asks for identity
    cases.append(case(
        "test_reduce_sum_empty_axes_noop_opset13", "ReduceSum",
        [("x", rd), ("axes", np.zeros(0, np.int64))],
        [("y", rd.copy())],
        {"keepdims": 1, "noop_with_empty_axes": 1}, opset=13))

    # -- opset-13 attribute-as-input forms -------------------------------
    sq13 = r(1, 3, 1, 4)
    cases.append(case(
        "test_squeeze_axes_input_opset13", "Squeeze",
        [("x", sq13), ("axes", np.array([0, 2], np.int64))],
        [("y", sq13.reshape(3, 4).copy())], opset=13))
    cases.append(case(
        "test_unsqueeze_axes_input_opset13", "Unsqueeze",
        [("x", sq13.reshape(3, 4).copy()),
         ("axes", np.array([0, 3], np.int64))],
        [("y", sq13.reshape(1, 3, 4, 1).copy())], opset=13))
    sp13 = r(6, 4)
    cases.append(case(
        "test_split_sizes_input_opset13", "Split",
        [("x", sp13), ("split", np.array([4, 2], np.int64))],
        [("y0", sp13[:4].copy()), ("y1", sp13[4:].copy())],
        {"axis": 0}, opset=13))
    cases.append(case(
        "test_split_axis1_num_outputs", "Split", [("x", sp13)],
        [("y0", sp13[:, :2].copy()), ("y1", sp13[:, 2:].copy())],
        {"axis": 1}))
    cl13 = r(3, 4)
    cases.append(case(
        "test_clip_min_max_opset13", "Clip",
        [("x", cl13), ("min", np.float32(-0.4)),
         ("max", np.float32(0.5))],
        [("y", np.clip(cl13, -0.4, 0.5))], opset=13))

    # -- Pad modes --------------------------------------------------------
    pdx = r(2, 3)
    cases.append(case(
        "test_pad_reflect", "Pad",
        [("x", pdx), ("pads", np.array([0, 1, 0, 1], np.int64))],
        [("y", np.pad(pdx, ((0, 0), (1, 1)), mode="reflect"))],
        {"mode": "reflect"}))
    cases.append(case(
        "test_pad_edge", "Pad",
        [("x", pdx), ("pads", np.array([1, 0, 1, 0], np.int64))],
        [("y", np.pad(pdx, ((1, 1), (0, 0)), mode="edge"))],
        {"mode": "edge"}))
    cases.append(case(
        "test_pad_constant_value", "Pad",
        [("x", pdx), ("pads", np.array([0, 2, 1, 0], np.int64)),
         ("value", np.float32(1.5))],
        [("y", np.pad(pdx, ((0, 1), (2, 0)), constant_values=1.5))]))

    # -- Resize modes (r5: linear / cubic / non-integer nearest) ---------
    def resize_ref(x, out_hw, mode, coord, nearest="round_prefer_floor",
                   a_cubic=-0.75, scales=None):
        from numpy import floor, ceil, clip

        def coords(o, i, s):
            j = np.arange(o, dtype=np.float64)
            if coord == "align_corners":
                return j * (i - 1) / max(o - 1, 1)
            if coord == "asymmetric":
                return j / s
            return (j + 0.5) / s - 0.5

        def axis_tables(o, i, s):
            xx = coords(o, i, s)
            if mode == "nearest":
                if nearest == "floor":
                    idx = floor(xx)
                else:
                    idx = ceil(xx - 0.5)
                return [(clip(idx, 0, i - 1).astype(int), 1.0)]
            if mode == "linear":
                lo = floor(xx)
                whi = xx - lo
                return [(clip(lo, 0, i - 1).astype(int), 1 - whi),
                        (clip(lo + 1, 0, i - 1).astype(int), whi)]
            base = floor(xx).astype(int)
            frac = xx - base

            def ck(t):
                t = np.abs(t)
                return np.where(
                    t <= 1,
                    (a_cubic + 2) * t**3 - (a_cubic + 3) * t**2 + 1,
                    np.where(t < 2, a_cubic * t**3 - 5 * a_cubic * t**2
                             + 8 * a_cubic * t - 4 * a_cubic, 0.0))
            return [(clip(base + k, 0, i - 1).astype(int), ck(k - frac))
                    for k in (-1, 0, 1, 2)]

        N, C, H, W = x.shape
        oh, ow = out_hw
        sh = scales[2] if scales else oh / H
        sw = scales[3] if scales else ow / W
        out = np.zeros((N, C, oh, W))
        for idx, w in axis_tables(oh, H, sh):
            out += x[:, :, idx, :] * np.asarray(w).reshape(1, 1, -1, 1)
        out2 = np.zeros((N, C, oh, ow))
        for idx, w in axis_tables(ow, W, sw):
            out2 += out[:, :, :, idx] * np.asarray(w).reshape(1, 1, 1, -1)
        return out2.astype(np.float32)

    rz = r(1, 1, 4, 4)
    scl = np.array([1, 1, 2, 2], np.float32)
    roi = np.zeros(0, np.float32)
    cases.append(case(
        "test_resize_upsample_scales_linear", "Resize",
        [("x", rz), ("roi", roi), ("scales", scl)],
        [("y", resize_ref(rz, (8, 8), "linear", "half_pixel",
                          scales=[1, 1, 2, 2]))],
        {"mode": "linear"}))
    cases.append(case(
        "test_resize_upsample_scales_linear_align_corners", "Resize",
        [("x", rz), ("roi", roi), ("scales", scl)],
        [("y", resize_ref(rz, (8, 8), "linear", "align_corners",
                          scales=[1, 1, 2, 2]))],
        {"mode": "linear",
         "coordinate_transformation_mode": "align_corners"}))
    dscl = np.array([1, 1, 0.6, 0.6], np.float32)
    cases.append(case(
        "test_resize_downsample_scales_linear", "Resize",
        [("x", rz), ("roi", roi), ("scales", dscl)],
        [("y", resize_ref(rz, (2, 2), "linear", "half_pixel",
                          scales=[1, 1, 0.6, 0.6]))],
        {"mode": "linear"}))
    cases.append(case(
        "test_resize_upsample_scales_cubic", "Resize",
        [("x", rz), ("roi", roi), ("scales", scl)],
        [("y", resize_ref(rz, (8, 8), "cubic", "half_pixel",
                          scales=[1, 1, 2, 2]))],
        {"mode": "cubic"}))
    cases.append(case(
        "test_resize_downsample_scales_cubic", "Resize",
        [("x", rz), ("roi", roi),
         ("scales", np.array([1, 1, 0.8, 0.8], np.float32))],
        [("y", resize_ref(rz, (3, 3), "cubic", "half_pixel",
                          scales=[1, 1, 0.8, 0.8]))],
        {"mode": "cubic"}))
    cases.append(case(
        "test_resize_upsample_sizes_nearest", "Resize",
        [("x", rz), ("roi", roi), ("scales", np.zeros(0, np.float32)),
         ("sizes", np.array([1, 1, 7, 9], np.int64))],
        [("y", resize_ref(rz, (7, 9), "nearest", "half_pixel",
                          scales=[1, 1, 7 / 4, 9 / 4]))]))
    cases.append(case(
        "test_resize_downsample_sizes_nearest", "Resize",
        [("x", rz), ("roi", roi), ("scales", np.zeros(0, np.float32)),
         ("sizes", np.array([1, 1, 2, 3], np.int64))],
        [("y", resize_ref(rz, (2, 3), "nearest", "half_pixel",
                          scales=[1, 1, 2 / 4, 3 / 4]))]))
    cases.append(case(
        "test_resize_nearest_asymmetric_floor", "Resize",
        [("x", rz), ("roi", roi),
         ("scales", np.array([1, 1, 1.5, 1.5], np.float32))],
        [("y", resize_ref(rz, (6, 6), "nearest", "asymmetric", "floor",
                          scales=[1, 1, 1.5, 1.5]))],
        {"coordinate_transformation_mode": "asymmetric",
         "nearest_mode": "floor"}))
    # scale 1.4 on 2 elements: floor(2*1.4)=2 == in, but the spec still
    # maps coordinates through the scale — NOT a passthrough
    rz2 = r(1, 1, 2, 2)
    cases.append(case(
        "test_resize_nearest_scale_floors_to_same_size", "Resize",
        [("x", rz2), ("roi", roi),
         ("scales", np.array([1, 1, 1.4, 1.4], np.float32))],
        [("y", resize_ref(rz2, (2, 2), "nearest", "asymmetric", "floor",
                          scales=[1, 1, 1.4, 1.4]))],
        {"coordinate_transformation_mode": "asymmetric",
         "nearest_mode": "floor"}))

    # -- ConvTranspose output_padding / output_shape / pads --------------
    ctx2 = r(1, 1, 3, 3)
    ctw2 = r(1, 2, 3, 3)
    base = ref_conv_transpose2d(ctx2, ctw2, strides=(3, 2))
    # output_padding adds zeros at the bottom/right
    opadded = np.zeros((1, 2, base.shape[2] + 1, base.shape[3] + 1),
                       np.float32)
    opadded[:, :, :base.shape[2], :base.shape[3]] = base
    cases.append(case(
        "test_convtranspose_output_padding", "ConvTranspose",
        [("x", ctx2), ("w", ctw2)], [("y", opadded)],
        {"kernel_shape": [3, 3], "strides": [3, 2],
         "output_padding": [1, 1]}))
    # pads crop the full output symmetrically
    full = ref_conv_transpose2d(ctx2, ctw2, strides=(2, 2))
    cases.append(case(
        "test_convtranspose_pads", "ConvTranspose",
        [("x", ctx2), ("w", ctw2)],
        [("y", full[:, :, 1:-1, 1:-1].copy())],
        {"kernel_shape": [3, 3], "strides": [2, 2],
         "pads": [1, 1, 1, 1]}))
    # output_shape: spec derives the pads. Default auto_pad (NOTSET)
    # puts the LARGER pad half at the BEGIN (crop from the start);
    # SAME_UPPER reverses it — both splits pinned.
    want_h, want_w = full.shape[2] - 1, full.shape[3] - 1
    cases.append(case(
        "test_convtranspose_output_shape", "ConvTranspose",
        [("x", ctx2), ("w", ctw2)],
        [("y", full[:, :, 1:, 1:].copy())],
        {"kernel_shape": [3, 3], "strides": [2, 2],
         "output_shape": [want_h, want_w]}))
    cases.append(case(
        "test_convtranspose_output_shape_same_upper", "ConvTranspose",
        [("x", ctx2), ("w", ctw2)],
        [("y", full[:, :, :want_h, :want_w].copy())],
        {"kernel_shape": [3, 3], "strides": [2, 2],
         "output_shape": [want_h, want_w], "auto_pad": "SAME_UPPER"}))

    # -- Softmax: the opset-semantics fork -------------------------------
    # opset<=12 coerces to 2D at `axis` (ref_softmax); opset-13 is
    # single-axis. These fixtures use 3D x with an INNER axis — the one
    # shape class where the two disagree — so the backend's opset
    # dispatch is actually exercised.
    smf = r(2, 3, 4)
    cases.append(case(
        "test_softmax_axis1_3d_coerce_opset11", "Softmax", [("x", smf)],
        [("y", ref_softmax(smf, 1))], {"axis": 1}))
    e13 = np.exp(smf - smf.max(1, keepdims=True))
    cases.append(case(
        "test_softmax_axis1_3d_peraxis_opset13", "Softmax", [("x", smf)],
        [("y", (e13 / e13.sum(1, keepdims=True)).astype(np.float32))],
        {"axis": 1}, opset=13))
    ed = np.exp(smf - smf.max(-1, keepdims=True))
    cases.append(case(
        "test_softmax_default_axis_opset13", "Softmax", [("x", smf)],
        [("y", (ed / ed.sum(-1, keepdims=True)).astype(np.float32))],
        opset=13))

    # -- misc spec variants ----------------------------------------------
    g2 = r(3, 4, 5)
    gi2 = np.array([[0, 2], [1, 3]], np.int64)
    cases.append(case(
        "test_gather_2d_indices", "Gather",
        [("x", g2), ("indices", gi2)],
        [("y", np.take(g2, gi2, axis=1))], {"axis": 1}))
    fl0 = r(2, 3, 4)
    cases.append(case(
        "test_flatten_axis0", "Flatten", [("x", fl0)],
        [("y", fl0.reshape(1, -1).copy())], {"axis": 0}))
    cases.append(case(
        "test_flatten_negative_axis", "Flatten", [("x", fl0)],
        [("y", fl0.reshape(6, 4).copy())], {"axis": -1}))
    cases.append(case(
        "test_concat_3d_negative_axis", "Concat",
        [("a", fl0[:, :, :2].copy()), ("b", fl0[:, :, 2:].copy())],
        [("y", fl0.copy())], {"axis": -1}))
    tp4 = r(2, 3, 4, 5)
    cases.append(case(
        "test_transpose_4d", "Transpose", [("x", tp4)],
        [("y", tp4.transpose(0, 3, 1, 2).copy())],
        {"perm": [0, 3, 1, 2]}))
    gm3 = (r(3, 5), r(5, 4), r(1, 4))
    cases.append(case(
        "test_gemm_beta_broadcast_c", "Gemm",
        [("a", gm3[0]), ("b", gm3[1]), ("c", gm3[2])],
        [("y", ref_gemm(gm3[0], gm3[1], gm3[2], 1.0, 0.7))],
        {"beta": 0.7}))
    gmt = (r(5, 3), r(5, 4), r(3, 4))
    cases.append(case(
        "test_gemm_transA", "Gemm",
        [("a", gmt[0]), ("b", gmt[1]), ("c", gmt[2])],
        [("y", ref_gemm(gmt[0], gmt[1], gmt[2], transA=1))],
        {"transA": 1}))
    # averagepool with SAME-style explicit pads (count_include_pad=1,
    # the mode our backend implements — attribute set explicitly so the
    # fixture is unambiguous about which spec mode is claimed)
    apx = r(1, 2, 4, 4)
    app = np.pad(apx, ((0, 0), (0, 0), (1, 1), (1, 1)))
    apo = np.zeros((1, 2, 4, 4), np.float32)
    for i in range(4):
        for j in range(4):
            apo[:, :, i, j] = app[:, :, i:i + 3, j:j + 3].mean((2, 3))
    cases.append(case(
        "test_averagepool_2d_pads_count_include_pad", "AveragePool",
        [("x", apx)], [("y", apo)],
        {"kernel_shape": [3, 3], "pads": [1, 1, 1, 1],
         "count_include_pad": 1}))
    # the ONNX DEFAULT divides by the valid-element count per window
    apo_ex = np.zeros((1, 2, 4, 4), np.float32)
    for i in range(4):
        for j in range(4):
            win = apx[:, :, max(0, i - 1):i + 2, max(0, j - 1):j + 2]
            apo_ex[:, :, i, j] = win.mean((2, 3))
    cases.append(case(
        "test_averagepool_2d_pads_exclude_pad_default", "AveragePool",
        [("x", apx)], [("y", apo_ex)],
        {"kernel_shape": [3, 3], "pads": [1, 1, 1, 1]}))
    # strided slice over 3 axes
    sl3 = r(4, 5, 6)
    cases.append(case(
        "test_slice_3axes_steps", "Slice",
        [("x", sl3), ("starts", np.array([1, 0, 5], np.int64)),
         ("ends", np.array([4, 5, 0], np.int64)),
         ("axes", np.array([0, 1, 2], np.int64)),
         ("steps", np.array([2, 2, -2], np.int64))],
        [("y", sl3[1:4:2, 0:5:2, 5:0:-2].copy())]))
    # scatter along axis 1
    scx = np.zeros((3, 5), np.float32)
    sci = np.array([[1, 3]], np.int64)
    scu = np.array([[1.5, 2.5]], np.float32)
    sco = scx.copy()
    sco[0, 1], sco[0, 3] = 1.5, 2.5
    cases.append(case(
        "test_scatter_elements_axis1", "ScatterElements",
        [("x", scx), ("indices", sci), ("updates", scu)],
        [("y", sco)], {"axis": 1}))
    # where with broadcasting
    wc = (np.arange(12).reshape(3, 4) % 2 == 0)
    wa, wb = r(3, 4), r(1, 4)
    cases.append(case(
        "test_where_broadcast", "Where",
        [("c", wc), ("a", wa), ("b", wb)],
        [("y", np.where(wc, wa, np.broadcast_to(wb, (3, 4)))
          .astype(np.float32))]))
    # hard dtype edges
    cases.append(case(
        "test_cast_float_to_int64", "Cast",
        [("x", np.array([1.9, -1.9, 0.4], np.float32))],
        [("y", np.array([1.9, -1.9, 0.4], np.float32)
          .astype(np.int64))],
        {"to": int(TensorProto.INT64)}))
    # global average pool on non-square input
    gap = r(2, 3, 5, 7)
    cases.append(case(
        "test_globalaveragepool_nonsquare", "GlobalAveragePool",
        [("x", gap)], [("y", gap.mean((2, 3), keepdims=True)
                        .astype(np.float32))]))
    # elementwise binaries with full broadcasting
    bca, bcb = r(2, 1, 4), r(3, 1)
    for op, fn in [("Add", np.add), ("Sub", np.subtract),
                   ("Mul", np.multiply)]:
        cases.append(case(
            f"test_{op.lower()}_bcast_3d", op,
            [("a", bca), ("b", bcb)],
            [("y", fn(bca, bcb).astype(np.float32))]))
    bcd = np.abs(r(3, 1)) + 0.4
    cases.append(case(
        "test_div_bcast_3d", "Div", [("a", bca), ("b", bcd)],
        [("y", (bca / bcd).astype(np.float32))]))
    # LRN non-default attributes
    lr2 = r(2, 6, 3, 3)
    half = 5 // 2
    sq = np.zeros_like(lr2)
    for c in range(6):
        lo, hi = max(0, c - half), min(6, c + half + 1)
        sq[:, c] = (lr2[:, lo:hi] ** 2).sum(axis=1)
    cases.append(case(
        "test_lrn_custom_attrs", "LRN", [("x", lr2)],
        [("y", (lr2 / (2.0 + (1e-3 / 5) * sq) ** 0.5)
          .astype(np.float32))],
        {"size": 5, "alpha": 1e-3, "beta": 0.5, "bias": 2.0}))

    return cases


def main():
    if os.path.isdir(OUT_DIR):
        shutil.rmtree(OUT_DIR)
    cases = build_cases()
    for name, model, ins, outs in cases:
        d = os.path.join(OUT_DIR, name)
        ds = os.path.join(d, "test_data_set_0")
        os.makedirs(ds)
        with open(os.path.join(d, "model.onnx"), "wb") as f:
            f.write(model.SerializeToString())
        for i, arr in enumerate(ins):
            t = numpy_helper.from_array(np.asarray(arr), f"input_{i}")
            with open(os.path.join(ds, f"input_{i}.pb"), "wb") as f:
                f.write(t.SerializeToString())
        for i, arr in enumerate(outs):
            t = numpy_helper.from_array(np.asarray(arr), f"output_{i}")
            with open(os.path.join(ds, f"output_{i}.pb"), "wb") as f:
                f.write(t.SerializeToString())
    print(f"wrote {len(cases)} node cases to {OUT_DIR}")


if __name__ == "__main__":
    main()
