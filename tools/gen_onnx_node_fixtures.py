"""Generate the hermetic ONNX node-conformance fixtures.

The official ONNX backend node suite (what the reference runs via
test/python/test_onnx_backend.py) ships inside the `onnx` wheel, which
this environment does not have. This script freezes an equivalent
subset — single-node ModelProtos plus input/output TensorProtos in the
official on-disk layout (model.onnx + test_data_set_0/{input,output}_N
.pb) — built from the ONNX operator-spec semantics implemented in plain
numpy, serialized with the vendored wire-compatible protos
(singa_tpu/onnx_proto). The committed fixtures make
tests/test_onnx_nodes.py a conformance suite that runs with zero
optional dependencies; tests/test_onnx_backend.py still runs the real
upstream suite whenever the onnx wheel is importable.

Regenerate (deterministic, seed-pinned):
    python tools/gen_onnx_node_fixtures.py
"""

import os
import shutil
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from singa_tpu.onnx_compat import TensorProto, helper, numpy_helper  # noqa

OUT_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "data", "onnx_nodes")

F = TensorProto.FLOAT


def _vi(name, arr):
    dt = helper.np_dtype_to_tensor_dtype(np.asarray(arr).dtype)
    return helper.make_tensor_value_info(name, dt, list(np.shape(arr)))


def case(name, op_type, inputs, outputs, attrs=None, opset=11):
    """inputs/outputs: list of (name, ndarray). Returns (name, model,
    input arrays, output arrays)."""
    node = helper.make_node(op_type, [n for n, _ in inputs],
                            [n for n, _ in outputs], **(attrs or {}))
    graph = helper.make_graph(
        [node], name,
        [_vi(n, a) for n, a in inputs],
        [_vi(n, a) for n, a in outputs])
    model = helper.make_model(
        graph, opset_imports=[helper.make_operatorsetid("", opset)])
    return (name, model, [a for _, a in inputs], [a for _, a in outputs])


# ---------------------------------------------------------------------------
# numpy reference implementations of the ONNX operator spec
# ---------------------------------------------------------------------------

def ref_softmax(x, axis):
    # opset-11 semantics: coerce to 2D at `axis`, softmax the rows
    shape = x.shape
    flat = x.reshape(int(np.prod(shape[:axis])) if axis > 0 else 1, -1)
    e = np.exp(flat - flat.max(axis=1, keepdims=True))
    return (e / e.sum(axis=1, keepdims=True)).reshape(shape)


def ref_conv2d(x, w, strides=(1, 1), pads=(0, 0, 0, 0)):
    N, C, H, W = x.shape
    M, _, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]),
                    (pads[1], pads[3])))
    oh = (xp.shape[2] - kh) // strides[0] + 1
    ow = (xp.shape[3] - kw) // strides[1] + 1
    out = np.zeros((N, M, oh, ow), np.float32)
    for n in range(N):
        for m in range(M):
            for i in range(oh):
                for j in range(ow):
                    patch = xp[n, :, i * strides[0]:i * strides[0] + kh,
                               j * strides[1]:j * strides[1] + kw]
                    out[n, m, i, j] = np.sum(patch * w[m])
    return out


def ref_pool2d(x, k, strides, is_max):
    N, C, H, W = x.shape
    oh = (H - k[0]) // strides[0] + 1
    ow = (W - k[1]) // strides[1] + 1
    out = np.zeros((N, C, oh, ow), np.float32)
    red = np.max if is_max else np.mean
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = red(
                x[:, :, i * strides[0]:i * strides[0] + k[0],
                  j * strides[1]:j * strides[1] + k[1]], axis=(2, 3))
    return out


def ref_gemm(a, b, c=None, alpha=1.0, beta=1.0, transA=0, transB=0):
    aa = a.T if transA else a
    bb = b.T if transB else b
    y = alpha * (aa @ bb)
    if c is not None:
        y = y + beta * c
    return y.astype(np.float32)


def ref_batchnorm(x, s, bias, mean, var, eps=1e-5):
    shp = (1, -1, 1, 1)
    return ((x - mean.reshape(shp)) / np.sqrt(var.reshape(shp) + eps)
            * s.reshape(shp) + bias.reshape(shp)).astype(np.float32)


def build_cases():
    rng = np.random.RandomState(0)

    def r(*shape):
        return rng.randn(*shape).astype(np.float32)

    cases = []

    # -- simple activations / unary ------------------------------------
    x = r(3, 4, 5)
    xpos = np.abs(r(3, 4, 5)) + 0.1
    for name, op, inp, out in [
        ("test_relu", "Relu", x, np.maximum(x, 0)),
        ("test_sigmoid", "Sigmoid", x, 1 / (1 + np.exp(-x))),
        ("test_tanh", "Tanh", x, np.tanh(x)),
        ("test_softplus", "Softplus", x, np.log1p(np.exp(x))),
        ("test_neg", "Neg", x, -x),
        ("test_abs", "Abs", x, np.abs(x)),
        ("test_exp", "Exp", x, np.exp(x)),
        ("test_log", "Log", xpos, np.log(xpos)),
        ("test_sqrt", "Sqrt", xpos, np.sqrt(xpos)),
        ("test_ceil", "Ceil", x, np.ceil(x)),
        ("test_floor", "Floor", x, np.floor(x)),
        ("test_reciprocal", "Reciprocal", xpos, 1.0 / xpos),
        ("test_sign", "Sign", x, np.sign(x)),
        ("test_erf", "Erf", x, np.vectorize(__import__("math").erf)(x)
         .astype(np.float32)),
    ]:
        cases.append(case(name, op, [("x", inp)],
                          [("y", out.astype(np.float32))]))

    cases.append(case("test_elu", "Elu", [("x", x)],
                      [("y", np.where(x > 0, x, 2.0 * (np.exp(x) - 1))
                        .astype(np.float32))], {"alpha": 2.0}))
    cases.append(case("test_leakyrelu", "LeakyRelu", [("x", x)],
                      [("y", np.where(x > 0, x, 0.1 * x)
                        .astype(np.float32))], {"alpha": 0.1}))
    a_selu, g_selu = 1.6732632, 1.0507009
    cases.append(case(
        "test_selu_default", "Selu", [("x", x)],
        [("y", (g_selu * np.where(x > 0, x, a_selu * (np.exp(x) - 1)))
          .astype(np.float32))]))

    # -- binary elementwise (with broadcasting rows) --------------------
    a, b = r(3, 4, 5), r(3, 4, 5)
    bc = r(5)                                   # numpy-style broadcast
    bpos = np.abs(r(3, 4, 5)) + 0.5
    for name, op, (i1, i2), out in [
        ("test_add", "Add", (a, b), a + b),
        ("test_add_bcast", "Add", (a, bc), a + bc),
        ("test_sub", "Sub", (a, b), a - b),
        ("test_mul", "Mul", (a, b), a * b),
        ("test_div", "Div", (a, bpos), a / bpos),
        ("test_pow", "Pow", (np.abs(a) + 0.1, b), (np.abs(a) + 0.1) ** b),
    ]:
        cases.append(case(name, op, [("a", i1), ("b", i2)],
                          [("y", out.astype(np.float32))]))

    # -- matmul / gemm --------------------------------------------------
    m2a, m2b = r(4, 6), r(6, 3)
    cases.append(case("test_matmul_2d", "MatMul",
                      [("a", m2a), ("b", m2b)], [("y", m2a @ m2b)]))
    m3a, m3b = r(2, 4, 6), r(2, 6, 3)
    cases.append(case("test_matmul_3d", "MatMul",
                      [("a", m3a), ("b", m3b)],
                      [("y", (m3a @ m3b).astype(np.float32))]))
    ga, gb, gc = r(3, 5), r(5, 4), r(3, 4)
    gat, gbt = r(5, 3), r(4, 5)
    cases.append(case("test_gemm_all_attributes", "Gemm",
                      [("a", gat), ("b", gbt), ("c", gc)],
                      [("y", ref_gemm(gat, gbt, gc, 0.25, 0.35, 1, 1))],
                      {"alpha": 0.25, "beta": 0.35,
                       "transA": 1, "transB": 1}))
    cases.append(case("test_gemm_default", "Gemm",
                      [("a", ga), ("b", gb), ("c", gc)],
                      [("y", ref_gemm(ga, gb, gc))]))

    # -- softmax --------------------------------------------------------
    sm = r(3, 7)
    cases.append(case("test_softmax_axis_1", "Softmax", [("x", sm)],
                      [("y", ref_softmax(sm, 1))], {"axis": 1}))
    cases.append(case("test_softmax_default_axis", "Softmax",
                      [("x", sm)], [("y", ref_softmax(sm, 1))]))

    # -- shape ops ------------------------------------------------------
    c1, c2 = r(2, 3), r(2, 3)
    cases.append(case("test_concat_2d_axis_0", "Concat",
                      [("a", c1), ("b", c2)],
                      [("y", np.concatenate([c1, c2], 0))], {"axis": 0}))
    cases.append(case("test_concat_2d_axis_1", "Concat",
                      [("a", c1), ("b", c2)],
                      [("y", np.concatenate([c1, c2], 1))], {"axis": 1}))
    fl = r(2, 3, 4)
    cases.append(case("test_flatten_axis1", "Flatten", [("x", fl)],
                      [("y", fl.reshape(2, 12))], {"axis": 1}))
    tr = r(2, 3, 4)
    cases.append(case("test_transpose_default", "Transpose", [("x", tr)],
                      [("y", tr.transpose(2, 1, 0).copy())]))
    rs = r(2, 3, 4)
    tgt = np.array([4, 2, 3], np.int64)
    cases.append(case("test_reshape_reordered_all_dims", "Reshape",
                      [("x", rs), ("shape", tgt)],
                      [("y", rs.reshape(4, 2, 3))]))
    sq = r(1, 3, 4, 1)
    cases.append(case("test_squeeze", "Squeeze", [("x", sq)],
                      [("y", sq.reshape(3, 4))], {"axes": [0, 3]}))
    us = r(3, 4)
    cases.append(case("test_unsqueeze_axis_0", "Unsqueeze", [("x", us)],
                      [("y", us.reshape(1, 3, 4))], {"axes": [0]}))
    gt = r(5, 4)
    gi0 = np.array([0, 1, 3], np.int64)
    cases.append(case("test_gather_0", "Gather",
                      [("x", gt), ("i", gi0)],
                      [("y", np.take(gt, gi0, 0))], {"axis": 0}))
    cases.append(case("test_gather_1", "Gather",
                      [("x", gt), ("i", np.array([0, 2], np.int64))],
                      [("y", np.take(gt, [0, 2], 1))], {"axis": 1}))

    # -- reductions / clip ---------------------------------------------
    rd = r(3, 2, 2)
    cases.append(case(
        "test_reduce_mean_default_axes_keepdims_example", "ReduceMean",
        [("x", rd)], [("y", rd.mean(keepdims=True).astype(np.float32)
                       .reshape(1, 1, 1))]))
    cases.append(case(
        "test_reduce_sum_default_axes_keepdims_example", "ReduceSum",
        [("x", rd)], [("y", rd.sum(keepdims=True).astype(np.float32)
                       .reshape(1, 1, 1))]))
    cl = np.array([-2.0, -1.0, 0.0, 1.0, 2.0], np.float32)
    cases.append(case("test_clip_example", "Clip",
                      [("x", cl), ("min", np.float32(-1.0)),
                       ("max", np.float32(1.0))],
                      [("y", np.clip(cl, -1, 1))]))

    # -- conv / pool / bn ----------------------------------------------
    cx, cw = r(1, 1, 7, 5), r(1, 1, 3, 3)
    cases.append(case(
        "test_conv_with_strides_no_padding", "Conv",
        [("x", cx), ("w", cw)],
        [("y", ref_conv2d(cx, cw, (2, 2)))],
        {"kernel_shape": [3, 3], "strides": [2, 2], "pads": [0, 0, 0, 0]}))
    cases.append(case(
        "test_conv_with_strides_padding", "Conv",
        [("x", cx), ("w", cw)],
        [("y", ref_conv2d(cx, cw, (2, 2), (1, 1, 1, 1)))],
        {"kernel_shape": [3, 3], "strides": [2, 2], "pads": [1, 1, 1, 1]}))
    px = r(1, 3, 8, 8)
    cases.append(case(
        "test_maxpool_2d_default", "MaxPool", [("x", px)],
        [("y", ref_pool2d(px, (2, 2), (1, 1), True))],
        {"kernel_shape": [2, 2]}))
    cases.append(case(
        "test_averagepool_2d_strides", "AveragePool", [("x", px)],
        [("y", ref_pool2d(px, (3, 3), (2, 2), False))],
        {"kernel_shape": [3, 3], "strides": [2, 2]}))
    cases.append(case(
        "test_globalaveragepool", "GlobalAveragePool", [("x", px)],
        [("y", px.mean(axis=(2, 3), keepdims=True).astype(np.float32))]))
    bx = r(2, 3, 4, 4)
    bs, bb = np.abs(r(3)) + 0.5, r(3)
    bm, bv = r(3), np.abs(r(3)) + 0.5
    cases.append(case(
        "test_batchnorm_epsilon", "BatchNormalization",
        [("x", bx), ("s", bs), ("bias", bb), ("mean", bm), ("var", bv)],
        [("y", ref_batchnorm(bx, bs, bb, bm, bv, 1e-2))],
        {"epsilon": 1e-2}))
    cases.append(case(
        "test_batchnorm_example", "BatchNormalization",
        [("x", bx), ("s", bs), ("bias", bb), ("mean", bm), ("var", bv)],
        [("y", ref_batchnorm(bx, bs, bb, bm, bv))]))

    return cases


def main():
    if os.path.isdir(OUT_DIR):
        shutil.rmtree(OUT_DIR)
    cases = build_cases()
    for name, model, ins, outs in cases:
        d = os.path.join(OUT_DIR, name)
        ds = os.path.join(d, "test_data_set_0")
        os.makedirs(ds)
        with open(os.path.join(d, "model.onnx"), "wb") as f:
            f.write(model.SerializeToString())
        for i, arr in enumerate(ins):
            t = numpy_helper.from_array(np.asarray(arr), f"input_{i}")
            with open(os.path.join(ds, f"input_{i}.pb"), "wb") as f:
                f.write(t.SerializeToString())
        for i, arr in enumerate(outs):
            t = numpy_helper.from_array(np.asarray(arr), f"output_{i}")
            with open(os.path.join(ds, f"output_{i}.pb"), "wb") as f:
                f.write(t.SerializeToString())
    print(f"wrote {len(cases)} node cases to {OUT_DIR}")


if __name__ == "__main__":
    main()
