#!/usr/bin/env python
"""Flight-recorder → Chrome-trace/Perfetto exporter CLI.

Renders a recorder JSONL file — a blackbox dump
(``telemetry/blackbox-<rank>.jsonl``) or a live span sink
(``spans.jsonl`` from ``--telemetry``) — into a ``.trace.json`` that
opens in https://ui.perfetto.dev or ``chrome://tracing``::

    python tools/trace_export.py run/telemetry/blackbox-0.jsonl
    python tools/trace_export.py run/telemetry/spans.jsonl -o s.trace.json
    python tools/trace_export.py --selftest            # CI gate

Each rank renders as a process row; serving requests (records carrying
the gateway-minted request id) each get their own named thread lane, so
one request reads queue → prefill → decode ticks → delivery on one row.
A serving gateway exports the same document live at ``GET /trace.json``.

``--selftest`` (run in CI by ``tests/test_examples.py`` like the other
tool selftests) synthesizes a train-and-serve recorder ring — training
spans on two ranks, a retrace event, one full per-request serving
timeline, an in-flight span, a closing metrics snapshot with a fusion
table — exports it through the real file path, schema-validates the
JSON round-trip, and asserts the per-request lane grouping.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _synthetic_records():
    """A deterministic train-and-serve session's worth of records (no
    clocks: fixed timestamps so the selftest is reproducible)."""
    t = 1000.0
    recs = []
    # training: two ranks, nested spans, a retrace
    for rank in (0, 1):
        recs.append({"kind": "span", "name": "restore", "rank": rank,
                     "ts_start": t, "ts": t + 0.5, "dur_s": 0.5})
        for step in range(3):
            s0 = t + 1 + step * 0.1
            recs.append({"kind": "span", "name": "step", "rank": rank,
                         "step": step, "ts_start": s0, "ts": s0 + 0.09,
                         "dur_s": 0.09, "parent": "run"})
    recs.append({"kind": "event", "name": "retrace", "rank": 0,
                 "ts": t + 1.25, "program": "train_step",
                 "compile_s": 0.8,
                 "changed": [{"arg": "arg0", "old": [[16, 8], "float32"],
                              "new": [[12, 8], "float32"]}]})
    # serving: one request's full timeline + a second interleaved one
    for rid, off in (("req-a1", 2.0), ("req-b2", 2.05)):
        recs.append({"kind": "event", "name": "request.queued",
                     "request": rid, "ts": t + off, "queue_depth": 1})
        recs.append({"kind": "event", "name": "request.prefill",
                     "request": rid, "ts": t + off + 0.01, "slot": 0,
                     "prompt_len": 4})
        for k in range(3):
            recs.append({"kind": "event", "name": "request.decode_tick",
                         "request": rid, "ts": t + off + 0.02 + k * 0.01,
                         "slot": 0, "pos": 5 + k})
        recs.append({"kind": "event", "name": "request.delivered",
                     "request": rid, "ts": t + off + 0.06,
                     "status": "completed", "tokens": 4})
    # a span still open at dump time (the satellite's span_open shape)
    recs.append({"kind": "span_open", "name": "checkpoint.save",
                 "rank": 0, "ts_start": t + 3.0, "ts": t + 3.4,
                 "age_s": 0.4, "step": 2})
    # the snapshot a blackbox closes with, fusion table included
    recs.append({"kind": "metrics", "ts": t + 3.5, "snapshot": {
        "schema": "singa-tpu-metrics/1", "ts": t + 3.5, "metrics": [
            {"name": "profile_fusion_seconds", "kind": "gauge",
             "help": "", "labels": ["fusion"], "series": [
                 {"labels": {"fusion": "fusion.1|convolution.3"},
                  "value": 0.004},
                 {"labels": {"fusion": "dot_general.5"},
                  "value": 0.001}]}]}})
    return recs


def selftest():
    from singa_tpu.observability import trace_export as te

    recs = _synthetic_records()
    with tempfile.TemporaryDirectory() as td:
        # through the real file path: JSONL in, .trace.json out
        src = os.path.join(td, "blackbox-0.jsonl")
        with open(src, "w") as f:
            f.write(json.dumps({"kind": "dump", "ts": 999.0,
                                "reason": "selftest"}) + "\n")
            for r in recs:
                f.write(json.dumps(r) + "\n")
            f.write("{torn line\n")      # must be skipped, not fatal
        out = os.path.join(td, "out.trace.json")
        doc = te.export_records(te.records_from_jsonl(src), out)
        with open(out) as f:
            doc2 = json.load(f)          # JSON round-trip
    te.validate_chrome_trace(doc2)
    evs = doc2["traceEvents"]
    if evs != doc["traceEvents"]:
        raise AssertionError("trace changed across the JSON round-trip")

    names = {e["name"] for e in evs}
    for needle in ("step", "restore", "retrace", "request.queued",
                   "request.decode_tick", "request.delivered",
                   "checkpoint.save", "metrics_snapshot",
                   "blackbox_dump"):
        if needle not in names:
            raise AssertionError(f"exported trace lost {needle!r}")

    # two ranks → two named process rows
    procs = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "process_name"}
    if not {"rank 0", "rank 1"} <= procs:
        raise AssertionError(f"rank process rows missing: {procs}")

    # one request = one lane: every req-a1 record shares a tid, and
    # that lane is named after the request id
    a1 = [e for e in evs if e.get("args", {}).get("request") == "req-a1"]
    if len(a1) != 6 or len({e["tid"] for e in a1}) != 1:
        raise AssertionError(f"req-a1 lane broken: {a1}")
    lanes = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e["name"] == "thread_name"}
    if "request req-a1" not in lanes or "request req-b2" not in lanes:
        raise AssertionError(f"request lanes not named: {lanes}")

    # the open span exports as a complete event flagged open
    (open_ev,) = [e for e in evs if e["name"] == "checkpoint.save"]
    if not open_ev["args"].get("open") or open_ev["dur"] <= 0:
        raise AssertionError(f"span_open mis-rendered: {open_ev}")

    # the fusion table survived into the snapshot event's args
    (snap,) = [e for e in evs if e["name"] == "metrics_snapshot"]
    fus = snap["args"].get("profile_fusion_seconds")
    if not fus or fus[0][0] != "fusion.1|convolution.3":
        raise AssertionError(f"fusion table lost: {snap['args']}")

    # validator catches real breakage
    bad = {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 0,
                            "ts": -5, "dur": 1}]}
    try:
        te.validate_chrome_trace(bad)
    except ValueError:
        pass
    else:
        raise AssertionError("validator accepted a negative timestamp")
    print("selftest ok: synthetic ring exported, chrome-trace schema "
          "round-trip, rank rows + per-request lanes, open spans, "
          "fusion table")


def main():
    ap = argparse.ArgumentParser(
        description="render a flight-recorder JSONL into a Perfetto-"
                    "openable Chrome trace")
    ap.add_argument("recorder", nargs="?",
                    help="recorder JSONL (blackbox-<rank>.jsonl or a "
                         "live spans.jsonl sink)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <input>.trace.json)")
    ap.add_argument("--selftest", action="store_true",
                    help="export a synthetic ring and validate the "
                         "schema round-trip (the tier-1 CI gate)")
    args = ap.parse_args()

    if args.selftest:
        selftest()
        return
    if not args.recorder:
        ap.error("need a recorder JSONL file (or --selftest)")
    from singa_tpu.observability import trace_export as te

    records = te.records_from_jsonl(args.recorder)
    if not records:
        print(f"no records in {args.recorder}", file=sys.stderr)
        raise SystemExit(2)
    out = args.out or (args.recorder + ".trace.json")
    doc = te.export_records(records, out)
    spans_n = sum(1 for e in doc["traceEvents"]
                  if e.get("ph") == "X")
    print(f"wrote {out}: {len(doc['traceEvents'])} events "
          f"({spans_n} spans) — open it at https://ui.perfetto.dev")


if __name__ == "__main__":
    main()
