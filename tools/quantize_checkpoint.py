#!/usr/bin/env python
"""Convert an fp32 checkpoint to int8 weight-only form, offline.

Reads one step of a :class:`~singa_tpu.checkpoint.CheckpointManager`
directory, verifies it against its content-digest sidecar (a corrupt
source must never be laundered into a fresh-looking quantized copy —
**nonzero exit on digest mismatch**), quantizes every eligible
``model/`` tensor to an int8 payload plus a ``quant-scale/`` fp32
sidecar (``singa_tpu.quant.quantize_state_arrays``), and writes the
result as a NEW digest-verified checkpoint directory — ~4x smaller, so
restore and scrub time drop proportionally.

Optimizer aux (``optimizer/``, ``aux/``) is STRIPPED by default: a
quantized checkpoint is an inference artifact, and fp32 momentum would
dwarf the int8 payloads. ``--keep-optimizer`` keeps it (verbatim).

``CheckpointManager.restore_latest`` / ``AsyncModelCheckpointer
.restore`` on the output dequantize payload × scale back into the
model's floating masters automatically (``checkpoint._apply_restored``),
and ``tools/scrub_checkpoints.py`` verifies it like any other
checkpoint.

Exit codes: 0 converted (or selftest passed), 1 usage/conversion
failure, 2 source failed digest verification.

Usage::

    python tools/quantize_checkpoint.py SRC_DIR DST_DIR [--step N]
        [--keep-optimizer] [--json]
    python tools/quantize_checkpoint.py --selftest
"""

import argparse
import json
import os
import sys

# conversion is host-side IO + rounding; never grab an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

EXIT_DIGEST_MISMATCH = 2


def _dir_bytes(path):
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def convert(src, dst, step=None, keep_optimizer=False):
    """Convert ``src``'s ``step`` (default: latest) into ``dst``.
    Returns a summary dict. Raises ``IntegrityError`` on a source
    digest mismatch, ``ValueError`` when there is nothing to convert."""
    import jax
    import numpy as np
    from singa_tpu.checkpoint import CheckpointManager
    from singa_tpu.integrity import digest_tree
    from singa_tpu.quant import core as qcore

    src_mgr = CheckpointManager(src, sweep=False)   # read-only open
    try:
        steps = sorted(src_mgr.all_steps())
        if not steps:
            raise ValueError(f"no checkpoint steps in {src!r}")
        step = int(step) if step is not None else steps[-1]
        if step not in steps:
            raise ValueError(f"step {step} not in {src!r} "
                             f"(has {steps})")
        meta = src_mgr._mgr.item_metadata(step)
        tree = dict(getattr(meta, "tree", None) or meta)
        template = {k: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype)
                    for k, m in tree.items()}
        restored = src_mgr._mgr.restore(
            step, args=src_mgr._ocp.args.StandardRestore(template))
        # the load-bearing gate: IntegrityError (exit 2) on mismatch —
        # corrupt fp32 bytes must fail HERE, not round silently into a
        # fresh-looking int8 copy that passes every later scrub
        src_mgr._verify_restored(step, restored)
    finally:
        src_mgr.close()

    arrays = dict(restored)
    if not keep_optimizer:
        arrays = {k: v for k, v in arrays.items()
                  if not k.startswith(("optimizer/", "aux/"))}
    q = qcore.quantize_state_arrays(arrays, prefix="model/")
    n_q = sum(1 for k in q if k.startswith(qcore.SCALE_PREFIX))
    if n_q == 0:
        raise ValueError(
            f"nothing to quantize in step {step} of {src!r} (already "
            "quantized, or no eligible >=2-D float model/ tensors)")
    q = {k: np.asarray(v) for k, v in q.items()}

    dst_mgr = CheckpointManager(dst)
    try:
        dst_mgr._mgr.save(step,
                          args=dst_mgr._ocp.args.StandardSave(q),
                          force=True)
        dst_mgr._mgr.wait_until_finished()
        # synchronous digest sidecar (no training step to overlap)
        dst_mgr._write_digests(step, digest_tree(q))
    finally:
        dst_mgr.close()

    src_b = _dir_bytes(os.path.join(src, str(step)))
    dst_b = _dir_bytes(os.path.join(dst, str(step)))
    return {
        "step": step,
        "quantized_tensors": n_q,
        "entries": len(q),
        "kept_optimizer": bool(keep_optimizer),
        "src_bytes": src_b,
        "dst_bytes": dst_b,
        "ratio": round(src_b / dst_b, 2) if dst_b else None,
    }


def selftest():
    """End-to-end smoke (run in tier-1 via tests/test_examples.py):
    save an fp32 model, convert, restore into a FRESH fp32 model,
    verify dequantized parity + >=3x shrink + a clean scrub, and pin
    the digest-mismatch exit path."""
    import tempfile

    import numpy as np
    from singa_tpu import device, tensor
    from singa_tpu.checkpoint import CheckpointManager
    from singa_tpu.integrity import IntegrityError
    from singa_tpu.models.mlp import MLP

    dev = device.get_default_device()

    def mlp():
        # big enough that tensor bytes dominate orbax's per-step
        # metadata overhead — the >=3x assertion measures the payload
        # shrink, not bookkeeping noise
        m = MLP(data_size=128, perceptron_size=256, num_classes=16)
        x = tensor.Tensor(data=np.random.RandomState(0)
                          .randn(4, 128).astype(np.float32),
                          device=dev, requires_grad=False)
        m.forward(x)
        return m

    with tempfile.TemporaryDirectory() as td:
        src, dst = os.path.join(td, "fp32"), os.path.join(td, "int8")
        m = mlp()
        mgr = CheckpointManager(src)
        assert mgr.save(0, m, force=True)
        mgr.wait()
        mgr.close()

        rep = convert(src, dst)
        assert rep["quantized_tensors"] >= 2, rep
        assert rep["ratio"] and rep["ratio"] >= 3.0, \
            f"expected >=3x smaller, got {rep}"

        # restore into a FRESH fp32 model: payload x scale lands in the
        # floating masters within the int8 grid's error bound
        m2 = mlp()
        out = CheckpointManager(dst, sweep=False)
        assert out.restore_latest(m2) == 1
        out.close()
        for name, t in m.get_states().items():
            a = np.asarray(t.data)
            b = np.asarray(m2.get_states()[name].data)
            assert b.dtype == a.dtype, (name, b.dtype)
            tol = np.abs(a).max() / 127.0 + 1e-6
            assert np.abs(a - b).max() <= tol, \
                (name, float(np.abs(a - b).max()), float(tol))

        # the quantized output scrubs clean like any other checkpoint
        out = CheckpointManager(dst, sweep=False)
        assert set(out.scrub().values()) == {"ok"}, out.scrub()
        out.close()

        # corrupt source bytes -> IntegrityError (the exit-2 path)
        import glob
        # the LARGEST file is tensor payload (metadata is small JSON):
        # flipping a payload byte must surface as a digest mismatch,
        # not an unreadable-metadata parse error
        victim = max(
            (f for f in glob.glob(os.path.join(src, "0", "**", "*"),
                                  recursive=True) if os.path.isfile(f)),
            key=os.path.getsize)
        with open(victim, "r+b") as f:
            f.seek(256)
            byte = f.read(1)
            f.seek(256)
            f.write(bytes([byte[0] ^ 0xFF]))
        try:
            convert(src, os.path.join(td, "int8-2"))
        except IntegrityError:
            pass
        else:
            raise AssertionError(
                "corrupt source converted without a digest failure")
    print("quantize_checkpoint selftest: OK")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("src", nargs="?", help="source CheckpointManager "
                    "directory (fp32)")
    ap.add_argument("dst", nargs="?", help="output directory for the "
                    "quantized checkpoint")
    ap.add_argument("--step", type=int, default=None,
                    help="step to convert (default: latest)")
    ap.add_argument("--keep-optimizer", action="store_true",
                    help="keep optimizer/aux entries (verbatim fp32) "
                    "instead of stripping them")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON line")
    ap.add_argument("--selftest", action="store_true",
                    help="run the end-to-end smoke test and exit")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.src or not args.dst:
        ap.error("SRC and DST are required (or --selftest)")

    from singa_tpu.integrity import IntegrityError
    try:
        rep = convert(args.src, args.dst, step=args.step,
                      keep_optimizer=args.keep_optimizer)
    except IntegrityError as e:
        print(f"DIGEST MISMATCH: {e}", file=sys.stderr)
        return EXIT_DIGEST_MISMATCH
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(rep))
    else:
        print(f"step {rep['step']}: {rep['quantized_tensors']} tensors "
              f"quantized, {rep['src_bytes']} -> {rep['dst_bytes']} "
              f"bytes ({rep['ratio']}x smaller)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
