#!/usr/bin/env python
"""Render the banked BENCH_r*.json trajectory as a table (or JSON).

Every benchmark round banks one record (``tools/tpu_watch.py`` /
``bench.py``), but until now the trajectory was invisible — reading it
meant eyeballing raw JSON blobs. This CLI folds the records into one
per-round table: per-leg throughput (img/s, tok/s), MFU, peak HBM,
compile cost, serving SLOs, and the step-timeline decomposition
(compute/exposed-comm/idle fractions) the MFU push steers by — each
with its delta vs the previous record, and loud ``REGRESSION`` flags
when a throughput metric drops more than the threshold::

    python tools/bench_report.py                  # repo-root records
    python tools/bench_report.py --dir runs/ --json
    python tools/bench_report.py --threshold 0.10
    python tools/bench_report.py --selftest       # CI gate

``--selftest`` (wired into tests/test_examples.py like the other tool
selftests) synthesizes a three-round trajectory with a known bf16
regression and asserts the extraction, the deltas, and the flag.
"""

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# (column label, extractor) — every metric the trajectory tracks. An
# extractor returns None when the leg didn't run that round; deltas
# skip None-to-None and None-to-value transitions.
METRICS = [
    ("img_s", lambda p: p.get("value") or p.get("throughput")),
    ("mfu", lambda p: p.get("mfu")),
    ("bf16_img_s", lambda p: p.get("bf16_throughput")),
    ("bf16_mfu", lambda p: p.get("bf16_mfu")),
    ("lm_tok_s", lambda p: p.get("lm_tokens_per_sec")),
    ("lm_mfu", lambda p: p.get("lm_mfu")),
    ("lm_bf16_tok_s", lambda p: p.get("lm_bf16_tokens_per_sec")),
    ("lm_bf16_mfu", lambda p: p.get("lm_bf16_mfu")),
    ("serve_tok_s", lambda p: (p.get("serving") or {}).get(
        "decode_tok_s")),
    ("serve_p99_ms", lambda p: _scale((p.get("serving") or {}).get(
        "p99_token_s"), 1e3)),
    ("quant_img_s", lambda p: (p.get("quant") or {}).get(
        "resnet_img_s")),
    ("sweep_best_tok_s", lambda p: _sweep_best(p.get("serving_sweep"))),
    ("serve_sh_tok_s", lambda p: (p.get("serving_sharded") or {}).get(
        "decode_tok_s")),
    ("serve_sh_kv_dev_mib", lambda p: _scale(
        (p.get("serving_sharded") or {}).get("kv_per_device_bytes"),
        1 / 2**20)),
    ("serve_sh_hbm_gib", lambda p: _scale(
        (p.get("serving_sharded") or {}).get("hbm_peak_bytes"),
        1 / 2**30)),
    ("hbm_peak_gib", lambda p: _scale(p.get("hbm_peak_bytes"),
                                      1 / 2**30)),
    ("bf16_hbm_gib", lambda p: _scale(p.get("bf16_hbm_peak_bytes"),
                                      1 / 2**30)),
    ("compile_s", lambda p: (p.get("compile") or {}).get("seconds")),
]

# higher-is-better metrics get the regression gate; latency/memory
# metrics are reported with deltas but a rise there is not flagged
# (the p99 of a 2-request CPU smoke is far too noisy to gate on)
GATED = {"img_s", "bf16_img_s", "lm_tok_s", "lm_bf16_tok_s",
         "serve_tok_s", "quant_img_s", "sweep_best_tok_s",
         "serve_sh_tok_s"}

# SLO latency targets (ms) the serving_sweep winner table is computed
# against: for each, the highest-throughput config whose p99 per-tick
# latency fits under it ("None" = unconstrained best throughput)
SWEEP_SLO_TARGETS_MS = (1.0, 5.0, 25.0, None)

# per-leg MFU columns the --mfu-floor gate guards (the MFU-push PRs'
# cron tripwire: a win banked by one round must not silently erode)
MFU_GATED = {"mfu", "bf16_mfu", "lm_mfu", "lm_bf16_mfu"}

# exposed-comm rises smaller than this (seconds) are timing noise, not
# an overlap regression — CPU/TPU profiler jitter sits well under it
EXPOSED_COMM_EPS_S = 1e-4

# per-leg timeline columns (bucket fractions + exposed comm) — the
# "what to fix" companion of each MFU number
TIMELINE_LEGS = [("timeline", "fp32"), ("bf16_timeline", "bf16"),
                 ("lm_timeline", "lm"),
                 ("lm_bf16_timeline", "lm_bf16"),
                 ("serving.timeline", "serving")]


def _scale(v, k):
    return v * k if isinstance(v, (int, float)) else None


def _sweep_configs(sweep):
    return [c for c in (sweep or {}).get("configs") or []
            if isinstance(c, dict)
            and isinstance(c.get("decode_tok_s"), (int, float))]


def _sweep_best(sweep):
    """Best decode tok/s across the round's serving_sweep configs —
    the one scalar the trajectory/regression gate tracks (per-config
    curves render separately)."""
    configs = _sweep_configs(sweep)
    return max((c["decode_tok_s"] for c in configs), default=None)


def _cfg_name(c):
    return (f"{c.get('kv_layout', '?')} s{c.get('slots', '?')}"
            f" pf{c.get('prefill_len', '?')}"
            f" k{c.get('speculative_k', 0)}")


def sweep_winners(sweep):
    """Winner per SLO target: for each p99 tick-latency budget, the
    highest-throughput config that fits under it. The load-sweep's
    whole point — "which engine config should this fleet run at THIS
    latency target" answered from banked curves, not guesses."""
    configs = _sweep_configs(sweep)
    winners = []
    for t in SWEEP_SLO_TARGETS_MS:
        elig = [c for c in configs
                if t is None
                or (isinstance(c.get("p99_token_s"), (int, float))
                    and c["p99_token_s"] * 1e3 <= t)]
        if not elig:
            winners.append({"slo_ms": t, "config": None})
            continue
        best = max(elig, key=lambda c: c["decode_tok_s"])
        winners.append({"slo_ms": t, "config": _cfg_name(best),
                        "decode_tok_s": best["decode_tok_s"],
                        "p99_ms": _scale(best.get("p99_token_s"), 1e3)})
    return winners


def _round_no(path):
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_records(directory):
    """[(round_no, parsed-record dict)] sorted by round, skipping
    files without a parsed benchmark payload."""
    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_r*.json")),
                       key=_round_no):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_report: skipping {path} ({e})",
                  file=sys.stderr)
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if isinstance(doc, dict) and parsed is None and \
                ("value" in doc or "throughput" in doc):
            parsed = doc          # a bare bench.py record, unwrapped
        if not isinstance(parsed, dict):
            print(f"bench_report: {path} has no parsed record",
                  file=sys.stderr)
            continue
        out.append((doc.get("n", _round_no(path)), parsed))
    return out


def _timeline_doc(parsed, key):
    node = parsed
    for part in key.split("."):
        node = (node or {}).get(part) if isinstance(node, dict) else None
    return node if isinstance(node, dict) else None


def build_report(records, threshold=0.05, mfu_floor=None):
    """The JSON-able report doc: one row per round with extracted
    metrics, deltas vs the previous record (fractional), per-leg
    timeline decompositions, and the regression list.

    ``mfu_floor`` arms the MFU-push cron gate: a leg whose MFU falls
    BELOW the floor after the previous same-platform record held it
    (or keeps dropping past ``threshold`` while already under it) is a
    regression, and so is a per-leg ``exposed_collective_s`` that
    rises more than ``threshold`` (plus a noise epsilon) vs the
    previous same-platform record — the two numbers this PR's overlap
    and fused-kernel wins are banked in, guarded round over round."""
    rows = []
    # deltas compare a round against the previous record on the SAME
    # platform: a tpu round after a cpu-fallback round is not a
    # 100000% speedup, and the cpu round after it is not a regression
    prev_by_platform = {}
    for n, parsed in records:
        vals = {name: fn(parsed) for name, fn in METRICS}
        row = {"round": n,
               "measured_at": parsed.get("measured_at"),
               "git": parsed.get("git"),
               "platform": parsed.get("platform"),
               "device_kind": parsed.get("device_kind"),
               "metrics": vals, "deltas": {}, "regressions": []}
        prev = prev_by_platform.get(row["platform"])
        timelines = {}
        for key, leg in TIMELINE_LEGS:
            tl = _timeline_doc(parsed, key)
            if tl:
                timelines[leg] = {
                    "fractions": tl.get("fractions"),
                    "exposed_collective_s":
                        tl.get("exposed_collective_s")}
        if timelines:
            row["timeline"] = timelines
        sweep = parsed.get("serving_sweep")
        sweep_cfgs = _sweep_configs(sweep)
        if sweep_cfgs:
            row["serving_sweep"] = {
                "configs": [
                    {"name": _cfg_name(c),
                     "decode_tok_s": c["decode_tok_s"],
                     "p99_ms": _scale(c.get("p99_token_s"), 1e3),
                     "prefix_cache_hits": c.get("prefix_cache_hits"),
                     "speculative_accepted_ratio":
                         c.get("speculative_accepted_ratio")}
                    for c in sweep_cfgs],
                "winners": sweep_winners(sweep)}
            # per-config same-platform deltas, matched by config name
            # (a grid change between rounds simply yields no delta)
            prev_cfgs = {c["name"]: c for c in
                         ((prev or {}).get("serving_sweep") or {})
                         .get("configs", [])}
            for c in row["serving_sweep"]["configs"]:
                pc = prev_cfgs.get(c["name"])
                if pc and isinstance(pc.get("decode_tok_s"),
                                     (int, float)) \
                        and pc["decode_tok_s"]:
                    c["delta"] = (c["decode_tok_s"]
                                  - pc["decode_tok_s"]) \
                        / pc["decode_tok_s"]
        sh = parsed.get("serving_sharded")
        if isinstance(sh, dict) and \
                isinstance(sh.get("decode_tok_s"), (int, float)):
            mesh = sh.get("mesh") or {}
            blk = {"decode_tok_s": sh["decode_tok_s"],
                   "mesh": f"{mesh.get('batch', '?')}x"
                           f"{mesh.get('model', '?')}",
                   "kv_per_device_mib": _scale(
                       sh.get("kv_per_device_bytes"), 1 / 2**20),
                   "hbm_peak_gib": _scale(sh.get("hbm_peak_bytes"),
                                          1 / 2**30)}
            # vs the SAME round's unsharded serving record: what
            # sharding costs (CPU: unoverlapped collectives) or buys
            # (per-chip memory) this round — never across platforms
            unsh = (parsed.get("serving") or {}).get("decode_tok_s")
            if isinstance(unsh, (int, float)) and unsh:
                blk["vs_unsharded"] = sh["decode_tok_s"] / unsh
            row["serving_sharded"] = blk
        if prev is not None:
            for name, v in vals.items():
                pv = prev["metrics"].get(name)
                if isinstance(v, (int, float)) and \
                        isinstance(pv, (int, float)) and pv:
                    d = (v - pv) / pv
                    row["deltas"][name] = d
                    if name in GATED and d < -threshold:
                        row["regressions"].append(
                            {"metric": name, "delta": d,
                             "prev": pv, "now": v,
                             "vs_round": prev["round"]})
                    if mfu_floor is not None and name in MFU_GATED \
                            and v < mfu_floor \
                            and (pv >= mfu_floor or d < -threshold):
                        # lost the floor the previous round held, or
                        # still sliding while already under it
                        row["regressions"].append(
                            {"metric": name, "kind": "mfu_floor",
                             "floor": mfu_floor, "delta": d,
                             "prev": pv, "now": v,
                             "vs_round": prev["round"]})
            if mfu_floor is not None:
                for leg, tl in timelines.items():
                    cur = tl.get("exposed_collective_s")
                    ptl = (prev.get("timeline") or {}).get(leg) or {}
                    pv = ptl.get("exposed_collective_s")
                    if not (isinstance(cur, (int, float))
                            and isinstance(pv, (int, float))):
                        continue
                    if cur > pv * (1 + threshold) + EXPOSED_COMM_EPS_S:
                        row["regressions"].append(
                            {"metric": f"{leg}_exposed_comm",
                             "kind": "exposed_comm",
                             "delta": (cur - pv) / pv if pv else None,
                             "prev": pv, "now": cur,
                             "vs_round": prev["round"]})
        rows.append(row)
        prev_by_platform[row["platform"]] = row
    return {"schema": "singa-tpu-bench-report/1", "rounds": rows,
            "threshold": threshold, "mfu_floor": mfu_floor,
            "regressions": [r for row in rows
                            for r in row["regressions"]]}


def _fmt(v):
    if v is None:
        return "-"
    if isinstance(v, float):
        if abs(v) >= 1000:
            return f"{v:,.0f}"
        return f"{v:.4g}"
    return str(v)


def _fmt_delta(d):
    return "" if d is None else f" ({d:+.1%})"


def render_table(report):
    """Plain-text trajectory table: one block per round (records carry
    different leg sets per round, so a fixed-width grid would be
    mostly holes)."""
    lines = []
    for row in report["rounds"]:
        head = f"round r{row['round']:02d}"
        if row.get("measured_at"):
            head += f"  {row['measured_at']}"
        if row.get("git"):
            head += f"  git {row['git']}"
        if row.get("device_kind"):
            head += f"  [{row['device_kind']}]"
        lines.append(head)
        for name, _fn in METRICS:
            v = row["metrics"].get(name)
            if v is None:
                continue
            flag = next((r for r in row["regressions"]
                         if r["metric"] == name), None)
            lines.append(
                f"  {name:<14} {_fmt(v):>12}"
                f"{_fmt_delta(row['deltas'].get(name))}"
                + ("   << REGRESSION" if flag else ""))
        for leg, tl in (row.get("timeline") or {}).items():
            fr = tl.get("fractions") or {}
            parts = " ".join(f"{b}={fr[b]:.0%}" for b in
                             ("compute", "collective", "memcpy",
                              "host", "idle") if b in fr)
            exp = tl.get("exposed_collective_s")
            lines.append(f"  {leg + '_timeline':<14} {parts}"
                         + (f"  exposed_comm={exp * 1e3:.3g}ms"
                            if exp is not None else ""))
        sw = row.get("serving_sweep")
        if sw:
            for c in sw["configs"]:
                extras = []
                if c.get("p99_ms") is not None:
                    extras.append(f"p99={c['p99_ms']:.3g}ms")
                if c.get("prefix_cache_hits"):
                    extras.append(f"prefix_hits={c['prefix_cache_hits']}")
                if isinstance(c.get("speculative_accepted_ratio"),
                              (int, float)):
                    extras.append(
                        f"spec_accept="
                        f"{c['speculative_accepted_ratio']:.0%}")
                lines.append(
                    f"  sweep {c['name']:<22}"
                    f" {_fmt(c['decode_tok_s']):>10} tok/s"
                    f"{_fmt_delta(c.get('delta'))}  "
                    + " ".join(extras))
            for w in sw["winners"]:
                target = "unconstrained" if w["slo_ms"] is None \
                    else f"p99<={w['slo_ms']:g}ms"
                if w.get("config"):
                    lines.append(
                        f"  sweep winner [{target}] {w['config']}"
                        f" ({_fmt(w['decode_tok_s'])} tok/s)")
                else:
                    lines.append(
                        f"  sweep winner [{target}] none fits")
        sh = row.get("serving_sharded")
        if sh:
            parts = [f"mesh {sh['mesh']}",
                     f"{_fmt(sh['decode_tok_s'])} tok/s"
                     f"{_fmt_delta(row['deltas'].get('serve_sh_tok_s'))}"]
            if sh.get("vs_unsharded") is not None:
                parts.append(f"{sh['vs_unsharded']:.2f}x unsharded")
            if sh.get("kv_per_device_mib") is not None:
                parts.append(
                    f"kv/dev={sh['kv_per_device_mib']:.3g}MiB")
            if sh.get("hbm_peak_gib") is not None:
                parts.append(f"hbm/dev={sh['hbm_peak_gib']:.3g}GiB")
            lines.append("  sharded " + "  ".join(parts))
        lines.append("")
    regs = report["regressions"]
    lines.append(f"{len(report['rounds'])} round(s), "
                 f"{len(regs)} regression(s) at "
                 f"threshold {report['threshold']:.0%}"
                 + (f", mfu floor {report['mfu_floor']}"
                    if report.get("mfu_floor") is not None else ""))
    for r in regs:
        kind = f" [{r['kind']}]" if r.get("kind") else ""
        delta = f" ({r['delta']:+.1%})" if isinstance(
            r.get("delta"), (int, float)) else ""
        lines.append(f"  REGRESSION{kind} {r['metric']}: "
                     f"{_fmt(r['prev'])} -> {_fmt(r['now'])}{delta}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------

def selftest():
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        recs = [
            # r1: fp32 only, no timeline yet
            {"n": 1, "parsed": {
                "value": 1000.0, "mfu": 0.12, "platform": "tpu",
                "device_kind": "TPU v5 lite", "git": "aaa111",
                "measured_at": "2026-01-01T00:00:00"}},
            # r2: bf16 + lm appear, timeline + serving_sweep banked
            {"n": 2, "parsed": {
                "value": 1100.0, "mfu": 0.14, "platform": "tpu",
                "bf16_throughput": 2400.0, "bf16_mfu": 0.30,
                "lm_tokens_per_sec": 140000.0,
                "hbm_peak_bytes": 6 * 2**30, "git": "bbb222",
                "timeline": {"fractions": {
                    "compute": 0.5, "collective": 0.1, "memcpy": 0.05,
                    "host": 0.15, "idle": 0.2},
                    "exposed_collective_s": 4e-5, "window_s": 4e-4},
                "serving": {"decode_tok_s": 500.0,
                            "p99_token_s": 0.002},
                "serving_sharded": {
                    "decode_tok_s": 400.0,
                    "mesh": {"batch": 2, "model": 2, "devices": 4},
                    "kv_per_device_bytes": 8 * 2**20,
                    "hbm_peak_bytes": 2 * 2**30},
                "serving_sweep": {"configs": [
                    {"kv_layout": "ring", "slots": 4,
                     "prefill_len": 16, "speculative_k": 0,
                     "decode_tok_s": 500.0, "p99_token_s": 0.0008},
                    {"kv_layout": "paged", "slots": 4,
                     "prefill_len": 16, "speculative_k": 4,
                     "decode_tok_s": 900.0, "p99_token_s": 0.004,
                     "prefix_cache_hits": 5,
                     "speculative_accepted_ratio": 0.4}]}}},
            # r3: bf16 REGRESSES 20%, lm improves; a cpu-fallback round
            # in between must NOT become anyone's comparison baseline
            {"n": 3, "parsed": {
                "value": 9.0, "platform": "cpu", "git": "ccc333"}},
            {"n": 4, "parsed": {
                "value": 1105.0, "platform": "tpu",
                "bf16_throughput": 1920.0, "bf16_mfu": 0.30,
                "lm_tokens_per_sec": 150000.0, "git": "ddd444",
                "timeline": {"fractions": {"compute": 0.55},
                             "exposed_collective_s": 4e-5,
                             "window_s": 4e-4},
                "serving_sharded": {
                    "decode_tok_s": 440.0,
                    "mesh": {"batch": 2, "model": 2, "devices": 4},
                    "kv_per_device_bytes": 8 * 2**20},
                "serving_sweep": {"configs": [
                    {"kv_layout": "paged", "slots": 4,
                     "prefill_len": 16, "speculative_k": 4,
                     "decode_tok_s": 990.0, "p99_token_s": 0.004}]}}},
        ]
        for r in recs:
            with open(os.path.join(td, f"BENCH_r{r['n']:02d}.json"),
                      "w") as f:
                json.dump(r, f)
        # a torn file must be skipped, not fatal
        with open(os.path.join(td, "BENCH_r99.json"), "w") as f:
            f.write("{torn")

        records = load_records(td)
        assert [n for n, _p in records] == [1, 2, 3, 4], records
        report = build_report(records, threshold=0.05)
        rows = {r["round"]: r for r in report["rounds"]}

        assert rows[1]["metrics"]["img_s"] == 1000.0
        assert rows[1]["deltas"] == {}           # nothing to diff yet
        # r2 deltas against r1; legs appearing for the first time have
        # no delta
        assert abs(rows[2]["deltas"]["img_s"] - 0.10) < 1e-9
        assert "bf16_img_s" not in rows[2]["deltas"]
        assert rows[2]["timeline"]["fp32"]["fractions"]["idle"] == 0.2
        assert "serving" not in rows[2]["timeline"]  # no timeline there
        assert rows[2]["metrics"]["serve_tok_s"] == 500.0
        assert rows[2]["metrics"]["serve_p99_ms"] == 2.0
        assert rows[2]["metrics"]["hbm_peak_gib"] == 6.0
        # serving_sweep: best-config scalar extracted, per-config
        # curves + winner-per-SLO table built
        assert rows[2]["metrics"]["sweep_best_tok_s"] == 900.0
        # serving_sharded: decode tok/s + per-device bytes extracted,
        # the vs-unsharded ratio computed from the SAME round's
        # serving record, and the r4 repeat carries a same-platform
        # delta across the cpu round
        shb = rows[2]["serving_sharded"]
        assert shb["mesh"] == "2x2" and shb["decode_tok_s"] == 400.0
        assert abs(shb["vs_unsharded"] - 0.8) < 1e-9, shb
        assert shb["kv_per_device_mib"] == 8.0
        assert rows[2]["metrics"]["serve_sh_kv_dev_mib"] == 8.0
        assert abs(rows[4]["deltas"]["serve_sh_tok_s"] - 0.10) < 1e-9
        assert "vs_unsharded" not in rows[4]["serving_sharded"]
        sw = rows[2]["serving_sweep"]
        assert [c["name"] for c in sw["configs"]] == \
            ["ring s4 pf16 k0", "paged s4 pf16 k4"]
        by_slo = {w["slo_ms"]: w for w in sw["winners"]}
        # under a 1ms p99 budget only the ring config fits; the paged
        # speculative config wins once the budget allows it
        assert by_slo[1.0]["config"] == "ring s4 pf16 k0", by_slo
        assert by_slo[5.0]["config"] == "paged s4 pf16 k4"
        assert by_slo[None]["config"] == "paged s4 pf16 k4"
        # r4's repeated paged config carries a same-platform delta
        # (matched by name, across the cpu round); the vanished ring
        # config simply has none
        sw4 = rows[4]["serving_sweep"]["configs"]
        assert abs(sw4[0]["delta"] - 0.10) < 1e-9, sw4
        # the cpu-fallback round has no tpu baseline: no delta, no flag
        assert rows[3]["deltas"] == {} and not rows[3]["regressions"]
        # r4 compares against r2 (the previous TPU round, ACROSS the
        # cpu round): the 20% bf16 drop is flagged; the small fp32
        # wiggle and the lm IMPROVEMENT are not
        (reg,) = report["regressions"]
        assert reg["metric"] == "bf16_img_s" and \
            abs(reg["delta"] + 0.20) < 1e-9 and \
            reg["vs_round"] == 2, reg
        assert rows[4]["deltas"]["lm_tok_s"] > 0
        assert not [r for r in rows[4]["regressions"]
                    if r["metric"] != "bf16_img_s"]

        text = render_table(report)
        assert "REGRESSION" in text and "bf16_img_s" in text
        assert "compute=50%" in text and "exposed_comm" in text
        assert "sweep paged s4 pf16 k4" in text and \
            "sweep winner [p99<=1ms] ring s4 pf16 k0" in text and \
            "spec_accept=40%" in text, text
        assert "sharded mesh 2x2" in text and \
            "0.80x unsharded" in text and "kv/dev=8MiB" in text, text
        json.dumps(report)                       # JSON-able end to end

        # --mfu-floor gate: r5 drops bf16 MFU below the floor r2 held
        # AND exposes more collective time than r2's timeline banked —
        # both flag (and only with the floor armed)
        with open(os.path.join(td, "BENCH_r05.json"), "w") as f:
            json.dump({"n": 5, "parsed": {
                "value": 1100.0, "platform": "tpu", "git": "eee555",
                "bf16_throughput": 2400.0, "bf16_mfu": 0.22,
                "timeline": {"fractions": {"compute": 0.6},
                             "exposed_collective_s": 9e-4,
                             "window_s": 4e-4}}}, f)
        records5 = load_records(td)
        plain = build_report(records5, threshold=0.05)
        assert not [r for r in plain["regressions"]
                    if r.get("kind")], plain["regressions"]
        armed = build_report(records5, threshold=0.05, mfu_floor=0.30)
        kinds = {r["metric"]: r for r in armed["regressions"]
                 if r.get("kind")}
        floor = kinds["bf16_mfu"]
        assert floor["kind"] == "mfu_floor" and floor["prev"] == 0.30 \
            and floor["now"] == 0.22, floor
        ec = kinds["fp32_exposed_comm"]
        assert ec["kind"] == "exposed_comm" and ec["prev"] == 4e-5 \
            and ec["now"] == 9e-4, ec
        # an MFU already under the floor but HOLDING (tiny wiggle) does
        # not flag: r6 repeats r5's bf16_mfu
        with open(os.path.join(td, "BENCH_r06.json"), "w") as f:
            json.dump({"n": 6, "parsed": {
                "value": 1100.0, "platform": "tpu",
                "bf16_throughput": 2400.0, "bf16_mfu": 0.219}}, f)
        armed6 = build_report(load_records(td), threshold=0.05,
                              mfu_floor=0.30)
        assert not [r for r in armed6["regressions"]
                    if r.get("kind") and r.get("vs_round") == 5], \
            armed6["regressions"]
        text5 = render_table(armed)
        assert "mfu_floor" in text5 and "exposed_comm" in text5
    print("selftest: OK — 4-round trajectory extracted, same-platform "
          "deltas and timeline columns rendered, the 20% bf16 drop "
          "flagged across the cpu round, torn record skipped, the "
          "serving_sweep curves + winner-per-SLO table built (with "
          "per-config deltas), the serving_sharded leg rendered with "
          "its vs-unsharded ratio + per-device bytes, and the "
          "--mfu-floor gate flags the lost floor + exposed-comm rise "
          "only when armed")


def main():
    ap = argparse.ArgumentParser(
        description="render the banked BENCH_r*.json benchmark "
                    "trajectory (per-leg throughput/MFU/HBM/timeline "
                    "with regression deltas)")
    ap.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON instead of the "
                         "table")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="fractional drop that flags a regression "
                         "(default 0.05)")
    ap.add_argument("--mfu-floor", type=float, default=None,
                    metavar="X",
                    help="arm the MFU gate: exit 3 when any leg's MFU "
                         "falls below X after the previous same-"
                         "platform record held it (or keeps dropping "
                         "past --threshold under it), or when a leg's "
                         "timeline exposed_collective_s rises more "
                         "than --threshold vs the previous record — "
                         "the cron guard for the overlap/fused-kernel "
                         "wins")
    ap.add_argument("--selftest", action="store_true",
                    help="run the built-in synthetic-trajectory check "
                         "(the tier-1 CI gate)")
    args = ap.parse_args()
    if args.selftest:
        selftest()
        return
    records = load_records(args.dir)
    if not records:
        print(f"no BENCH_r*.json records under {args.dir}",
              file=sys.stderr)
        raise SystemExit(2)
    report = build_report(records, threshold=args.threshold,
                          mfu_floor=args.mfu_floor)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render_table(report))
    # regressions exit nonzero so a cron wrapper can alarm on it
    if report["regressions"]:
        raise SystemExit(3)


if __name__ == "__main__":
    main()
