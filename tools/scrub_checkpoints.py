#!/usr/bin/env python
"""Scrub at-rest checkpoints: re-verify every step against its content
digests, report, and optionally demote the corrupt ones.

Detects the layout automatically:

- a :class:`~singa_tpu.checkpoint.DistributedCheckpointManager` root
  (``commits/`` + ``rank<N>/`` shard dirs): every rank's shards are
  scrubbed, and a committed step none of whose shards verify is flagged
  — that checkpoint is unrecoverable and the fleet should know *before*
  it tries to restore from it;
- a plain :class:`~singa_tpu.checkpoint.CheckpointManager` directory:
  its steps are scrubbed directly.

``--delete`` demotes corrupt/unreadable steps (shard dir + digest
sidecar removed) so the rotation window only ever counts verified
steps — without demotion a corrupt newest step would let
``max_to_keep`` rotate away the last restorable one. Commit markers
are NEVER deleted here: a marker whose local shard is corrupt may
still be restorable from a peer's shard.

Data-state sidecars (``data_state/<step>.json`` — the checkpointable
data pipeline's resume offsets, digest-guarded like everything else)
are verified alongside the tensor digests: a step whose resume offset
fails its digest is flagged ``corrupt`` exactly like flipped tensor
bytes, because restoring it would silently break the exactly-once
sample-stream contract.

Exit code: 0 when every verified step is clean, 1 when anything is
corrupt/unreadable (cron-able: page on nonzero).

Usage::

    python tools/scrub_checkpoints.py CKPT_DIR [--delete] [--json]
"""

import argparse
import json
import os
import sys

# scrubbing is host-side IO + CRC work; never grab an accelerator for it
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _scrub_dir(path, delete):
    from singa_tpu.checkpoint import CheckpointManager
    # read-only open: never sweep another writer's in-flight step
    mgr = CheckpointManager(path, sweep=False)
    try:
        return mgr.scrub(delete=delete)
    finally:
        mgr.close()


def scrub_root(root, delete=False):
    """Scrub ``root`` (plain or distributed layout). Returns
    ``{relative_dir: {step_or_aot_artifact: status}}``. A distributed
    root's shared ``aot/`` sidecar (exported compiled executables —
    the per-rank scrub only sees per-rank sidecars) is verified here,
    reported under the ``"aot"`` key."""
    root = os.path.abspath(root)
    rank_dirs = sorted(
        d for d in (os.listdir(root) if os.path.isdir(root) else [])
        if d.startswith("rank") and d[4:].isdigit()
        and os.path.isdir(os.path.join(root, d)))
    if os.path.isdir(os.path.join(root, "commits")) and rank_dirs:
        report = {d: _scrub_dir(os.path.join(root, d), delete)
                  for d in rank_dirs}
        aot_dir = os.path.join(root, "aot")
        if os.path.isdir(aot_dir):
            from singa_tpu.aot.export import AotStore
            report["aot"] = {f"aot/{p}": s for p, s in
                             AotStore(aot_dir).scrub(
                                 delete=delete).items()}
        return report
    return {".": _scrub_dir(root, delete)}


def main():
    ap = argparse.ArgumentParser(
        description="re-verify at-rest checkpoints against their "
                    "content digests")
    ap.add_argument("directory", help="checkpoint root (plain "
                    "CheckpointManager dir or a distributed root with "
                    "commits/ + rank<N>/)")
    ap.add_argument("--delete", action="store_true",
                    help="demote corrupt/unreadable steps (keeps the "
                         "rotation window verified-only)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    args = ap.parse_args()

    report = scrub_root(args.directory, delete=args.delete)

    bad = 0
    # a distributed step is LOST only when no rank's shard verifies;
    # aot artifacts (string keys) are counted as bad shards but are
    # not steps — a corrupt artifact quarantines and recompiles fresh
    steps: dict = {}
    for d, res in report.items():
        for step, status in res.items():
            if not isinstance(step, str):
                steps.setdefault(step, []).append(status)
            if status in ("corrupt", "unreadable"):
                bad += 1
    lost = sorted(s for s, sts in steps.items()
                  if sts and all(x in ("corrupt", "unreadable")
                                 for x in sts))

    if args.json:
        print(json.dumps({"report": report, "corrupt_shards": bad,
                          "lost_steps": lost, "deleted": args.delete}))
    else:
        for d, res in sorted(report.items()):
            # step keys are ints, aot artifact keys are strings — one
            # report, sorted stably across both
            for step, status in sorted(res.items(), key=lambda kv:
                                       (isinstance(kv[0], str),
                                        kv[0] if isinstance(kv[0], int)
                                        else str(kv[0]))):
                print(f"[scrub] {d}/{step}: {status}")
        if lost:
            print(f"[scrub] LOST step(s) {lost}: no rank's shard "
                  "verifies — restore will fall back past them")
        print(f"[scrub] {bad} corrupt/unreadable shard(s)"
              + (" (demoted)" if args.delete and bad else ""))
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
